"""Scheduling queue: activeQ / backoffQ / unschedulablePods.

Reference semantics: pkg/scheduler/internal/queue/scheduling_queue.go
  PriorityQueue (:140-181): three tiers —
    activeQ            heap ordered by the QueueSort plugin (priority, FIFO ties)
    podBackoffQ        heap ordered by backoff expiry
    unschedulablePods  parking lot, re-activated by cluster events that a
                       pod's failed plugins registered for (EventsToRegister)
  flushBackoffQCompleted (:440)  every 1 s
  flushUnschedulablePodsLeftover (:471)  every 30 s, pods parked > 5 min
  MoveAllToActiveOrBackoffQueue + moveRequestCycle race guard: an event that
    arrives while a pod is mid-cycle must not strand it in unschedulable.
  PodNominator: bookkeeping of preemption-nominated pods per node.

Pop() additionally supports pop_batch(max_n) — the TPU batch path drains up
to K pods at once; this is the only queue-surface addition vs the reference.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Callable, Iterable

from ..api import meta
from ..api.meta import Obj
from .types import ClusterEvent, PodInfo, QueuedPodInfo

DEFAULT_POD_INITIAL_BACKOFF = 1.0     # scheduler.go:188
DEFAULT_POD_MAX_BACKOFF = 10.0        # scheduler.go:193
DEFAULT_UNSCHEDULABLE_TIMEOUT = 300.0  # flushUnschedulablePodsLeftover

# system-cluster-critical / system-node-critical priority floor; pods at or
# above this are in the "system" band and exempt from admission shedding
SYSTEM_PRIORITY_BAND = 2_000_000_000


def default_sort_key(qpi: QueuedPodInfo) -> tuple:
    """PrioritySort plugin order: higher .spec.priority first, then FIFO."""
    return (-qpi.pod_info.priority, qpi.timestamp)


class _Heap:
    """Heap with lazy deletion keyed by pod key (internal/heap/heap.go)."""

    def __init__(self, key_fn: Callable[[QueuedPodInfo], tuple]):
        self._key_fn = key_fn
        self._heap: list[tuple[tuple, int, QueuedPodInfo]] = []
        self._entries: dict[str, QueuedPodInfo] = {}
        self._counter = itertools.count()

    def push(self, qpi: QueuedPodInfo) -> None:
        self._entries[qpi.key] = qpi
        heapq.heappush(self._heap, (self._key_fn(qpi), next(self._counter), qpi))

    def pop(self) -> QueuedPodInfo | None:
        while self._heap:
            _, _, qpi = heapq.heappop(self._heap)
            if self._entries.get(qpi.key) is qpi:
                del self._entries[qpi.key]
                return qpi
        return None

    def peek(self) -> QueuedPodInfo | None:
        while self._heap:
            _, _, qpi = self._heap[0]
            if self._entries.get(qpi.key) is qpi:
                return qpi
            heapq.heappop(self._heap)
        return None

    def remove(self, key: str) -> QueuedPodInfo | None:
        return self._entries.pop(key, None)

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def items(self) -> list[QueuedPodInfo]:
        return list(self._entries.values())


class _BucketQueue:
    """Priority-bucketed FIFO active queue — the fast replacement for
    _Heap when the queue-sort is priority-FIFO shaped (PrioritySort /
    default sort): one deque per distinct priority, entries dict for lazy
    deletion.  push/pop are O(1) dict+deque ops instead of O(log n)
    heap churn with a key_fn call per push (~8µs/pod saved at bench
    scale; almost all pods share one priority).

    Ordering note: within a priority the order is INSERTION order.  For
    fresh adds that equals the timestamp order the heap used; a pod
    re-activated from backoff/unschedulable joins at the tail instead of
    jumping ahead of fresher pods by its older park timestamp — the
    reference's activeQ refreshes Timestamp on requeue, which makes
    insertion order the faithful equivalent."""

    def __init__(self) -> None:
        self._buckets: dict[int, deque] = {}
        self._prios: list[int] = []  # heap of active -priority values
        self._entries: dict[str, QueuedPodInfo] = {}

    def push(self, qpi: QueuedPodInfo) -> None:
        self._entries[qpi.key] = qpi
        p = -qpi.pod_info.priority
        d = self._buckets.get(p)
        if d is None:
            d = self._buckets[p] = deque()
            heapq.heappush(self._prios, p)
        d.append(qpi)

    def pop(self) -> QueuedPodInfo | None:
        entries = self._entries
        while self._prios:
            p = self._prios[0]
            d = self._buckets[p]
            while d:
                qpi = d.popleft()
                if entries.get(qpi.key) is qpi:
                    del entries[qpi.key]
                    return qpi
            heapq.heappop(self._prios)
            del self._buckets[p]
        return None

    def peek(self) -> QueuedPodInfo | None:
        entries = self._entries
        while self._prios:
            p = self._prios[0]
            d = self._buckets[p]
            while d:
                qpi = d[0]
                if entries.get(qpi.key) is qpi:
                    return qpi
                d.popleft()
            heapq.heappop(self._prios)
            del self._buckets[p]
        return None

    def remove(self, key: str) -> QueuedPodInfo | None:
        return self._entries.pop(key, None)

    def pop_tail(self) -> QueuedPodInfo | None:
        """Pop the LOWEST-priority, youngest pod — the shed victim order
        for bounded admission.  Walks buckets from the largest -priority
        key (lowest priority) and takes the deque tail (latest insertion).
        Emptied buckets are left in place: their key is still in the
        _prios heap and pop()/peek() retire the pair together."""
        entries = self._entries
        for p in sorted(self._buckets, reverse=True):
            d = self._buckets[p]
            while d:
                qpi = d.pop()
                if entries.get(qpi.key) is qpi:
                    del entries[qpi.key]
                    return qpi
        return None

    def pop_n(self, max_n: int) -> list[QueuedPodInfo]:
        """Drain up to max_n pods in priority/FIFO order.  The full-drain
        case (the TPU batch path's dominant shape: the whole queue fits
        one batch) validates ghosts against the entries dict bucket by
        bucket and retires the dict with ONE clear() instead of a del
        per pod — measurably cheaper at 16k-pod drains."""
        entries = self._entries
        if len(entries) <= max_n:
            out: list[QueuedPodInfo] = []
            while self._prios:
                p = heapq.heappop(self._prios)
                for qpi in self._buckets.pop(p):
                    if entries.get(qpi.key) is qpi:
                        out.append(qpi)
            entries.clear()
            return out
        out = []
        while len(out) < max_n:
            qpi = self.pop()
            if qpi is None:
                break
            out.append(qpi)
        return out

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def items(self) -> list[QueuedPodInfo]:
        return list(self._entries.values())


class PodNominator:
    """Nominated-pod bookkeeping (scheduling_queue.go nominator)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._node_to_pods: dict[str, dict[str, PodInfo]] = {}
        self._pod_to_node: dict[str, str] = {}

    def add_nominated_pod(self, pi: PodInfo, node_name: str | None = None) -> None:
        node = node_name or pi.nominated_node_name
        if not node:
            return
        with self._lock:
            self.delete_nominated_pod_if_exists(pi.pod)
            self._node_to_pods.setdefault(node, {})[pi.key] = pi
            self._pod_to_node[pi.key] = node

    def delete_nominated_pod_if_exists(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        if key not in self._pod_to_node:
            return  # lock-free precheck: dict reads are GIL-atomic and the
            # hot caller (bulk bind-confirm delete) never nominated the pod
        with self._lock:
            node = self._pod_to_node.pop(key, None)
            if node:
                pods = self._node_to_pods.get(node)
                if pods:
                    pods.pop(key, None)
                    if not pods:
                        del self._node_to_pods[node]

    def nominated_pods_for_node(self, node_name: str) -> list[PodInfo]:
        with self._lock:
            return list(self._node_to_pods.get(node_name, {}).values())

    def all_nominations(self) -> list[tuple[PodInfo, str]]:
        """(pod_info, node_name) for every live nomination — the batched
        preemption path folds these into the device's per-priority-group
        claimed capacity (RunFilterPluginsWithNominatedPods parity)."""
        with self._lock:
            return [(pi, node)
                    for node, pods in self._node_to_pods.items()
                    for pi in pods.values()]


class SchedulingQueue:
    """The 3-tier priority queue."""

    def __init__(
        self,
        sort_key: Callable[[QueuedPodInfo], tuple] = default_sort_key,
        pod_initial_backoff: float = DEFAULT_POD_INITIAL_BACKOFF,
        pod_max_backoff: float = DEFAULT_POD_MAX_BACKOFF,
        unschedulable_timeout: float = DEFAULT_UNSCHEDULABLE_TIMEOUT,
        cluster_event_map: dict[str, list[ClusterEvent]] | None = None,
        priority_fifo: bool | None = None,
        queue_cap: int = 0,
        shed_protect_priority: int = 1000,
        shed_protect_age: float = 30.0,
    ):
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # priority-FIFO-shaped sorts (the default + PrioritySort) take the
        # O(1) bucket queue; a custom QueueSort keeps the generic heap
        if priority_fifo is None:
            priority_fifo = sort_key is default_sort_key
        # the three tiers move pods between them under one lock; _cond
        # shares it (Condition(self._lock)), so either name proves a
        # mutation site to the lock-discipline rule
        self._active = _BucketQueue() if priority_fifo else _Heap(sort_key)  # guarded-by: _lock|_cond
        self._backoff = _Heap(lambda q: (self._backoff_expiry(q),))  # guarded-by: _lock|_cond
        self._unschedulable: dict[str, QueuedPodInfo] = {}  # guarded-by: _lock|_cond
        self._initial_backoff = pod_initial_backoff
        self._max_backoff = pod_max_backoff
        self._unschedulable_timeout = unschedulable_timeout
        # plugin name -> events it re-queues on (from EnqueueExtensions)
        self._cluster_event_map = cluster_event_map or {}
        self.nominator = PodNominator()
        self._scheduling_cycle = 0
        self._move_request_cycle = -1
        self._closed = False
        self._flush_thread: threading.Thread | None = None
        # bounded admission (overload: stanza) — 0 = unbounded
        self._queue_cap = queue_cap
        self._shed_protect_priority = shed_protect_priority
        self._shed_protect_age = shed_protect_age
        self._shed_pending: dict[tuple[str, str], int] = {}  # guarded-by: _lock|_cond
        # engagement gate (overload: engagement): when the scheduler's
        # engagement controller drives this queue it parks the cap until
        # the pipeline is actually drowning — the disengaged hot path
        # then costs one attribute read in _shed_over_cap_locked.
        # Default True: a cap set without a controller (tests, legacy
        # `engagement: always`) enforces immediately, as it always has.
        self._overload_engaged = True

    # -- backoff ---------------------------------------------------------

    def _backoff_duration(self, qpi: QueuedPodInfo) -> float:
        d = self._initial_backoff
        for _ in range(qpi.attempts - 1):
            d *= 2
            if d >= self._max_backoff:
                return self._max_backoff
        return d

    def _backoff_expiry(self, qpi: QueuedPodInfo) -> float:
        return qpi.timestamp + self._backoff_duration(qpi)

    def _is_backing_off(self, qpi: QueuedPodInfo) -> bool:
        return qpi.attempts > 0 and self._backoff_expiry(qpi) > time.monotonic()

    # -- bounded admission (overload: stanza) ----------------------------

    def set_overload_policy(self, queue_cap: int,
                            shed_protect_priority: int = 1000,
                            shed_protect_age: float = 30.0) -> None:
        with self._lock:
            self._queue_cap = queue_cap
            self._shed_protect_priority = shed_protect_priority
            self._shed_protect_age = shed_protect_age

    def set_overload_engaged(self, engaged: bool) -> None:
        """Gate bounded admission on the engagement state machine.  While
        False the cap is dormant (adds never shed); flipping True does
        NOT retro-shed — call enforce_cap() for that."""
        with self._lock:
            self._overload_engaged = engaged

    def enforce_cap(self) -> None:
        """One-shot cap enforcement against the CURRENT queue: the
        engagement controller calls this at the disengaged->engaged edge
        so backlog admitted while dormant is shed immediately instead of
        waiting for the next add/event to trip the cap."""
        with self._lock:
            self._shed_over_cap_locked("engaged")

    def _priority_band(self, priority: int) -> str:
        if priority >= SYSTEM_PRIORITY_BAND:
            return "system"
        if priority >= self._shed_protect_priority:
            return "high"
        if priority > 0:
            return "normal"
        return "best_effort"

    def _shed_victim_locked(self) -> QueuedPodInfo | None:
        """Lowest-priority-first, youngest-first-within-priority victim.
        O(1)-ish on the bucket queue; generic heaps take an O(n) scan."""
        pop_tail = getattr(self._active, "pop_tail", None)
        if pop_tail is not None:
            return pop_tail()
        items = self._active.items()
        if not items:
            return None
        victim = min(items, key=lambda q: (q.pod_info.priority, -q.timestamp))
        return self._active.remove(victim.key)

    def _shed_over_cap_locked(self, reason: str) -> None:
        """Shed activeQ down to the cap: move excess pods to the backoff
        tier, lowest priority first.  Shedding is never a drop — the pod
        keeps its initial_attempt_timestamp and re-enters through the
        backoff flush; attempts is bumped so repeat sheds wait out a
        growing backoff instead of hot-looping shed→flush→shed.

        Protection (pods put back untouched, making the cap soft):
          - priority >= shed_protect_priority (system/high band), and
          - pods queued longer than shed_protect_age — which bounds the
            shed loop: every pod's age only grows, so eventual admission
            is guaranteed."""
        cap = self._queue_cap
        if cap <= 0 or not self._overload_engaged:
            return
        excess = len(self._active) - cap
        if excess <= 0:
            return
        now = time.monotonic()
        protected: list[QueuedPodInfo] = []
        for _ in range(excess):
            qpi = self._shed_victim_locked()
            if qpi is None:
                break
            if (qpi.pod_info.priority >= self._shed_protect_priority
                    or now - qpi.initial_attempt_timestamp
                    >= self._shed_protect_age):
                protected.append(qpi)
                continue
            qpi.attempts += 1
            qpi.timestamp = now
            self._backoff.push(qpi)
            band = self._priority_band(qpi.pod_info.priority)
            key = (reason, band)
            self._shed_pending[key] = self._shed_pending.get(key, 0) + 1
        for qpi in protected:
            self._active.push(qpi)

    def drain_shed_total(self) -> dict[tuple[str, str], int]:
        """Drained by Scheduler.expose_metrics into
        scheduler_queue_shed_total{reason,priority_band}."""
        with self._lock:
            out, self._shed_pending = self._shed_pending, {}
        return out

    # -- add/pop ---------------------------------------------------------

    def add(self, pod: Obj) -> None:
        qpi = QueuedPodInfo(PodInfo(pod))
        with self._cond:
            self._backoff.remove(qpi.key)
            self._unschedulable.pop(qpi.key, None)
            self._active.push(qpi)
            self.nominator.add_nominated_pod(qpi.pod_info)
            self._shed_over_cap_locked("admission")
            self._cond.notify()

    def add_many(self, pods: list[Obj]) -> None:
        """Bulk add: PodInfo parsing happens OUTSIDE the lock (it is the
        expensive part), then one locked loop + one wakeup for the burst."""
        qpis = [QueuedPodInfo(PodInfo(p)) for p in pods]
        with self._cond:
            for qpi in qpis:
                self._backoff.remove(qpi.key)
                self._unschedulable.pop(qpi.key, None)
                self._active.push(qpi)
                self.nominator.add_nominated_pod(qpi.pod_info)
            self._shed_over_cap_locked("admission")
            self._cond.notify()

    def delete_many(self, pods: list[Obj]) -> None:
        """Bulk delete (scheduler bind confirmations) under one lock."""
        with self._cond:
            for pod in pods:
                key = meta.namespaced_name(pod)
                self._active.remove(key)
                self._backoff.remove(key)
                self._unschedulable.pop(key, None)
                self.nominator.delete_nominated_pod_if_exists(pod)

    def scheduling_cycle(self) -> int:
        with self._lock:
            return self._scheduling_cycle

    def stats(self) -> dict[str, int]:
        """Queue sizes for the pending_pods{queue=} gauge."""
        with self._lock:
            return {"active": len(self._active),
                    "backoff": len(self._backoff),
                    "unschedulable": len(self._unschedulable)}

    def has(self, pod: Obj) -> bool:
        """Whether the pod sits in ANY tier — the scale-out partition
        resync uses this to avoid re-admitting pods it already holds
        (a duplicate active entry would schedule the pod twice and
        manufacture a self-conflict at bind time)."""
        key = meta.namespaced_name(pod)
        with self._lock:
            return (key in self._active or key in self._backoff
                    or key in self._unschedulable)

    def pop(self, timeout: float | None = None) -> QueuedPodInfo | None:
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not len(self._active) and not self._closed:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)
            if self._closed and not len(self._active):
                return None
            qpi = self._active.pop()
            if qpi is not None:
                qpi.attempts += 1
                self._scheduling_cycle += 1
            return qpi

    def pop_batch(self, max_n: int, timeout: float | None = None) -> list[QueuedPodInfo]:
        """Drain up to max_n pods for a TPU batch. Blocks for the first pod
        only; the rest are taken non-blocking so latency stays bounded."""
        first = self.pop(timeout)
        if first is None:
            return []
        batch = [first]
        with self._cond:
            pop_n = getattr(self._active, "pop_n", None)
            if pop_n is not None:
                rest = pop_n(max_n - 1)
                for qpi in rest:
                    qpi.attempts += 1
                self._scheduling_cycle += len(rest)
                batch.extend(rest)
            else:
                while len(batch) < max_n:
                    qpi = self._active.pop()
                    if qpi is None:
                        break
                    qpi.attempts += 1
                    self._scheduling_cycle += 1
                    batch.append(qpi)
        return batch

    def requeue_backoff(self, qpis: Iterable[QueuedPodInfo]) -> None:
        """Return a popped batch whose BACKEND failed (remote seam down,
        device lost — see scheduler.BackendUnavailableError) to the backoff
        tier.

        Unlike add_unschedulable_if_not_present this records no per-pod
        failure: the pods were never scheduled against, so they keep their
        unschedulable_plugins and are not parked.  attempts was already
        incremented by pop/pop_batch, so the refreshed timestamp makes each
        pod wait out its exponential backoff before the flush loop moves it
        back to activeQ — a dead seam cannot spin the scheduling loop hot."""
        with self._cond:
            now = time.monotonic()
            for qpi in qpis:
                key = qpi.key
                if (key in self._active or key in self._backoff
                        or key in self._unschedulable):
                    continue  # re-added by an event while the batch was out
                qpi.timestamp = now
                self._backoff.push(qpi)
            # no notify: nothing landed in activeQ (the flush loop promotes
            # pods as their backoff expires)

    def add_unschedulable_if_not_present(self, qpi: QueuedPodInfo,
                                         pod_scheduling_cycle: int) -> None:
        """Park a pod that failed scheduling (scheduling_queue.go:374).

        If a move request raced with this pod's cycle, send it to backoff/
        active instead of the parking lot (the moveRequestCycle guard).
        """
        with self._cond:
            key = qpi.key
            if key in self._active or key in self._backoff or key in self._unschedulable:
                return
            qpi.timestamp = time.monotonic()
            self.nominator.add_nominated_pod(qpi.pod_info)
            if self._move_request_cycle >= pod_scheduling_cycle:
                self._backoff.push(qpi)
            else:
                self._unschedulable[key] = qpi

    def update(self, old: Obj, new: Obj) -> None:
        """Pod updated while pending: refresh in place; an update that could
        make it schedulable moves it out of unschedulable (simplified
        updatePodMayBeMakeSchedulable)."""
        key = meta.namespaced_name(new)
        with self._cond:
            qpi = self._unschedulable.get(key)
            if qpi is not None:
                qpi.pod_info.update(new)
                del self._unschedulable[key]
                if self._is_backing_off(qpi):
                    self._backoff.push(qpi)
                else:
                    self._active.push(qpi)
                    self._cond.notify()
                return
            if key in self._active:
                q = self._active.remove(key)
                q.pod_info.update(new)
                self._active.push(q)
            elif key in self._backoff:
                q = self._backoff.remove(key)
                q.pod_info.update(new)
                self._backoff.push(q)

    def delete(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        with self._cond:
            self._active.remove(key)
            self._backoff.remove(key)
            self._unschedulable.pop(key, None)
            self.nominator.delete_nominated_pod_if_exists(pod)

    # -- event-driven requeue -------------------------------------------

    def _pod_matches_event(self, qpi: QueuedPodInfo, event: ClusterEvent) -> bool:
        if event == ClusterEvent("*", "*"):
            return True
        if not qpi.unschedulable_plugins:
            return True
        for plugin in qpi.unschedulable_plugins:
            for ev in self._cluster_event_map.get(plugin, ()):
                if ev.match(event):
                    return True
        return False

    def move_all_to_active_or_backoff(self, event: ClusterEvent) -> None:
        """MoveAllToActiveOrBackoffQueue: cluster changed — unpark pods whose
        failure could be resolved by `event`."""
        with self._cond:
            moved = []
            for key, qpi in list(self._unschedulable.items()):
                if self._pod_matches_event(qpi, event):
                    moved.append(key)
                    if self._is_backing_off(qpi):
                        self._backoff.push(qpi)
                    else:
                        self._active.push(qpi)
            for key in moved:
                del self._unschedulable[key]
            self._move_request_cycle = self._scheduling_cycle
            if moved:
                self._shed_over_cap_locked("event_move")
                self._cond.notify_all()

    def assigned_pod_added(self, pod: Obj) -> None:
        """A pod got bound: affinity-failed pods may now fit (simplified
        AssignedPodAdded — we move pods failed on InterPodAffinity)."""
        self.move_all_to_active_or_backoff(ClusterEvent("AssignedPod", "Add"))

    # -- flush loops (Run, :298) ----------------------------------------

    def run(self) -> None:
        if self._flush_thread is not None:
            return
        self._flush_thread = threading.Thread(
            target=self._flush_loop, name="queue-flush", daemon=True)
        self._flush_thread.start()

    def _flush_loop(self) -> None:
        last_unsched_flush = time.monotonic()
        while not self._closed:
            time.sleep(0.2)  # reference: 1s backoff flush; we poll faster
            with self._cond:
                now = time.monotonic()
                notified = False
                while True:
                    head = self._backoff.peek()
                    if head is None or self._backoff_expiry(head) > now:
                        break
                    self._active.push(self._backoff.pop())
                    notified = True
                if now - last_unsched_flush > 5.0:
                    last_unsched_flush = now
                    for key, qpi in list(self._unschedulable.items()):
                        if now - qpi.timestamp > self._unschedulable_timeout:
                            del self._unschedulable[key]
                            if self._is_backing_off(qpi):
                                self._backoff.push(qpi)
                            else:
                                self._active.push(qpi)
                                notified = True
                if notified:
                    self._shed_over_cap_locked("backoff_promotion")
                    self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- introspection ---------------------------------------------------

    def pending_pods(self) -> tuple[list[Obj], str]:
        with self._lock:
            active = [q.pod for q in self._active.items()]
            backoff = [q.pod for q in self._backoff.items()]
            unsched = [q.pod for q in self._unschedulable.values()]
        summary = (f"activeQ:{len(active)} backoffQ:{len(backoff)} "
                   f"unschedulable:{len(unsched)}")
        return active + backoff + unsched, summary
