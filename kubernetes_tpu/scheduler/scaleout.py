"""Horizontal scale-out: partition assignment + instance liveness for N
cooperating scheduler instances over one shared store.

Design lineage is Omega-style shared-state scheduling: every instance
runs the full pipeline (informers, cache, queue, device backend) against
one store and commits binds optimistically — the compare-and-bind
precondition in the store (kv.bind_many) is what prevents double-binds,
and a structured kv.BindConflict tells the loser to Forget and requeue.
Partitioning therefore only exists to keep the conflict rate near zero:
it routes work, it does not enforce correctness.

Partitioning (ScaleOutPolicy, scheduler/config.py `scaleOut:` stanza):

  nodePoolRing (default)   node names AND pod keys hash (crc32, stable
                           across processes) onto `ring_slices` virtual
                           slices; slice s belongs to instance
                           s % instance_count.  When that home instance
                           is dead, the slice falls back to the live
                           instance at s % len(live) — every survivor
                           computes the same map from the same lease
                           table, no coordination round.
  namespaceHash (fallback) pods partition by namespace hash; every
                           instance sees all nodes.  For clusters whose
                           node names hash unevenly or that want
                           namespace affinity to instance-local caches.

Liveness rides the store, the same seam replication fencing uses
(store/replica.py): each instance renews a Lease object under
kube-system every renew_interval; a lease unrenewed for lease_duration
marks the instance dead and its slices are absorbed by survivors on
their next sweep.  An instance that loses its own lease (partitioned,
suspended, or fenced by a store failover) flips self_live to False and
the scheduler stops committing binds — its in-flight batch lands in the
backoff tiers, never on a node a peer now owns.

Reference: staging/src/k8s.io/client-go/tools/leaderelection/leaderelection.go
(Lease acquire/renew discipline, here per-instance instead of
single-winner) + pkg/scheduler/schedule_one.go:1023 (the bind
conflict -> Forget -> requeue tail this coordinator's fence protects).
"""

from __future__ import annotations

import threading
import time
import zlib

from ..api.meta import Obj
from ..client.clientset import LEASES
from ..store import kv

LEASE_NAMESPACE = "kube-system"
LEASE_PREFIX = "scheduler-instance-"


def _slice(key: str, slices: int) -> int:
    """Stable cross-process hash (Python's str hash is salted)."""
    return zlib.crc32(key.encode()) % slices


class ScaleOutCoordinator:
    """One per scheduler instance: ownership queries + lease liveness.

    Ownership queries (owns_pod/owns_node) sit on the informer hot path,
    so membership is kept as an immutable sorted tuple swapped under a
    lock and read without one (GIL-atomic reference read, the same
    discipline as the store's fence flag)."""

    def __init__(self, policy, now_fn=time.time):
        self.policy = policy
        self._now = now_fn
        self._lock = threading.Lock()
        self._live: tuple[int, ...] = tuple(range(policy.instance_count))
        self._retired = False
        self._last_tick = float("-inf")
        self._boot = now_fn()

    # -- identity ---------------------------------------------------------

    @property
    def index(self) -> int:
        return self.policy.instance_index

    @property
    def live(self) -> tuple[int, ...]:
        return self._live

    @property
    def self_live(self) -> bool:
        """False once this instance retired or lost its lease: the write
        fence for binds (scheduler._bulk_bind_commit checks it)."""
        return not self._retired and self.index in self._live

    # -- ownership --------------------------------------------------------

    def _owner(self, s: int) -> int:
        """Home instance of slice s, falling back round-robin over the
        live membership when the home is dead — minimal-motion: a live
        instance's slices never move, only a dead one's reassign."""
        home = s % self.policy.instance_count
        live = self._live
        if not live or home in live:
            return home
        return live[s % len(live)]

    def owns_pod(self, namespace: str, name: str) -> bool:
        namespace = namespace or "default"  # one normal form, every caller
        if self.policy.partition_by == "namespaceHash":
            key = namespace
        else:
            key = f"{namespace}/{name}"
        return self._owner(_slice(key, self.policy.ring_slices)) == self.index

    def owns_node(self, node_name: str) -> bool:
        if self.policy.partition_by == "namespaceHash":
            return True  # pods partition; the node view is shared
        return self._owner(
            _slice(node_name, self.policy.ring_slices)) == self.index

    # -- membership -------------------------------------------------------

    def set_live(self, indices) -> bool:
        """Install a membership view; True when it changed (the caller
        must then resync ownership — Scheduler._scaleout_resync)."""
        new = tuple(sorted(set(indices)))
        with self._lock:
            changed = new != self._live
            self._live = new
        return changed

    def retire(self) -> None:
        """Stop renewing and stop binding — the instance-kill switch the
        chaos harness flips (a real deployment gets here through lease
        expiry or a store fence)."""
        self._retired = True

    def revive(self) -> None:
        self._retired = False
        self._boot = self._now()  # fresh grace window for our own lease

    # -- lease heartbeat + sweep ------------------------------------------

    def _lease_name(self, index: int) -> str:
        return f"{LEASE_PREFIX}{index}"

    def heartbeat(self, client, now: float) -> None:
        """Renew this instance's Lease (create on first touch)."""
        name = self._lease_name(self.index)
        body = {"kind": "Lease", "apiVersion": "coordination.k8s.io/v1",
                "metadata": {"namespace": LEASE_NAMESPACE, "name": name},
                "spec": {"holderIdentity": str(self.index),
                         "renewTime": now}}
        try:
            client.create(LEASES, body)
        except kv.AlreadyExistsError:
            def renew(cur: Obj) -> Obj:
                cur.setdefault("spec", {})["renewTime"] = now
                cur["spec"]["holderIdentity"] = str(self.index)
                return cur
            client.guaranteed_update(LEASES, LEASE_NAMESPACE, name, renew)

    def sweep(self, client, now: float) -> bool:
        """Recompute the live set from the shared lease table; True when
        membership changed.  An instance whose lease has never appeared
        is granted one lease_duration of boot grace so a cold start is
        not a churn storm."""
        leases, _ = client.list(LEASES, LEASE_NAMESPACE)
        renewed: dict[int, float] = {}
        for lease in leases:
            name = (lease.get("metadata") or {}).get("name", "")
            if not name.startswith(LEASE_PREFIX):
                continue
            try:
                idx = int(name[len(LEASE_PREFIX):])
            except ValueError:
                continue
            renewed[idx] = float(
                (lease.get("spec") or {}).get("renewTime") or 0.0)
        live = []
        for idx in range(self.policy.instance_count):
            seen = renewed.get(idx)
            if seen is None:
                alive = now - self._boot <= self.policy.lease_duration
            else:
                alive = now - seen <= self.policy.lease_duration
            if alive:
                live.append(idx)
        return self.set_live(live)

    def tick(self, client, now: float | None = None) -> bool:
        """Heartbeat + sweep, rate-limited to renew_interval; called from
        the scheduling loop (no extra thread).  Returns True when the
        membership changed and the caller must resync its partition."""
        if now is None:
            now = self._now()
        if now - self._last_tick < self.policy.renew_interval:
            return False
        self._last_tick = now
        if not self._retired:
            try:
                self.heartbeat(client, now)
            except (kv.StoreError, OSError):
                # fenced / read-only / partitioned store, or an apiserver
                # mid-handoff (connection refused): we cannot renew, so
                # the sweep below will eventually drop us from live.  An
                # exception here must never kill the scheduling loop —
                # the lease protocol already handles a silent instance.
                pass
        try:
            return self.sweep(client, now)
        except (kv.StoreError, OSError):
            return False
