"""Scheduler core: cache + queue + profiles + the scheduling pipeline.

Reference: pkg/scheduler/scheduler.go (Scheduler/New/Run),
pkg/scheduler/schedule_one.go (the whole per-pod pipeline: schedulingCycle
:116, bindingCycle :223, schedulePod :372, findNodesThatFitPod :425,
numFeasibleNodesToFind :585, prioritizeNodes :671, selectHost :777, assume
:802, bind :824, handleSchedulingFailure :873), and
pkg/scheduler/eventhandlers.go:249 (informer wiring).

Two execution modes share every correctness-critical piece (cache
assume/confirm, queue backoff/requeue, Reserve/Permit/bind, failure
handling):

  per-pod  - faithful scheduleOne: one pod per cycle, Filter/Score over
             nodes in Python.  The oracle and fallback.
  batch    - TPU path: pop_batch(K) drains up to K pods, ships them through
             a BatchBackend (ops/backend.py) that computes feasibility masks,
             scores and a conflict-free assignment for the whole batch on
             device, then each assignment is assumed/reserved/bound
             individually so failure semantics stay per-pod.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

import numpy as np

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import Client, NAMESPACES, NODES, PDBS, PODS
from ..client.informer import SharedInformerFactory
from ..store import kv
from ..component_base import tracing
from ..component_base import timeline as cb_timeline
from ..utils import fasthost, stagelat
from . import metrics as _metrics
from .cache import Cache, Snapshot
from .framework import CycleState, Framework, Handle
from .preemption import evict_victims
from .queue import SchedulingQueue
from .types import (
    ERROR, SUCCESS, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, WAIT,
    ClusterEvent, Diagnosis, FitError, NodeInfo, PodInfo, QueuedPodInfo, Status,
    is_success,
)

logger = logging.getLogger(__name__)

# numFeasibleNodesToFind (schedule_one.go:54-59)
MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND = 5


class SchedulerMetrics:
    """Scheduler metric bundle.

    Full named metric set lives in metrics.Metrics (pkg/scheduler/metrics/
    metrics.go parity, Prometheus exposition via .expose()); this wrapper
    keeps the cheap in-process views (attempt counts, raw latency list)
    that the perf harness samples at 1s without text parsing.
    """

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.prom = _metrics.Metrics()
        self.schedule_attempts = {"scheduled": 0, "unschedulable": 0, "error": 0}  # guarded-by: lock
        self.scheduling_latency_sum = 0.0  # guarded-by: lock
        self.scheduling_latencies: list[float] = []  # guarded-by: lock
        # submit->bind per pod: queue admission (QueuedPodInfo creation)
        # to bind write confirmed.  The OTHER half of the north-star metric
        # (p99 <10ms); reference: pod_scheduling_duration_seconds
        # (pkg/scheduler/metrics/metrics.go:55-75)
        self.pod_e2e_latencies: list[float] = []  # guarded-by: lock
        # per-pod e2e decomposition segments (ms), keyed by segment name
        # (timeline.POD_SEGMENTS); populated only while profiling.timeline
        # is armed — the raw series behind latency_decomposition rows.
        # Held as append-only chunks (the ndarray columns straight off the
        # bind path) rather than boxed floats: arming must not create
        # hundreds of thousands of gc-tracked objects per run.  Sample
        # counts are tracked separately for the watermark contract.
        self.pod_segment_ms: dict[str, list] = {}  # guarded-by: lock
        self._pod_segment_n: dict[str, int] = {}  # guarded-by: lock
        # raw (t_enq, t_bind, marks) wave tuples awaiting derivation:
        # the bind path defers the clamp chain + histogram ingest to
        # the first reader (segment_mark/segment_summary/expose)
        self._pending_segments: list = []  # guarded-by: lock
        self.preemption_attempts = 0

    def observe_attempt(self, result: str, latency: float,
                        profile: str = "default-scheduler") -> None:
        with self.lock:
            self.schedule_attempts[result] = self.schedule_attempts.get(result, 0) + 1
            self.scheduling_latency_sum += latency
            self.scheduling_latencies.append(latency)
        self.prom.schedule_attempts.inc(1.0, result, profile)
        self.prom.scheduling_attempt_duration.observe(latency, result, profile)

    def observe_attempts(self, result: str, latencies: list[float],
                         profile: str = "default-scheduler") -> None:
        """Bulk observe (batch bind tail): one lock, one counter bump."""
        if not latencies:
            return
        with self.lock:
            self.schedule_attempts[result] = (
                self.schedule_attempts.get(result, 0) + len(latencies))
            self.scheduling_latency_sum += sum(latencies)
            self.scheduling_latencies.extend(latencies)
        self.prom.schedule_attempts.inc(float(len(latencies)), result, profile)
        self.prom.scheduling_attempt_duration.observe_many(latencies, result,
                                                           profile)

    def observe_e2e(self, lat_attempts: list[tuple[float, int]]) -> None:
        """Record submit->bind latencies for successfully-bound pods.
        Entries are (latency_seconds, attempts) — the prom histogram is
        labelled by attempt count like the reference's."""
        if not lat_attempts:
            return
        with self.lock:
            self.pod_e2e_latencies.extend(l for l, _ in lat_attempts)
        by_attempts: dict[str, list[float]] = {}
        for lat, att in lat_attempts:
            by_attempts.setdefault(str(att), []).append(lat)
        for att, ls in by_attempts.items():
            self.prom.pod_scheduling_duration.observe_many(ls, att)

    def e2e_mark(self) -> int:
        """Watermark into the e2e buffer; pass to e2e_summary(since=...)
        to report only pods bound after this point (the perf harness
        excludes warm-up pods this way, like the reference's
        collectMetrics gating)."""
        with self.lock:
            return len(self.pod_e2e_latencies)

    def e2e_summary(self, since: int = 0) -> dict:
        """Percentiles over recorded submit->bind latencies (ms),
        optionally only entries recorded after the `since` watermark."""
        with self.lock:
            xs = sorted(self.pod_e2e_latencies[since:])
        if not xs:
            return {}
        def pct(p: float) -> float:
            return round(1e3 * xs[min(int(len(xs) * p), len(xs) - 1)], 2)
        return {"count": len(xs), "p50_ms": pct(0.50), "p90_ms": pct(0.90),
                "p95_ms": pct(0.95), "p99_ms": pct(0.99),
                "max_ms": round(1e3 * xs[-1], 2)}

    def observe_segments(self, by_seg: dict) -> None:
        """Record per-pod telescoped latency decompositions, one column
        of ms values per segment (the timeline's invariant: the segments
        of one pod sum to its e2e).  Columns are numpy arrays or lists.
        Feeds scheduler_pod_latency_ms{segment} and the in-process
        series segment_summary() reads."""
        with self.lock:
            for name, ls in by_seg.items():
                self.pod_segment_ms.setdefault(name, []).append(ls)
                self._pod_segment_n[name] = (
                    self._pod_segment_n.get(name, 0)
                    + (int(ls.size) if hasattr(ls, "size") else len(ls)))
        for name, ls in by_seg.items():
            self.prom.pod_latency_ms.observe_array(ls, name)

    def defer_segments(self, t_enq, t_bind: float, marks) -> None:
        """Bind-path tap: queue one wave's raw decomposition inputs
        (enqueue column, bind wall, stage marks) and return.  The clamp
        chain, the series chunks and the prom histogram ingest all run
        on the first read (_flush_segments) — the armed bind path must
        stay one append, or the ≤5% overhead pin breaks."""
        with self.lock:
            self._pending_segments.append((t_enq, t_bind, marks))

    def _flush_segments(self) -> None:
        with self.lock:
            pend, self._pending_segments = self._pending_segments, []
        if not pend:
            return
        for t_enq, t_bind, marks in pend:
            self.observe_segments(
                cb_timeline.derive_segment_cols(t_enq, t_bind, marks))

    def segment_mark(self) -> dict[str, int]:
        """Per-segment watermark (same contract as e2e_mark)."""
        self._flush_segments()
        with self.lock:
            return dict(self._pod_segment_n)

    def segment_summary(self, since: dict | None = None) -> dict:
        """p50/p95/p99 (ms) per decomposition segment, past the mark."""
        self._flush_segments()
        since = since or {}
        with self.lock:
            chunks = {k: list(v) for k, v in self.pod_segment_ms.items()}
        out: dict[str, dict] = {}
        for k, cs in chunks.items():
            flat: list[float] = []
            for c in cs:
                flat.extend(c.tolist() if hasattr(c, "tolist") else c)
            xs = sorted(flat[since.get(k, 0):])
            if not xs:
                continue
            def pct(p: float) -> float:
                return round(xs[min(int(len(xs) * p), len(xs) - 1)], 3)
            out[k] = {"count": len(xs), "p50_ms": pct(0.50),
                      "p95_ms": pct(0.95), "p99_ms": pct(0.99)}
        return out

    def observe_preemption(self, victims: int) -> None:
        with self.lock:
            self.preemption_attempts += 1
        self.prom.preemption_attempts.inc()
        self.prom.preemption_victims.observe(victims)

    def expose(self) -> str:
        self._flush_segments()  # scrape sees deferred wave decompositions
        return self.prom.expose()


class BackendUnavailableError(RuntimeError):
    """A batch backend failed for reasons unrelated to the pods in the
    batch — worker/transport failure, device loss, retries exhausted
    (raised by the remote seam's error ladder, ops/remote.py).

    This is NEVER a per-pod scheduling verdict: the scheduler returns the
    whole batch to the queue's backoff tier (requeue_backoff) and keeps
    running, instead of marking pods unschedulable or letting the loop
    thread die with the exception."""


class BatchBackend:
    """Contract for the TPU batch path (implemented by ops/backend.py and
    parallel/backend.py).

    assign() must account for intra-batch resource consumption: if two pods
    in the batch fit the same node only serially, the returned assignment
    reflects the running-sum constraint (SURVEY.md §7 hard part #1).

    Results carry node NAMES, resolved against the snapshot the batch was
    dispatched with — a later dispatch may recycle node rows (deleted node
    freed, new node reused the slot), so indices must never escape the
    backend.

    Backends that cannot safely overlap a new dispatch with an unresolved
    batch (no resident device state chaining) set supports_pipelining =
    False; the scheduler then resolves + finishes batch k before
    dispatching k+1.
    """

    supports_pipelining = True

    def assign(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot
               ) -> list[tuple[str | None, Status | None]]:
        """Returns, per pod (same order): (node_name or None, status)."""
        raise NotImplementedError

    def dispatch(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        """Async variant: kick off the batch and return resolve() -> results.
        Default wraps assign() synchronously; TPUBatchBackend overrides it
        with a true async device dispatch so the scheduler can overlap the
        previous batch's bind tail with the device round trip."""
        results = self.assign(pod_infos, snapshot)
        return lambda: results


class _WaveTuner:
    """AIMD controller for the dispatch wave size (overload: sloP99Ms).

    One observation per retired wave: dispatch -> results-applied latency
    against the SLO.  Breach -> multiplicative decrease (halve by
    default), under -> additive increase, faster while the queue is
    backlogged beyond the current wave.  Classic AIMD converges to the
    largest wave the device sustains within the latency target instead
    of letting a slow device turn a static batch size into unbounded
    per-wave latency ("The Tail at Scale" engineering: degrade
    throughput, not tail latency)."""

    def __init__(self, wave_cap: int, slo_s: float, wave_min: int,
                 increase: int, decrease: float):
        self._cap = max(1, wave_cap)
        self._slo = slo_s
        self._min = max(1, min(wave_min, self._cap))
        self._increase = max(1, increase)
        self._decrease = decrease
        self._wave = self._cap

    def current(self) -> int:
        return self._wave

    def observe(self, wave_latency_s: float, queue_depth: int) -> None:
        if wave_latency_s > self._slo:
            self._wave = max(self._min, int(self._wave * self._decrease))
        elif queue_depth > self._wave:
            self._wave = min(self._cap, self._wave + self._increase)
        else:
            # no backlog pressure: creep back up slowly so a burst after a
            # quiet period doesn't land on a wave still sized for the storm
            self._wave = min(self._cap,
                             self._wave + max(1, self._increase // 4))

    def reclamp(self, wave_cap: int) -> None:
        """Re-clamp the AIMD bounds to a new batch size (config
        hot-reload): without this a reload that SHRINKS backend.batchSize
        leaves the ceiling — and possibly the live wave — above the new
        batch size until a process restart."""
        self._cap = max(1, wave_cap)
        self._min = min(self._min, self._cap)
        self._wave = min(self._wave, self._cap)

    def reset(self) -> None:
        """Back to the full wave (the disengaged->engaged boundary: an
        engagement spell must not inherit the previous storm's shrunken
        wave, and a disengaged pipeline always dispatches full waves)."""
        self._wave = self._cap


class _OverloadBreaker:
    """Escape-storm circuit breaker: consecutive-failure open, probe-based
    re-close.  Same shape as ops/failover._Breaker (duplicated because
    ops.failover imports this module); now_fn is injectable so tests
    drive the probe clock deterministically."""

    def __init__(self, threshold: int, probe_interval: float,
                 now_fn=time.monotonic):
        self.threshold = max(1, threshold)
        self.probe_interval = probe_interval
        self._now = now_fn
        self.consecutive = 0
        self.opened_at: float | None = None

    @property
    def is_open(self) -> bool:
        return self.opened_at is not None

    def record_storm(self) -> bool:
        """Returns True when this storm OPENS the breaker (edge).  A storm
        while open re-arms the probe window."""
        self.consecutive += 1
        if self.opened_at is not None:
            self.opened_at = self._now()
            return False
        if self.consecutive >= self.threshold:
            self.opened_at = self._now()
            return True
        return False

    def record_calm(self) -> bool:
        """Returns True when a calm batch RE-CLOSES an open breaker."""
        self.consecutive = 0
        if self.opened_at is not None:
            self.opened_at = None
            return True
        return False

    def probe_due(self) -> bool:
        return (self.opened_at is not None
                and self._now() - self.opened_at >= self.probe_interval)


# Engagement transition taxonomy.  The README "Overload protections"
# table and the ktpu-lint taxonomy-sync rule both pin these exact
# tokens, so a new state or reason must land in code and README
# together; _EngagementController asserts it never emits outside them.
_ENGAGEMENT_STATES = ("disengaged", "arming", "engaged", "cooling")
_ENGAGEMENT_REASONS = ("slo_burn", "queue_growth", "blip", "calm",
                       "re_pressure", "cooled", "config")


class _EngagementController:
    """Hysteresis state machine gating the overload layers (overload:
    engagement auto — the shipping default).

        disengaged -> arming       first pressure wave (slo_burn /
                                   queue_growth)
        arming     -> engaged      arm_samples consecutive pressure waves
        arming     -> disengaged   pressure vanished unconfirmed (blip)
        engaged    -> cooling      engage_dwell calm seconds (calm)
        cooling    -> engaged      pressure returned (re_pressure)
        cooling    -> disengaged   cool_dwell calm seconds (cooled)

    The protections are active in engaged AND cooling: the cooling tier
    is the release-side hysteresis, so a flapping storm re-engages a
    still-armed machine instead of thrashing admission open/closed.
    The primary arm signal is the SRE multi-window burn-rate breach
    (SLOTracker.breached(): the two shortest windows both burning >1.0
    — fast window to react, slower window to confirm); the secondary is
    queue-depth growth (backlog beyond queue_growth_factor nominal
    waves AND still growing — catches a flood whose latency damage
    hasn't reached the bind tail yet).  One on_wave() per retired wave;
    the quiescent cost is the two pressure predicates.  All clocks are
    time.monotonic (injectable for tests); wall jumps change nothing.
    """

    DISENGAGED, ARMING, ENGAGED, COOLING = _ENGAGEMENT_STATES

    def __init__(self, policy, slo, now_fn=time.monotonic):
        self.policy = policy
        self.slo = slo  # component_base.profiling.SLOTracker
        self._now = now_fn
        self.state = self.DISENGAGED
        self._arm_count = 0
        self._last_depth = 0
        self._last_pressure_t = float("-inf")
        self._state_t = now_fn()

    @property
    def engaged(self) -> bool:
        return self.state in (self.ENGAGED, self.COOLING)

    def reconfigure(self, policy) -> None:
        """Config hot-reload (SIGHUP): swap knobs in place but KEEP the
        state and dwell clocks — a reload mid-incident must not drop an
        engaged shield or reset a cooling dwell."""
        self.policy = policy
        self.slo.target_s = (policy.slo_p99_ms or 250.0) / 1e3

    def note_latencies(self, latencies_s, now=None) -> None:
        """Feed submit->bind latencies (the bind-commit tail calls this
        every wave, profiling stanza or not — the arm signal must not
        depend on the observatory being on)."""
        self.slo.observe(latencies_s, now=now)

    def _pressure(self, queue_depth: int, nominal_wave: int,
                  now: float) -> str | None:
        if self.slo.breached(now):
            return "slo_burn"
        growing = queue_depth > self._last_depth
        self._last_depth = queue_depth
        if growing and queue_depth > (self.policy.queue_growth_factor
                                      * max(1, nominal_wave)):
            return "queue_growth"
        return None

    def on_wave(self, queue_depth: int, nominal_wave: int,
                now: float | None = None) -> list[tuple[str, str, str]]:
        """Advance one retired wave; returns the transition edges taken
        (the scheduler counts each into overload_transition_total and
        applies the queue/tuner side effects)."""
        now = self._now() if now is None else now
        why = self._pressure(queue_depth, nominal_wave, now)
        if why is not None:
            self._last_pressure_t = now
        edges: list[tuple[str, str, str]] = []

        def move(to: str, reason: str) -> None:
            assert to in _ENGAGEMENT_STATES \
                and reason in _ENGAGEMENT_REASONS
            edges.append((self.state, to, reason))
            self.state = to
            self._state_t = now

        if self.state == self.DISENGAGED:
            if why is not None:
                self._arm_count = 1
                move(self.ARMING, why)
                if self._arm_count >= self.policy.arm_samples:
                    move(self.ENGAGED, why)
        elif self.state == self.ARMING:
            if why is None:
                move(self.DISENGAGED, "blip")
            else:
                self._arm_count += 1
                if self._arm_count >= self.policy.arm_samples:
                    move(self.ENGAGED, why)
        elif self.state == self.ENGAGED:
            if (why is None and now - self._last_pressure_t
                    >= self.policy.engage_dwell):
                move(self.COOLING, "calm")
        elif self.state == self.COOLING:
            if why is not None:
                move(self.ENGAGED, "re_pressure")
            elif now - self._state_t >= self.policy.cool_dwell:
                move(self.DISENGAGED, "cooled")
        return edges

    def detach(self) -> list[tuple[str, str, str]]:
        """configure_overload swapping to always/off/None: drop to
        disengaged, counting the edge so transition totals never lie."""
        if self.state == self.DISENGAGED:
            return []
        edge = (self.state, self.DISENGAGED, "config")
        self.state = self.DISENGAGED
        self._arm_count = 0
        return [edge]


class Profile:
    __slots__ = ("framework", "percentage_of_nodes_to_score", "batch_backend",
                 "batch_size")

    def __init__(self, framework: Framework,
                 percentage_of_nodes_to_score: int = 0,
                 batch_backend: BatchBackend | None = None,
                 batch_size: int = 256):
        self.framework = framework
        self.percentage_of_nodes_to_score = percentage_of_nodes_to_score
        self.batch_backend = batch_backend
        self.batch_size = batch_size


class _BinderWorker:
    """Dedicated binder: one pinned worker + a bounded queue, mirroring
    the remote-worker seam's single-consumer discipline.

    LATENCY.md rounds 4-5 traced the ~8k/s host-only p99 knee to the
    binder: bulk commits ran on a 16-thread pool whose GIL wake-ups
    landed inside the NEXT wave's snapshot/assume window.  Routing the
    bulk/turbo commits through ONE thread (optionally CPU-pinned via
    KTPU_BINDER_PIN) moves the bind write off the wave critical path and
    stops the pool's thundering wake-ups.  The queue is BOUNDED: when
    binds fall behind, put() blocking the dispatch loop IS the
    backpressure (same contract as the bounded relay in ops/remote).

    Only the non-blocking commits route here — the per-pod cycle can
    park in WaitOnPermit (Coscheduling gangs) and would deadlock a
    single consumer, so it stays on the pool."""

    def __init__(self, maxsize: int = 16):
        import queue as _qmod
        self._q: "_qmod.Queue" = _qmod.Queue(maxsize=maxsize)
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._stopped = False

    def _ensure_started(self) -> None:
        if self._thread is None:
            with self._lock:
                if self._thread is None and not self._stopped:
                    t = threading.Thread(target=self._run, name="binder0",
                                         daemon=True)
                    t.start()
                    self._thread = t

    def _run(self) -> None:
        pin = os.environ.get("KTPU_BINDER_PIN", "")
        if pin and hasattr(os, "sched_setaffinity"):
            try:
                os.sched_setaffinity(0, {int(pin)})
            except (OSError, ValueError):
                pass  # advisory: pinning is a perf hint, never a failure
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            # not a retry loop: a failed commit is logged and dropped (its
            # pods requeue via the cycle's own conflict/error tails), the
            # loop moves on to the NEXT queued commit
            # ktpulint: disable=retry-backoff
            except Exception:  # pragma: no cover - commit tails self-handle
                logger.exception("binder worker cycle error")

    def submit(self, fn, *args) -> bool:
        """Enqueue a commit; blocks when the queue is full (backpressure).
        False once stopped — the caller falls back to inline/pool."""
        if self._stopped:
            return False
        self._ensure_started()
        self._q.put((fn, args))
        return True

    def stop(self) -> None:
        """Stop the worker and run any still-queued commits inline so no
        assumed pod is stranded unbound and unrequeued."""
        self._stopped = True
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=5.0)
        while True:
            try:
                item = self._q.get_nowait()
            except Exception:  # queue.Empty
                return
            if item is None:
                continue
            fn, args = item
            try:
                fn(*args)
            # drain, not retry: each queued commit runs once; a failure is
            # logged and the loop advances to the next leftover item
            # ktpulint: disable=retry-backoff
            except Exception:  # pragma: no cover
                logger.exception("binder drain cycle error")


class Scheduler:
    """The scheduler (scheduler.go:62)."""

    def __init__(self, client: Client,
                 informer_factory: SharedInformerFactory,
                 profiles: dict[str, Profile],
                 next_start_node_index_random: bool = False,
                 extenders: Sequence | None = None,
                 pipeline_depth: int = 1,
                 admission_interval: float = 0.0):
        self.client = client
        self.informer_factory = informer_factory
        self.profiles = profiles
        self.extenders = list(extenders or ())
        self.cache = Cache()
        self.metrics = SchedulerMetrics()
        # union of all profiles' event maps gates unschedulable requeue
        event_map: dict[str, list[ClusterEvent]] = {}
        for p in profiles.values():
            event_map.update(p.framework.cluster_event_map())
        default_fw = next(iter(profiles.values())).framework
        qs = default_fw.queue_sort
        sort_key = qs.sort_key if qs else None
        self.queue = SchedulingQueue(
            sort_key=sort_key or (lambda q: (-q.pod_info.priority, q.timestamp)),
            cluster_event_map=event_map,
            # PrioritySort (and the default key) are priority-FIFO shaped:
            # the bucket queue implements them exactly (queue.py)
            priority_fifo=qs is None or getattr(qs, "priority_fifo", False))
        for prof_name, p in profiles.items():
            p.framework.metrics_recorder = (
                lambda point, status, sec, _n=prof_name:
                self.metrics.prom.framework_extension_point_duration.observe(
                    sec, point, status, _n))
        for p in profiles.values():
            p.framework.handle.nominator = self.queue.nominator
            for plugin in p.framework.post_filter:
                if hasattr(plugin, "_snapshot_getter"):
                    plugin._snapshot_getter = (
                        lambda s=self: getattr(s, "_snapshot", None))
                if hasattr(plugin, "preemption_observer"):
                    plugin.preemption_observer = self.metrics.observe_preemption
        self._stop = threading.Event()
        # Batch pipeline: dispatched-but-unfinished batches, oldest first.
        # pipeline_depth bounds how many ride the device queue at once;
        # depth 1 == the classic dispatch-k+1-then-finish-k overlap.
        # Latency mode (p99-targeted): depth ~4 + a small
        # admission_interval — micro-batches dispatch every few ms and
        # their ~70ms tunnel round trips overlap, so a pod's end-to-end
        # latency is one round trip, not one per queued batch
        # (pkg/scheduler/metrics pod_scheduling_duration is the metric
        # this shapes).
        self._pending: list = []
        # adaptive estimate of dispatch -> result-landed latency, used to
        # time-gate eager batch retirement (see schedule_step); starts at
        # the tunneled chip's typical ~2x round-trip flight
        self._flight_est = 0.25
        # when the HEAD of _pending last retired: the stuck-wave watchdog
        # budgets each pipelined wave from the moment it reaches the head
        # of the device queue, not from its dispatch — a slow-but-healthy
        # wave N must not eat wave N+1's deadline (see
        # _resolve_with_deadline)
        self._last_retire_t = 0.0
        self.pipeline_depth = max(1, pipeline_depth)
        self.admission_interval = admission_interval
        self._deferred: list[QueuedPodInfo] = []  # per-pod pods awaiting a quiescent cache
        self._binder_pool = ThreadPoolExecutor(max_workers=16,
                                               thread_name_prefix="bind")
        # bulk/turbo commits route through ONE dedicated worker off the
        # wave critical path; the per-pod cycle (blocking WaitOnPermit)
        # stays on the pool above (see _BinderWorker)
        self._binder_worker = _BinderWorker()
        # distributed tracing (component_base/tracing.py): None until
        # configure_tracing attaches a provider; sampling is decided once
        # per batch at the root span and inherited everywhere below
        self.tracer_provider: tracing.TracerProvider | None = None
        self._tracer: tracing.Tracer | None = None
        # overload protection (config.py OverloadPolicy): None until
        # configure_overload attaches a policy.  The policy now ships
        # enabled by default (engagement: auto) — _engagement holds the
        # hysteresis controller that decides when the shed/tuner/breaker
        # machinery actually bites; None means legacy always-on
        # (engagement: always) or everything off
        self.overload_policy = None
        self._wave_tuner: _WaveTuner | None = None
        self._escape_breaker: _OverloadBreaker | None = None
        self._engagement: _EngagementController | None = None
        # horizontal scale-out (config.py ScaleOutPolicy): None until
        # configure_scaleout attaches a coordinator; single-instance
        # schedulers skip every ownership check
        self.scaleout = None
        # performance observatory (config.py ProfilingPolicy /
        # component_base/profiling.py): None until configure_profiling
        # attaches the host profiler + SLO tracker; everything off by
        # default so the hot path pays nothing unconfigured
        self._profiler = None
        self._slo = None
        self._census_wanted = False
        self._census: dict = {}
        # wave timeline (component_base/timeline.py): None until
        # configure_profiling attaches a recorder; every hot-path site
        # checks `tl is not None and tl.enabled` so the default costs
        # one attribute read
        self._timeline: cb_timeline.Timeline | None = None
        # last-seen tensor-maintenance wave counts per profile: the
        # backend keeps cumulative tallies, the Prometheus counter is
        # inc-only, so expose time applies deltas
        self._maint_seen: dict = {}
        self._next_start_node_index = 0
        self._threads: list[threading.Thread] = []
        self._wire_event_handlers()

    def configure_tracing(self, provider) -> None:
        """Attach a component_base.tracing.TracerProvider: each sampled
        batch gets one root span ("schedule_batch") with queue/flatten/
        H2D/filter/score/solve/D2H/bind children — the device-side ones
        come from ops/backend.py via the thread-local current span, and
        remote worker spans parent in through the propagated traceparent
        (ops/remote.py).  Pass None to detach."""
        self.tracer_provider = provider
        self._tracer = (provider.tracer("scheduler")
                        if provider is not None else None)

    def configure_overload(self, policy) -> None:
        """Attach a config.OverloadPolicy: bounded admission on the queue,
        AIMD wave sizing, the escape-storm breaker and the stuck-wave
        watchdog.  The policy ships enabled by default with
        ``engagement: auto`` — the layers are built here but only BITE
        while the hysteresis controller is engaged; ``always`` is the
        legacy always-on behaviour; pass None (or ``engagement: off``)
        to detach everything."""
        self.overload_policy = policy
        if policy is None or not policy.enabled:
            self._detach_engagement()
            self.queue.set_overload_policy(0)
            self.queue.set_overload_engaged(True)
            self._wave_tuner = None
            self._escape_breaker = None
            return
        self.queue.set_overload_policy(policy.queue_cap,
                                       policy.shed_protect_priority,
                                       policy.shed_protect_age)
        batch_profile = next((p for p in self.profiles.values()
                              if p.batch_backend is not None), None)
        wave_cap = batch_profile.batch_size if batch_profile else 256
        old_tuner = self._wave_tuner
        self._wave_tuner = (
            _WaveTuner(wave_cap, policy.slo_p99_ms / 1e3, policy.wave_min,
                       policy.wave_increase, policy.wave_decrease)
            if policy.slo_p99_ms > 0 else None)
        if old_tuner is not None and self._wave_tuner is not None:
            # hot-reload mid-incident: keep the AIMD position (a reload
            # must not blow a ratcheted-down wave back to full size),
            # re-clamped against the possibly-new batch-size ceiling
            self._wave_tuner._wave = old_tuner.current()
            self._wave_tuner.reclamp(wave_cap)
        # monotonic now_fn is the contract here: probe_due and the queue's
        # shed-age exemption must shrug off NTP wall-clock steps
        self._escape_breaker = (
            _OverloadBreaker(policy.breaker_threshold,
                             policy.breaker_probe_interval,
                             now_fn=time.monotonic)
            if policy.escape_rate_threshold > 0 else None)
        if policy.engagement == "auto":
            if self._engagement is not None:
                # SIGHUP reload mid-incident: swap knobs, keep the state
                self._engagement.reconfigure(policy)
            else:
                from ..component_base.profiling import SLOTracker
                # own tracker: arming must not depend on the profiling
                # stanza being configured
                self._engagement = _EngagementController(
                    policy,
                    SLOTracker(target_ms=policy.slo_p99_ms or 250.0,
                               objective=0.99))
            self.queue.set_overload_engaged(self._engagement.engaged)
        else:  # "always": legacy semantics, protections bite from wave 0
            self._detach_engagement()
            self.queue.set_overload_engaged(True)

    def _detach_engagement(self) -> None:
        if self._engagement is not None:
            self._apply_engagement_edges(self._engagement.detach())
            self._engagement = None

    def _apply_engagement_edges(
            self, edges: list[tuple[str, str, str]]) -> None:
        """Count each state-machine edge and apply its side effects.
        Only the scheduling-loop thread (and configure/reload, which run
        before/between loops) calls this, so the counter sees a single
        writer; the engaged gauge itself is refreshed at expose time."""
        if not edges:
            return
        eng = self._engagement
        for frm, to, reason in edges:
            self.metrics.prom.overload_transition_total.inc(1.0, frm, to,
                                                            reason)
            logger.info("overload engagement %s -> %s (%s)", frm, to, reason)
            if to == "engaged" and frm in ("arming", "disengaged"):
                # engage edge: the cap starts biting NOW — shed any
                # backlog already over it, and restart AIMD from the top
                # so the tuner reacts to live latency, not stale history
                if self._wave_tuner is not None:
                    self._wave_tuner.reset()
                self.queue.set_overload_engaged(True)
                self.queue.enforce_cap()
        if eng is not None:
            self.queue.set_overload_engaged(eng.engaged)

    @property
    def overload_engagement(self) -> str:
        """Engagement posture for /readyz and tests: the controller state
        when auto, else "always" (legacy always-on) or "off"."""
        if self._engagement is not None:
            return self._engagement.state
        pol = self.overload_policy
        if pol is not None and pol.enabled:
            return "always"
        return "off"

    def configure_scaleout(self, policy_or_coordinator) -> None:
        """Attach the horizontal scale-out layer (scaleout.py): ownership
        filters on the informer hot path, the lease tick in the
        scheduling loop, and the bind-side write fence.  Accepts a
        config.ScaleOutPolicy or a prebuilt ScaleOutCoordinator (tests
        and the bench harness inject one with a controlled clock).
        Pass None to detach."""
        from .scaleout import ScaleOutCoordinator
        so = policy_or_coordinator
        if so is not None and not isinstance(so, ScaleOutCoordinator):
            so = ScaleOutCoordinator(so) if so.enabled else None
        self.scaleout = so

    def configure_profiling(self, profiler, slo=None,
                            census: bool = False,
                            timeline=None) -> None:
        """Attach the performance observatory (component_base/profiling):
        `profiler` is a HostProfiler (started by the caller — usually
        scheduler_from_config off the profiling: stanza) whose per-stage
        host seconds drain into scheduler_host_stage_seconds at expose
        time; `slo` is an SLOTracker fed submit->bind latencies at the
        bind-commit tail, publishing rolling p50/p95/p99 + burn-rate
        gauges; `census=True` arms run_device_census() so the harness
        runs it once after backend warmup; `timeline` is a
        component_base.timeline.Timeline (usually the armed
        default_timeline) whose stage intervals the pipeline records and
        whose union-derived gauges expose_metrics refreshes.  Pass
        (None, None) to detach."""
        self._profiler = profiler
        self._slo = slo
        self._census_wanted = bool(census)
        self._timeline = timeline

    # stanzas reload_config can apply to a running scheduler; everything
    # else in a KubeSchedulerConfiguration (plugin pipelines, scaleOut
    # identity, extenders, remoteSeam deadlines, parallelism, queue
    # backoff) is wired at construction time and needs a process restart
    DYNAMIC_STANZAS = ("overload", "tracing", "profiling", "backend")

    def reload_config(self, source) -> dict:
        """Config hot-reload (SIGHUP / supervisor RPC): re-parse `source`
        (path, YAML text or dict) and apply the dynamic stanzas to the
        live scheduler.  Validation is all-or-nothing and happens before
        anything is touched — a ConfigError propagates to the caller and
        the old config stays live in full.  Returns {"applied": [...],
        "restart_only": [...]} naming the dynamic stanzas installed and
        any requested changes that need a restart (backend kind swap)."""
        from ..component_base import profiling, tracing
        from .config import load_config
        try:
            cfg = load_config(source)
        except Exception:
            self.metrics.prom.config_reload_total.inc(1.0, "rejected")
            raise
        restart_only: list[str] = []
        applied = ["overload", "tracing", "profiling"]
        # backend knobs land FIRST: the overload AIMD tuner clamps to the
        # live profile batch size, so a reload that shrinks batchSize must
        # apply it before configure_overload re-clamps the tuner —
        # otherwise the AIMD ceiling stays above the new wave cap until
        # restart.  A backend KIND swap means a different compiled kernel
        # + device residency — that is a restart, not a reload.
        if cfg.backend.kind != self.backend_policy.kind:
            restart_only.append("backend.kind")
        if cfg.backend.batch_size > 0:
            for profile in self.profiles.values():
                if profile.batch_backend is not None:
                    profile.batch_size = cfg.backend.batch_size
                    applied.append("backend.batchSize")
                    break
        self.configure_overload(cfg.overload if cfg.overload.enabled
                                else None)
        if cfg.tracing.enabled:
            tracing.default_tracer_provider.configure(
                sampling_rate_per_million=(
                    cfg.tracing.sampling_rate_per_million),
                max_spans=cfg.tracing.max_spans,
                max_traces=cfg.tracing.max_traces)
            self.configure_tracing(tracing.default_tracer_provider)
        else:
            self.configure_tracing(None)
        timeline = None
        if cfg.profiling.timeline:
            timeline = cb_timeline.default_timeline
            timeline.configure(enabled=True,
                               ring=cfg.profiling.timeline_ring)
        elif self._timeline is cb_timeline.default_timeline:
            cb_timeline.default_timeline.configure(enabled=False)
        if (cfg.profiling.enabled or cfg.profiling.census
                or cfg.profiling.timeline):
            profiler = None
            if cfg.profiling.enabled:
                profiler = profiling.default_host_profiler
                profiler.interval = cfg.profiling.sample_interval_ms / 1e3
                profiler.max_stacks = cfg.profiling.max_stacks
                profiler.start()
            elif (self._profiler is profiling.default_host_profiler
                    and self._profiler is not None):
                self._profiler.stop()
            slo = profiling.SLOTracker(
                target_ms=cfg.profiling.slo_target_ms,
                objective=cfg.profiling.slo_objective,
                windows=cfg.profiling.burn_windows_s)
            self.configure_profiling(profiler, slo,
                                     census=cfg.profiling.census,
                                     timeline=timeline)
        else:
            if (self._profiler is not None
                    and self._profiler is profiling.default_host_profiler):
                self._profiler.stop()
            self.configure_profiling(None, None)
        # pipeline depth applies live: raising it lets the next cycle
        # dispatch ahead; lowering it drains excess in-flight waves on
        # the next schedule_step (the trim loop retires oldest-first) —
        # nothing is cancelled
        depth = max(1, cfg.backend.pipeline_depth)
        if depth != self.pipeline_depth:
            self.pipeline_depth = depth
            applied.append("backend.pipeline")
        self.backend_policy = dataclasses.replace(
            cfg.backend, kind=self.backend_policy.kind)
        self.metrics.prom.config_reload_total.inc(1.0, "applied")
        return {"applied": applied, "restart_only": restart_only}

    def run_device_census(self) -> dict:
        """In-band device cost census: ask the batch backend to lower
        its compiled step variants and commit the collective/flops/HBM
        numbers as gauges (the ROADMAP \"collective bytes/wave\" criterion
        as a metric, not a script run).  Gated: only called when the
        profiling: stanza set census=true, and costs an AOT compile per
        variant, so the harness runs it right after backend warmup."""
        if not self._census_wanted:
            return {}
        from ..component_base.profiling import collective_bytes_by_op
        m = self.metrics.prom
        census_all: dict = {}
        for profile in self.profiles.values():
            backend = profile.batch_backend
            census_fn = getattr(backend, "device_census", None)
            if backend is None or census_fn is None:
                continue
            kind = getattr(backend, "census_kind",
                           type(backend).__name__)
            census = census_fn()
            census_all[kind] = census
            for variant, rec in census.items():
                label = f"{kind}-{variant}"
                per_wave, per_call = collective_bytes_by_op(rec)
                for op, v in per_wave.items():
                    m.tpu_wave_collective_bytes.set(float(v), op, label)
                for op, v in per_call.items():
                    m.tpu_step_collective_bytes.set(float(v), op, label)
                cost = rec.get("cost") or {}
                if cost:
                    m.tpu_wave_flops.set(cost.get("flops", 0.0),
                                         kind, variant)
                    m.tpu_step_hbm_bytes.set(cost.get("bytes_accessed", 0.0),
                                             kind, variant)
        self._census = census_all
        return census_all

    def expose_metrics(self) -> str:
        """Refresh pull-time gauges (pending_pods, cache_size) and return
        the Prometheus exposition text for this scheduler's registry."""
        for queue, n in self.queue.stats().items():
            self.metrics.prom.pending_pods.set(n, queue)
        for typ, n in self.cache.stats().items():
            self.metrics.prom.cache_size.set(n, typ)
        # remote-seam resilience counters live on the backend (retries,
        # resyncs, failovers, breaker state); snapshot them into gauges at
        # pull time — the cheap direction for a hot dispatch path
        for profile_name, profile in self.profiles.items():
            backend = profile.batch_backend
            if backend is None:
                continue
            snap_fn = getattr(backend, "seam_snapshot", None)
            stats = (snap_fn() if snap_fn is not None
                     else getattr(backend, "seam_stats", None))
            if stats:
                for counter, v in stats.items():
                    self.metrics.prom.tpu_seam_state.set(float(v), counter)
            breaker_fn = getattr(backend, "breaker_state", None)
            if breaker_fn is not None:
                for rung, v in breaker_fn().items():
                    self.metrics.prom.tpu_seam_breaker.set(float(v), rung)
            # incremental-flatten maintenance: per-wave patched-vs-
            # reflattened deltas into the counter, allocator pressure
            # into the gauges
            maint_fn = getattr(backend, "maintenance_snapshot", None)
            if maint_fn is not None:
                maint = maint_fn()
                seen = self._maint_seen.setdefault(profile_name, {})
                for mode, key in (("patched", "waves_patched"),
                                  ("reflattened", "waves_reflattened")):
                    now = float(maint.get(key, 0))
                    delta = now - seen.get(key, 0.0)
                    if delta > 0:
                        self.metrics.prom.tpu_tensor_waves.inc(delta, mode)
                    seen[key] = now
                self.metrics.prom.tpu_tensor_occupancy.set(
                    float(maint.get("row_occupancy", 0.0)))
                self.metrics.prom.tpu_tensor_tombstones.set(
                    float(maint.get("tombstone_rows", 0)))
        # overload-protection tallies: the queue accumulates sheds under
        # its own lock; the informers count relists — both drained here
        # (Counter is inc-only, the scheduler is the only writer)
        for (reason, band), n in self.queue.drain_shed_total().items():
            self.metrics.prom.queue_shed_total.inc(float(n), reason, band)
        drain_relists = getattr(self.informer_factory,
                                "drain_relist_total", None)
        if drain_relists is not None:
            for (resource, reason), n in drain_relists().items():
                self.metrics.prom.informer_relist_total.inc(
                    float(n), resource, reason)
        if self._wave_tuner is not None:
            self.metrics.prom.overload_wave_size.set(
                float(self._wave_tuner.current()))
        if self._escape_breaker is not None:
            self.metrics.prom.overload_breaker_open.set(
                1.0 if self._escape_breaker.is_open else 0.0)
        # engagement gauge refreshed at expose time (the counter tracks
        # edges; the gauge is derived state): 1 while the protections
        # bite — engaged/cooling under auto, or legacy always-on
        posture = self.overload_engagement
        self.metrics.prom.overload_engaged.set(
            1.0 if posture in ("engaged", "cooling", "always") else 0.0)
        # performance observatory: drain per-stage host seconds from the
        # sampling profiler (inc-only deltas) and refresh the SLO
        # rolling-window quantile + burn-rate gauges
        if self._profiler is not None:
            for stage, secs in self._profiler.drain_stage_seconds().items():
                self.metrics.prom.host_stage_seconds.inc(secs, stage)
        if self._slo is not None:
            q = self._slo.quantiles()
            for quant in ("p50", "p95", "p99"):
                self.metrics.prom.slo_latency_ms.set(q[f"{quant}_ms"], quant)
            for window, burn in self._slo.burn_rates().items():
                self.metrics.prom.slo_burn_rate.set(burn, window)
        # wave timeline: pull worker-side intervals over the seam (the
        # remote backend forwards its ring with epoch/seq framing and
        # wall-anchored clocks, so ingest is plain concatenation), then
        # refresh the union-derived gauges at pull time
        tl = self._timeline
        if tl is not None and tl.enabled:
            for profile in self.profiles.values():
                drain_fn = getattr(profile.batch_backend,
                                   "drain_worker_timeline", None)
                if drain_fn is not None:
                    try:
                        tl.ingest(drain_fn())
                    except Exception:  # noqa: BLE001 - seam may be down
                        pass
            summary = tl.snapshot_summary()
            idle = summary.get("device_idle_share")
            if idle is not None:
                self.metrics.prom.wave_device_idle_share.set(float(idle))
            for stage_name, ratio in summary.get("overlap", {}).items():
                self.metrics.prom.stage_overlap_ratio.set(
                    float(ratio), stage_name)
        return self.metrics.expose()

    # -- event handlers (eventhandlers.go:249) ---------------------------

    def _wire_event_handlers(self) -> None:
        pods = self.informer_factory.informer(PODS)
        nodes = self.informer_factory.informer(NODES)
        if hasattr(pods, "add_bulk_event_handler"):
            pods.add_bulk_event_handler(self._on_pod_events)
        else:  # pragma: no cover - non-bulk informer stand-ins
            pods.add_event_handler(self._on_pod_event)
        if hasattr(nodes, "add_bulk_event_handler"):
            nodes.add_bulk_event_handler(self._on_node_events)
        else:  # pragma: no cover - non-bulk informer stand-ins
            nodes.add_event_handler(self._on_node_event)
        # namespace label events feed the batch backends' namespaceSelector
        # resolution caches (ops/flatten.py); rare enough that the plain
        # per-event handler suffices
        namespaces = self.informer_factory.informer(NAMESPACES)
        namespaces.add_event_handler(self._on_namespace_event)
        # PDB events feed the backends' victim PDB-coverage bits (batched
        # preemption); same rare-event shape as namespaces
        pdbs = self.informer_factory.informer(PDBS)
        pdbs.add_event_handler(self._on_pdb_event)

    def _on_namespace_event(self, type_: str, ns: Obj,
                            old: Obj | None) -> None:
        for profile in self.profiles.values():
            fn = getattr(profile.batch_backend, "note_namespace_event", None)
            if fn is not None:
                fn(type_, ns, old)

    def _on_pdb_event(self, type_: str, pdb: Obj,
                      old: Obj | None) -> None:
        for profile in self.profiles.values():
            fn = getattr(profile.batch_backend, "note_pdb_event", None)
            if fn is not None:
                fn(type_, pdb, old)

    def _on_node_events(self, triples: list) -> None:
        """Bulk node-event handler: a registration flood (100k createNodes)
        lands as ADDED bursts — absorb each burst with ONE cache lock
        round and ONE queue move instead of one per node."""
        t_drain = time.monotonic()
        adds: list[Obj] = []

        def flush() -> None:
            if adds:
                self.cache.add_nodes(adds)
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent("Node", "Add"))
                adds.clear()

        ADDED = kv.ADDED
        so = self.scaleout
        for t, node, old in triples:
            if so is not None and not so.owns_node(meta.name(node)):
                continue  # a peer instance's node-pool ring slice
            if t == ADDED:
                adds.append(node)
            else:
                flush()  # preserve same-node event ordering
                self._on_node_event(t, node, old)
        flush()
        tl = self._timeline
        if tl is not None and tl.enabled:
            tl.record("event-drain", t_drain, time.monotonic())

    def _on_pod_events(self, triples: list) -> None:
        """Bulk pod-event handler: the two burst-dominant cases — new
        unbound pods entering the queue, and this scheduler's own binds
        coming back as watch confirmations — are applied with one lock
        round per burst instead of one per pod.  Everything else falls
        through to the per-event path, with flush barriers so same-pod
        event order is preserved exactly."""
        t_drain = time.monotonic()
        queue_adds: list[Obj] = []
        confirms: list[Obj] = []
        peer_bound: list[Obj] = []  # bound on a node a peer instance owns

        def flush() -> None:
            if queue_adds:
                self.queue.add_many(queue_adds)
                queue_adds.clear()
            if confirms:
                self.cache.confirm_or_add_pods(confirms)
                self.queue.delete_many(confirms)
                # one coalesced move: move_all processes every parked pod
                # per call, so N per-pod calls and 1 call are equivalent
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent("AssignedPod", "Add"))
                confirms.clear()
            if peer_bound:
                # a peer committed these pods; they are not our cache's
                # business, but drop any copy still queued here (a lost
                # optimistic-bind race leaves the pod in our backoff tier
                # until its peer bind confirmation streams in)
                self.queue.delete_many(peer_bound)
                peer_bound.clear()

        ADDED, MODIFIED = kv.ADDED, kv.MODIFIED
        profiles = self.profiles
        so = self.scaleout
        for t, pod, old in triples:
            spec = pod.get("spec") or {}
            bound = bool(spec.get("nodeName"))
            if t == ADDED and not bound:
                if spec.get("schedulerName", "default-scheduler") in profiles:
                    if so is not None:
                        md = pod.get("metadata") or {}
                        if not so.owns_pod(md.get("namespace", ""),
                                           md.get("name", "")):
                            continue  # a peer instance's partition
                    queue_adds.append(pod)
            elif (t == MODIFIED and bound
                    and old is not None
                    and not (old.get("spec") or {}).get("nodeName")
                    and pod["metadata"].get("deletionTimestamp") is None
                    and (pod.get("status") or {}).get("phase")
                    not in ("Succeeded", "Failed")):
                if so is not None and not so.owns_node(spec["nodeName"]):
                    peer_bound.append(pod)
                else:
                    confirms.append(pod)
            else:
                flush()
                self._on_pod_event(t, pod, old)
        flush()
        tl = self._timeline
        if tl is not None and tl.enabled:
            tl.record("event-drain", t_drain, time.monotonic())

    def _responsible_for(self, pod: Obj) -> bool:
        name = (pod.get("spec") or {}).get("schedulerName", "default-scheduler")
        return name in self.profiles

    def _scaleout_owns(self, pod: Obj) -> bool:
        """Ownership of an UNBOUND pod under the scale-out partition
        (always true single-instance)."""
        so = self.scaleout
        if so is None:
            return True
        md = pod.get("metadata") or {}
        return so.owns_pod(md.get("namespace", ""), md.get("name", ""))

    def _scaleout_tracks(self, node_name: str | None) -> bool:
        """Whether this instance's cache tracks the given node (bound-pod
        events on a peer's node slice are not our accounting)."""
        so = self.scaleout
        return so is None or not node_name or so.owns_node(node_name)

    def _on_pod_event(self, type_: str, pod: Obj, old: Obj | None) -> None:
        bound = bool(meta.pod_node_name(pod))
        tracked = self._scaleout_tracks(
            meta.pod_node_name(pod) or (old and meta.pod_node_name(old)))
        if type_ == kv.ADDED:
            if bound:
                if tracked:
                    self.cache.add_pod(pod)
                    self.queue.assigned_pod_added(pod)
            elif self._responsible_for(pod) and self._scaleout_owns(pod):
                self.queue.add(pod)
        elif type_ == kv.MODIFIED:
            was_bound = bool(old and meta.pod_node_name(old))
            if bound or was_bound:
                if not tracked:
                    # a peer's partition: just make sure no stale copy of
                    # the pod is still queued here (lost bind race)
                    self.queue.delete(pod)
                    return
                if was_bound:
                    self.cache.update_pod(old, pod)
                else:
                    self.cache.add_pod(pod)
                    self.queue.delete(pod)
                    self.queue.assigned_pod_added(pod)
                if meta.pod_is_terminal(pod):
                    # terminal pods free resources
                    self.cache.remove_pod(pod)
                    self.queue.move_all_to_active_or_backoff(
                        ClusterEvent("AssignedPod", "Delete"))
            elif self._responsible_for(pod) and self._scaleout_owns(pod):
                if old is not None:
                    self.queue.update(old, pod)
                else:
                    self.queue.add(pod)
        elif type_ == kv.DELETED:
            if bound:
                if tracked:
                    self.cache.remove_pod(pod)
                    self.queue.move_all_to_active_or_backoff(
                        ClusterEvent("AssignedPod", "Delete"))
            else:
                self.queue.delete(pod)

    def _on_node_event(self, type_: str, node: Obj, old: Obj | None) -> None:
        if self.scaleout is not None \
                and not self.scaleout.owns_node(meta.name(node)):
            return  # a peer instance's node-pool ring slice
        if type_ == kv.ADDED:
            self.cache.add_node(node)
            self.queue.move_all_to_active_or_backoff(ClusterEvent("Node", "Add"))
        elif type_ == kv.MODIFIED:
            self.cache.update_node(node)
            self.queue.move_all_to_active_or_backoff(ClusterEvent("Node", "Update"))
        elif type_ == kv.DELETED:
            self.cache.remove_node(node)
            self.queue.move_all_to_active_or_backoff(ClusterEvent("Node", "Delete"))
        else:
            return
        # incremental flatten: patch the event's row into the resident
        # device tensors NOW, off the dispatch path, instead of leaving it
        # for the next wave's snapshot drain (bulk ADDED floods stay on
        # the drain path — _encode_fresh_bulk absorbs those cheaper)
        name = meta.name(node)
        view = self.cache.flatten_view()
        for profile in self.profiles.values():
            fn = getattr(profile.batch_backend, "note_node_event", None)
            if fn is not None:
                fn(type_, name, view)

    # -- run loops (scheduler.go:341) ------------------------------------

    def run(self) -> None:
        """Start background scheduling (returns immediately)."""
        self.queue.run()
        t = threading.Thread(target=self._loop, name="sched-loop", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.close()
        for t in self._threads:
            t.join(timeout=2.0)
        if not any(t.is_alive() for t in self._threads):
            self._flush_pending()  # loop thread gone: safe to drain here
        self._binder_worker.stop()  # runs queued commits inline
        self._binder_pool.shutdown(wait=False)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.schedule_step(timeout=0.5)
        self._flush_pending()
        deferred, self._deferred = self._deferred, []
        for q in deferred:  # don't strand popped pods on shutdown
            self.schedule_one(q)

    def schedule_step(self, timeout: float | None = None) -> int:
        """One scheduling iteration; returns number of pods processed.
        Batch mode if any profile has a batch backend; else per-pod.

        Batch mode is a depth-1 pipeline: batch k+1 is dispatched to the
        device (async) BEFORE batch k's assume/bind tail runs on the host, so
        the device round trip (~70 ms on a tunneled chip) hides behind host
        work.  Safety: the backend refuses to pipeline (FLUSH_FIRST) whenever
        this would risk clobbering in-flight device accounting, and per-pod
        scheduling (other profiles, extender pods, tensor-escape pods) is
        deferred to moments when nothing is in flight — otherwise the
        per-pod Filter could double-place onto capacity an in-flight batch
        already claimed.  While a batch is in flight the queue pop is
        non-blocking so an emptying queue flushes the pipeline immediately
        instead of parking the last batch behind the pop timeout."""
        if self.scaleout is not None and self.scaleout.tick(self.client):
            # membership changed (an instance died or rejoined): recompute
            # this instance's partition before scheduling anything more
            self._scaleout_resync()
        batch_profile = next((p for p in self.profiles.values()
                              if p.batch_backend is not None), None)
        if batch_profile is not None:
            if not self._pending:
                t = timeout
            elif len(self._pending) < self.pipeline_depth:
                # room in the pipeline: wait at most the admission
                # interval so a trickle of pods still dispatches promptly
                t = self.admission_interval
            else:
                t = 0.0
            # AIMD wave sizing (overload: sloP99Ms): the tuner shrinks the
            # wave when the last waves blew the latency SLO and grows it
            # back while under — static batch_size while disengaged or
            # untuned (engagement gating: disengaged pipelines dispatch
            # full waves at zero overload cost)
            eng = self._engagement
            wave = (self._wave_tuner.current()
                    if self._wave_tuner is not None
                    and (eng is None or eng.engaged)
                    else batch_profile.batch_size)
            t_pop0 = time.monotonic()
            batch = self.queue.pop_batch(wave, t)
            t_pop1 = time.monotonic()
            mine: list[QueuedPodInfo] = []
            perpod: list[QueuedPodInfo] = []
            if batch:
                for q in batch:
                    (mine if self._profile_for(q.pod) is batch_profile
                     else perpod).append(q)
            if not batch and not self._pending and not self._deferred:
                if eng is not None:
                    # idle tick: dwell clocks must keep running so an
                    # engaged/cooling machine can stand down after the
                    # storm drains, even with no waves retiring
                    self._apply_engagement_edges(
                        eng.on_wave(0, batch_profile.batch_size))
                # truly idle: let the backend absorb node churn into its
                # host tensors now, so a later dispatch doesn't pay the
                # whole re-encode (at 100k nodes the creation flood costs
                # ~15s) inside a scheduling cycle
                prefetch = getattr(batch_profile.batch_backend,
                                   "prefetch", None)
                if prefetch is not None:
                    prefetch(self.cache.flatten_view())
                return 0
            if perpod or self._deferred:
                # per-pod scheduling needs a cache with no in-flight claims
                self._flush_pending()
                deferred, self._deferred = self._deferred, []
                for q in deferred + perpod:
                    self.schedule_one(q)
            if mine:
                pending = self._dispatch_batch(batch_profile, mine,
                                               pop_window=(t_pop0, t_pop1))
                if pending is not None:
                    self._pending.append(pending)
                while len(self._pending) > self.pipeline_depth:
                    self._finish_batch(*self._pending.pop(0))
            elif self._pending:
                # queue momentarily empty: retire the oldest in-flight
                # batch (blocks on its device result; pods accumulate in
                # the queue meanwhile — the pipeline's natural pacing)
                self._finish_batch(*self._pending.pop(0))
            # eager retirement (oldest-first, order preserved for the
            # backend's resident-state chain): a batch whose device result
            # has had time to land is retired now instead of riding the
            # pipeline to the depth cap — cutting its pods' latency by the
            # remaining pipeline residency.  Readiness is TIME-gated on an
            # adaptive flight estimate rather than jax.Array.is_ready():
            # on the tunneled device is_ready() is unreliable (observed
            # lying True before the data exists) and polling it from this
            # loop correlated with multi-second transfer stalls.  A low
            # estimate just means _finish_batch briefly blocks on the
            # pull; the estimate then adapts upward.
            # (the estimate itself adapts inside _finish_batch, from
            # every retirement path)
            now = time.monotonic()
            while self._pending and (now - self._pending[0][4]
                                     >= self._flight_est):
                self._finish_batch(*self._pending.pop(0))
                now = time.monotonic()
            return len(batch)
        qpi = self.queue.pop(timeout)
        if qpi is None:
            return 0
        self.schedule_one(qpi)
        return 1

    def _flush_pending(self) -> None:
        """Resolve every in-flight batch (blocks on device), oldest first,
        and run their tails."""
        while self._pending:
            self._finish_batch(*self._pending.pop(0))

    def _scaleout_resync(self) -> None:
        """Recompute this instance's partition after a membership change:
        absorb newly-owned nodes — and the bound pods on them, whose
        resources must be accounted before anything else is placed
        there — admit newly-owned pending pods, and shed what a live
        peer owns again.  Everything derives from the shared store and
        the shared lease table, so every survivor converges on the same
        ownership map with no coordination round."""
        so = self.scaleout
        nodes, _ = self.client.list(NODES)
        have, _pods, _assumed = self.cache.comparison_snapshot()
        owned = {meta.name(n) for n in nodes if so.owns_node(meta.name(n))}
        absorbed = [n for n in nodes if meta.name(n) in owned
                    and meta.name(n) not in have]
        if absorbed:
            self.cache.add_nodes(absorbed)
        for n in nodes:
            nm = meta.name(n)
            if nm in have and nm not in owned:
                self.cache.remove_node(n)
        pods, _ = self.client.list(PODS)
        confirm: list[Obj] = []
        for p in pods:
            node = meta.pod_node_name(p)
            if node:
                if node in owned and not meta.pod_is_terminal(p):
                    confirm.append(p)  # idempotent (confirm_or_add_pods)
                continue
            if not self._responsible_for(p):
                continue
            md = p.get("metadata") or {}
            if so.owns_pod(md.get("namespace", ""), md.get("name", "")):
                if not self.queue.has(p):
                    self.queue.add(p)  # a dead peer's pending pod: ours now
            else:
                self.queue.delete(p)
        if confirm:
            self.cache.confirm_or_add_pods(confirm)
        self.queue.move_all_to_active_or_backoff(ClusterEvent("Node", "Add"))
        logger.info("scale-out resync: instance %d live=%s owns %d/%d nodes",
                    so.index, so.live, len(owned), len(nodes))

    def _profile_for(self, pod: Obj) -> Profile | None:
        name = (pod.get("spec") or {}).get("schedulerName", "default-scheduler")
        return self.profiles.get(name)

    # -- per-pod pipeline (schedule_one.go:63) ---------------------------

    def schedule_one(self, qpi: QueuedPodInfo) -> None:
        pod = qpi.pod
        profile = self._profile_for(pod)
        if profile is None:
            logger.error("no profile for pod %s", qpi.key)
            return
        fw = profile.framework
        if self._skip_schedule(pod):
            return
        start = time.monotonic()
        state = CycleState()
        cycle = self.queue.scheduling_cycle()
        try:
            node_name = self._scheduling_cycle(fw, profile, state, qpi)
        except FitError as fe:
            # PostFilter: preemption (schedule_one.go:128 RunPostFilterPlugins)
            nominated = None
            if fw.post_filter:
                nominated, _ps = fw.run_post_filter_plugins(
                    state, qpi.pod_info, fe.diagnosis.node_to_status)
                if nominated:
                    self.queue.nominator.add_nominated_pod(qpi.pod_info, nominated)
            self._handle_failure(fw, qpi, Status(UNSCHEDULABLE, fe.message()),
                                 cycle, fe.diagnosis.unschedulable_plugins, start)
            return
        except Exception as e:  # pragma: no cover
            logger.exception("scheduling cycle error for %s", qpi.key)
            self._handle_failure(fw, qpi, Status(ERROR, str(e)), cycle, set(), start)
            return
        if node_name is None:
            return  # failure already handled (reserve/permit path)
        # async binding cycle (schedule_one.go:100)
        self._submit_binding(self._binding_cycle, fw, state, qpi,
                             node_name, cycle, start)

    def _skip_schedule(self, pod: Obj) -> bool:
        # schedule_one.go skipPodSchedule: deleted or assumed-and-updated
        if meta.deletion_timestamp(pod) is not None:
            return True
        if meta.pod_node_name(pod):
            return True
        return False

    def _scheduling_cycle(self, fw: Framework, profile: Profile,
                          state: CycleState, qpi: QueuedPodInfo) -> str | None:
        """Everything up to (and including) Reserve+Permit. Returns the chosen
        node or raises FitError; returns None if failure was handled inline."""
        pod_info = qpi.pod_info
        snapshot = Snapshot() if not hasattr(self, "_snapshot") else self._snapshot
        self._snapshot = self.cache.update_snapshot(snapshot)
        node_name = self._schedule_pod(fw, profile, state, pod_info, self._snapshot)

        # assume (schedule_one.go:802): optimistic cache commit
        assumed = meta.deep_copy(pod_info.pod)
        assumed["spec"]["nodeName"] = node_name
        self.cache.assume_pod(assumed)

        s = fw.run_reserve_plugins(state, pod_info, node_name)
        if not is_success(s):
            self.cache.forget_pod(assumed)
            self._handle_failure(fw, qpi, s, self.queue.scheduling_cycle(),
                                 {s.plugin} if s.plugin else set(), time.monotonic())
            return None
        s = fw.run_permit_plugins(state, pod_info, node_name)
        if s is not None and s.is_wait():
            return node_name  # binding cycle will WaitOnPermit
        if not is_success(s):
            fw.run_unreserve_plugins(state, pod_info, node_name)
            self.cache.forget_pod(assumed)
            self._handle_failure(fw, qpi, s, self.queue.scheduling_cycle(),
                                 {s.plugin} if s.plugin else set(), time.monotonic())
            return None
        return node_name

    def _schedule_pod(self, fw: Framework, profile: Profile, state: CycleState,
                      pod_info: PodInfo, snapshot: Snapshot) -> str:
        """schedulePod (schedule_one.go:372): PreFilter -> Filter -> PreScore
        -> Score -> selectHost. Raises FitError when nothing fits."""
        if len(snapshot) == 0:
            raise FitError(pod_info.pod, 0, Diagnosis(pre_filter_msg="no nodes available"))
        feasible, diagnosis = self._find_nodes_that_fit(fw, profile, state,
                                                        pod_info, snapshot)
        if feasible and self.extenders:
            feasible = self._find_nodes_that_pass_extenders(
                pod_info.pod, feasible, diagnosis)
        if not feasible:
            raise FitError(pod_info.pod, len(snapshot), diagnosis)
        if len(feasible) == 1:
            return feasible[0].name
        s = fw.run_pre_score_plugins(state, pod_info, feasible)
        if not is_success(s):
            raise RuntimeError(f"PreScore failed: {s.message()}")
        scores, s = fw.run_score_plugins(state, pod_info, feasible)
        if not is_success(s):
            raise RuntimeError(f"Score failed: {s.message()}")
        if self.extenders:
            self._add_extender_scores(pod_info.pod, feasible, scores)
        return self._select_host(scores)

    # -- extenders (schedule_one.go:613,733; extender.go) -----------------

    def _find_nodes_that_pass_extenders(self, pod: Obj,
                                        feasible: list[NodeInfo],
                                        diagnosis: Diagnosis) -> list[NodeInfo]:
        """findNodesThatPassExtenders: each interested extender filters in
        sequence; ignorable extender errors are skipped, others raise."""
        from .extender import ExtenderError
        for ext in self.extenders:
            if not feasible:
                break
            if not ext.is_interested(pod):
                continue
            try:
                feasible, failed, failed_unresolvable = ext.filter(pod, feasible)
            except ExtenderError as e:
                if ext.is_ignorable():
                    logger.warning("skipping ignorable extender %s: %s",
                                   ext.name(), e)
                    continue
                raise
            for name, msg in failed.items():
                diagnosis.node_to_status.setdefault(
                    name, Status(UNSCHEDULABLE, msg))
            for name, msg in failed_unresolvable.items():
                diagnosis.node_to_status[name] = Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE, msg)
        return feasible

    def _add_extender_scores(self, pod: Obj, feasible: list[NodeInfo],
                             scores: dict[str, int]) -> None:
        """prioritizeNodes extender fan-out (schedule_one.go:733): extender
        score × weight adds onto the plugin score sum; extender prioritize
        errors are never fatal."""
        from .extender import ExtenderError
        for ext in self.extenders:
            if not ext.is_interested(pod):
                continue
            try:
                ext_scores, weight = ext.prioritize(pod, feasible)
            except ExtenderError as e:
                logger.warning("extender %s prioritize failed: %s", ext.name(), e)
                continue
            for name, sc in ext_scores.items():
                if name in scores:
                    scores[name] += sc * weight

    def _extenders_bind(self, pod: Obj, node_name: str) -> bool:
        """schedule_one.go bind(): the first interested binder extender does
        the binding instead of the framework's Bind plugins."""
        from .extender import ExtenderError
        for ext in self.extenders:
            if ext.is_binder() and ext.is_interested(pod):
                try:
                    ext.bind(pod, node_name)
                    return True
                except ExtenderError as e:
                    raise RuntimeError(f"extender bind: {e}") from e
        return False

    def _find_nodes_that_fit(self, fw: Framework, profile: Profile,
                             state: CycleState, pod_info: PodInfo,
                             snapshot: Snapshot
                             ) -> tuple[list[NodeInfo], Diagnosis]:
        """findNodesThatFitPod (schedule_one.go:425) with adaptive sampling
        (:585) and round-robin start index (:541)."""
        diagnosis = Diagnosis()
        result, s = fw.run_pre_filter_plugins(state, pod_info, snapshot)
        if s is not None and not s.is_success():
            if s.is_rejected():
                diagnosis.pre_filter_msg = s.message()
                diagnosis.unschedulable_plugins.add(s.plugin)
                return [], diagnosis
            raise RuntimeError(f"PreFilter failed: {s.message()}")

        # nominated node gets first shot (schedule_one.go:437)
        if pod_info.nominated_node_name:
            ni = snapshot.get(pod_info.nominated_node_name)
            if ni is not None:
                st = fw.run_filter_plugins_with_nominated_pods(state, pod_info, ni)
                if is_success(st):
                    return [ni], diagnosis

        all_nodes = snapshot.list()
        if result is not None and not result.all_nodes():
            nodes = [snapshot.get(n) for n in result.node_names]
            nodes = [n for n in nodes if n is not None]
        else:
            nodes = all_nodes
        num_to_find = self._num_feasible_nodes_to_find(
            profile.percentage_of_nodes_to_score, len(nodes))

        feasible: list[NodeInfo] = []
        start = self._next_start_node_index % max(len(nodes), 1)
        checked = 0
        for i in range(len(nodes)):
            ni = nodes[(start + i) % len(nodes)]
            checked += 1
            st = fw.run_filter_plugins_with_nominated_pods(state, pod_info, ni)
            if is_success(st):
                feasible.append(ni)
                if len(feasible) >= num_to_find:
                    break
            else:
                diagnosis.node_to_status[ni.name] = st
                if st.plugin:
                    diagnosis.unschedulable_plugins.add(st.plugin)
        self._next_start_node_index = (start + checked) % max(len(nodes), 1)
        return feasible, diagnosis

    @staticmethod
    def _num_feasible_nodes_to_find(percentage: int, num_nodes: int) -> int:
        if num_nodes < MIN_FEASIBLE_NODES_TO_FIND:
            return num_nodes
        p = percentage
        if p <= 0:
            p = int(50 - num_nodes / 125)
            if p < MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND:
                p = MIN_FEASIBLE_NODES_PERCENTAGE_TO_FIND
        if p >= 100:
            return num_nodes
        return max(num_nodes * p // 100, MIN_FEASIBLE_NODES_TO_FIND)

    @staticmethod
    def _select_host(scores: dict[str, int]) -> str:
        """selectHost (schedule_one.go:777): max score, random tie-break via
        reservoir sampling. We take the first max (deterministic) — same
        contract, reproducible."""
        best, best_score = None, None
        for name, sc in scores.items():
            if best_score is None or sc > best_score:
                best, best_score = name, sc
        return best

    # -- binding cycle (schedule_one.go:223) -----------------------------

    def _binding_cycle(self, fw: Framework, state: CycleState,
                       qpi: QueuedPodInfo, node_name: str, cycle: int,
                       start: float) -> None:
        pod_info = qpi.pod_info
        assumed = meta.deep_copy(pod_info.pod)
        assumed["spec"]["nodeName"] = node_name
        if self.scaleout is not None and not self.scaleout.self_live:
            # write fence (lease lapsed or instance retired): committing
            # could double-bind against whichever peer absorbed our slice
            self._conflict_requeue(fw, [(state, qpi, node_name, assumed)],
                                   None, forced="fenced")
            return
        try:
            s = fw.wait_on_permit(pod_info)
            if not is_success(s):
                self._bind_failure(fw, state, qpi, assumed, node_name, s, cycle)
                return
            s = fw.run_pre_bind_plugins(state, pod_info, node_name)
            if not is_success(s):
                self._bind_failure(fw, state, qpi, assumed, node_name, s, cycle)
                return
            bound_by_extender = False
            if self.extenders:
                try:
                    bound_by_extender = self._extenders_bind(pod_info.pod,
                                                             node_name)
                except RuntimeError as e:
                    self._bind_failure(fw, state, qpi, assumed, node_name,
                                       Status(ERROR, str(e)), cycle)
                    return
            if not bound_by_extender:
                s = fw.run_bind_plugins(state, pod_info, node_name)
                if not is_success(s):
                    self._bind_failure(fw, state, qpi, assumed, node_name, s,
                                       cycle)
                    return
            self.cache.finish_binding(assumed)
            fw.run_post_bind_plugins(state, pod_info, node_name)
            now = time.monotonic()
            self.metrics.observe_attempt("scheduled", now - start,
                                         fw.profile_name)
            self.metrics.observe_e2e(
                [(now - qpi.initial_attempt_timestamp, qpi.attempts)])
            self.client.create_event(pod_info.pod, "Scheduled",
                                     f"Successfully assigned {qpi.key} to {node_name}")
        except kv.BindConflict:
            # a peer scheduler instance claimed the pod (or the node)
            # first: Forget + reclassify via the conflict taxonomy
            # instead of blaming the pod as a generic bind error
            self._conflict_requeue(fw, [(state, qpi, node_name, assumed)],
                                   None)
        except kv.FencedError:
            self._conflict_requeue(fw, [(state, qpi, node_name, assumed)],
                                   None, forced="fenced")
        except Exception as e:  # pragma: no cover
            logger.exception("binding cycle error for %s", qpi.key)
            self._bind_failure(fw, state, qpi, assumed, node_name,
                               Status(ERROR, str(e)), cycle)

    def _bind_failure(self, fw: Framework, state: CycleState, qpi: QueuedPodInfo,
                      assumed: Obj, node_name: str, s: Status, cycle: int) -> None:
        """schedule_one.go:229-258: Forget + unreserve + requeue + move event."""
        fw.run_unreserve_plugins(state, qpi.pod_info, node_name)
        try:
            self.cache.forget_pod(assumed)
        except ValueError:
            pass
        self.queue.move_all_to_active_or_backoff(ClusterEvent("AssignedPod", "Delete"))
        self._handle_failure(fw, qpi, s, cycle,
                             {s.plugin} if s.plugin else set(), time.monotonic())

    # -- failure handling (schedule_one.go:873) --------------------------

    def _handle_failure(self, fw: Framework, qpi: QueuedPodInfo, s: Status,
                        cycle: int, plugins: set[str], start: float) -> None:
        qpi.unschedulable_plugins = plugins
        result = "unschedulable" if s.code in (
            UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE) else "error"
        self.metrics.observe_attempt(result, time.monotonic() - start,
                                     fw.profile_name)
        # re-fetch: pod may have been updated/deleted meanwhile
        try:
            current = self.client.get(PODS, meta.namespace(qpi.pod), meta.name(qpi.pod))
        except kv.NotFoundError:
            return
        except (kv.StoreError, OSError):
            # apiserver unreachable (mid-handoff gap): requeue with the
            # pod we already have — the retry re-resolves against the
            # real state, and the store's compare-and-bind keeps
            # exactly-once even if the pod was bound elsewhere meanwhile
            current = qpi.pod
        if meta.pod_node_name(current):
            return  # got bound elsewhere
        qpi.pod_info.update(current)
        self.queue.add_unschedulable_if_not_present(qpi, cycle)
        try:
            self.client.create_event(qpi.pod, "FailedScheduling", s.message(),
                                     type_="Warning")
        except (kv.StoreError, OSError):
            pass  # events are best-effort; the requeue above already landed
        # patch status condition (schedule_one.go:918)
        try:
            def patch(p: Obj) -> Obj:
                conds = p.setdefault("status", {}).setdefault("conditions", [])
                conds[:] = [c for c in conds if c.get("type") != "PodScheduled"]
                conds.append({"type": "PodScheduled", "status": "False",
                              "reason": "Unschedulable", "message": s.message()})
                return p
            self.client.guaranteed_update(PODS, meta.namespace(qpi.pod),
                                          meta.name(qpi.pod), patch)
        except (kv.StoreError, OSError):
            pass

    def _batch_preempt(self, profile: Profile, fw: Framework,
                       failures: list[tuple[QueuedPodInfo, Status]],
                       cycle: int, start: float, span=None) -> None:
        """PostFilter for a batch's FitError pods, two device tiers:

        (1) preempt_batch — the FULL DryRunPreemption on device
        (ops/backend.preempt_batch -> models/preempt._preempt_dry_run):
        victim selection, reprieve pass, PDB violation counts and the
        pickOneNodeForPreemption tie-break all run as one fused call per
        chunk, and the host only resolves cross-pod conflicts and bulk-
        commits evictions + nominations.  (2) pods outside the batched
        kernel's exactness envelope (non-plain, nominated, kernel escape
        reasons) take the legacy tier: device top-k candidates
        (preempt_candidates) re-proved by the host Evaluator's exact
        dry-run, or the full host PostFilter when the device cannot
        group them — coverage always matches the per-pod path.

        Conflict resolution: winners commit in queue order (higher
        priority first — activeQ pop-order parity).  The wave itself
        resolves claim conflicts in preempt_batch (a later pod either
        proves an earlier winner's node closed and takes the next-best
        open one, or re-proves the claimed node host-side with the
        claims folded — bit-identical to the sequential Evaluator run
        in the same order), so the results here are claim-consistent:
        two winners naming the same node is a legal capacity share, and
        overlapping victim sets just dedup the eviction (a victim is
        deleted once).  Escaped pods take the legacy tier; everything
        requeues through _handle_failure and re-evaluates next wave
        against the persisted nominations."""
        plugin = next((p for p in fw.post_filter
                       if hasattr(p, "evaluator")
                       and hasattr(p, "persist_nomination")), None)
        backend = profile.batch_backend
        if plugin is None or not (hasattr(backend, "preempt_batch")
                                  or hasattr(backend, "preempt_candidates")):
            for qpi, st in failures:
                self._handle_failure(fw, qpi, st, cycle, set(), start)
            return
        snapshot = Snapshot() if not hasattr(self, "_snapshot") \
            else self._snapshot
        self._snapshot = snapshot = self.cache.update_snapshot(snapshot)
        ev = plugin.evaluator()
        # higher-priority preemptors go first (activeQ pop-order parity)
        order = sorted(range(len(failures)),
                       key=lambda i: -failures[i][0].pod_info.priority)
        dev: list[int] = []
        fallback: list[int] = []
        for i in order:
            pi = failures[i][0].pod_info
            if (hasattr(backend, "preempt_batch") and pi.plain
                    and not pi.nominated_node_name
                    and ev._pod_eligible(pi, snapshot)):
                dev.append(i)
            else:
                fallback.append(i)

        traced = span is not None and span.sampled
        results = None
        esc: dict[int, str] = {}
        if dev:
            dry_sp = (span.tracer.start_span("preempt.dry_run", parent=span)
                      if traced else None)
            node_ord_of = {ni.name: pos
                           for pos, ni in enumerate(snapshot.list())}
            results, esc = backend.preempt_batch(
                [failures[i][0].pod_info for i in dev], node_ord_of,
                self.queue.nominator.all_nominations())
            if dry_sp is not None:
                dry_sp.set_attribute("pods", len(dev))
                dry_sp.set_attribute("escapes", len(esc))
                dry_sp.set_attribute(
                    "candidates",
                    sum(1 for r in results if r is not None))
                dry_sp.end()

        # bulk commit under one child span: winners land in queue order;
        # batched evictions (deduped — shared-node winners may name the
        # same victim) + nominatedNodeName patches
        commit_sp = (span.tracer.start_span("preempt.commit", parent=span)
                     if traced else None)
        claimed_nodes: set[str] = set()
        claimed_victims: set[str] = set()
        commits = conflicts = 0
        if results is not None:
            for j, i in enumerate(dev):
                res = results[j]
                if res is None:
                    continue
                node_name, vkeys, _viol = res
                if node_name in claimed_nodes:
                    # conflict resolved inside the wave: this winner
                    # followed an earlier one onto the same node with
                    # the claim folded into its dry run
                    conflicts += 1
                claimed_nodes.add(node_name)
                pod_info = failures[i][0].pod_info
                ni = snapshot.get(node_name)
                vmap = {p.key: p for p in (ni.pods if ni is not None
                                           else ())}
                victims = [vmap[k] for k in vkeys
                           if k in vmap and k not in claimed_victims]
                claimed_victims.update(vkeys)
                evict_victims(self.client, victims, pod_info.key, node_name)
                plugin.persist_nomination(pod_info, node_name)
                self.queue.nominator.add_nominated_pod(pod_info, node_name)
                if ev.observer is not None:
                    ev.observer(len(vkeys))
                commits += 1
        if commit_sp is not None:
            commit_sp.set_attribute("commits", commits)
            commit_sp.set_attribute("conflicts", conflicts)
            commit_sp.set_attribute("victims", len(claimed_victims))
            commit_sp.end()
        occ_fn = getattr(backend, "victim_occupancy", None)
        if occ_fn is not None:
            try:
                self.metrics.prom.tpu_victim_occupancy.set(occ_fn())
            except Exception:  # noqa: BLE001 - gauge is best-effort
                logger.debug("victim occupancy gauge update failed",
                             exc_info=True)

        # legacy tier: kernel escapes + pods outside the envelope
        fallback += [dev[j] for j in sorted(esc)]
        if fallback:
            fallback.sort(key=lambda i: -failures[i][0].pod_info.priority)
            if hasattr(backend, "preempt_candidates"):
                cand_names = backend.preempt_candidates(
                    [failures[i][0].pod_info for i in fallback])
            else:  # pragma: no cover - ladder rung without the device op
                cand_names = [None] * len(fallback)
            for j, i in enumerate(fallback):
                pod_info = failures[i][0].pod_info
                names = cand_names[j]
                nominated = None
                if names is None:
                    # device couldn't evaluate this pod: full host scan
                    nominated, _ps = fw.run_post_filter_plugins(
                        CycleState(), pod_info, {})
                elif names:
                    infos = [ni for ni in (snapshot.get(nm) for nm in names)
                             if ni is not None]
                    nominated, _ps = ev.preempt_among(
                        CycleState(), pod_info, infos, snapshot)
                    if nominated:
                        plugin.persist_nomination(pod_info, nominated)
                if nominated:
                    self.queue.nominator.add_nominated_pod(pod_info,
                                                           nominated)
        for i in order:
            qpi, st = failures[i]
            self._handle_failure(fw, qpi, st, cycle, set(), start)

    # -- batch pipeline (TPU path; no reference equivalent) --------------

    def schedule_batch(self, profile: Profile, batch: list[QueuedPodInfo]) -> None:
        """Schedule a whole batch through the TPU backend synchronously
        (dispatch + finish in one call; the run loop pipelines instead)."""
        pending = self._dispatch_batch(profile, batch)
        if pending is not None:
            self._finish_batch(*pending)
        deferred, self._deferred = self._deferred, []
        for q in deferred:
            self.schedule_one(q)

    def _requeue_batch(self, live: list[QueuedPodInfo],
                       err: BackendUnavailableError) -> None:
        """Backend (not pod) failure: the whole batch re-enters the queue's
        backoff tier.  attempts was already incremented at pop, so a batch
        that keeps hitting a dead seam backs off exponentially per pod;
        nothing is dropped and no pod is marked unschedulable or status-
        patched (the failure is not the pod's fault)."""
        logger.warning("batch backend unavailable (%s); requeueing %d pods "
                       "into backoff", err, len(live))
        self.queue.requeue_backoff(live)
        self.metrics.prom.tpu_seam_events.inc(1.0, "batch_failures")
        self.metrics.prom.tpu_seam_events.inc(float(len(live)),
                                              "requeued_pods")

    def _dispatch_batch(self, profile: Profile, batch: list[QueuedPodInfo],
                        pop_window: tuple[float, float] | None = None):
        """Pre-process a batch and dispatch it to the device (async).

        Returns (profile, live, resolve, cycle, start, span) for
        _finish_batch, or None if nothing went to the device."""
        from ..ops.backend import FLUSH_FIRST
        backend = profile.batch_backend
        if not backend.supports_pipelining:
            # no resident device-state chaining: batch k must be resolved
            # AND assumed before k+1's snapshot is flattened, or k+1 is
            # scored against capacity batch k already claimed
            self._flush_pending()
        cycle = self.queue.scheduling_cycle()
        start = time.monotonic()
        root: tracing.Span | None = None
        if self._tracer is not None:
            root = self._tracer.start_span("schedule_batch", start=start)
            if not root.sampled:
                root.end(start)
                root = None
            else:
                root.set_attribute("process", "scheduler")
                root.set_attribute("cycle", cycle)
                root.set_attribute("pods", len(batch))
                if pop_window is not None:
                    # the pop happened before the root existed; backdate a
                    # child over the measured window so the trace shows
                    # time spent waiting on the queue
                    pop_sp = self._tracer.start_span(
                        "queue.pop", parent=root, start=pop_window[0])
                    pop_sp.set_attribute("pods", len(batch))
                    pop_sp.end(pop_window[1])
        live = [q for q in batch if not self._skip_schedule(q.pod)]
        gates = profile.framework.batch_gates
        if gates and live:
            # host-side plugin gates (Coscheduling minMember): a pod a
            # gate rejects must never reach the device batch — it would
            # assume capacity it can only hold until a Permit timeout
            passed = []
            gate_cache: dict = {}  # per-batch memo (per-group checks)
            for q in live:
                failed = None
                for gate in gates:
                    s = gate.batch_gate(q.pod_info, gate_cache)
                    if s is not None and not s.is_success():
                        failed = s
                        break
                if failed is None:
                    passed.append(q)
                else:
                    self._handle_failure(
                        profile.framework, q, failed, cycle,
                        {failed.plugin} if failed.plugin else set(),
                        start)
            live = passed
        if self.extenders:
            # extender webhooks are per-pod HTTP calls: route interested
            # pods through the oracle path (deferred to a quiescent moment)
            # so the extender contract holds
            ext_pods = [q for q in live if any(
                e.is_interested(q.pod) for e in self.extenders)]
            live = [q for q in live if q not in ext_pods]
            self._deferred.extend(ext_pods)
        if not live:
            if root is not None:
                root.add_event("no_live_pods")
                root.end()
            return None
        # zero-copy flatten: the backend re-encodes dirty node rows straight
        # from cache NodeInfos under the cache lock — no Snapshot clone on
        # the batch path (the per-pod oracle keeps its immutable Snapshot)
        view = self.cache.flatten_view()
        self.metrics.prom.tpu_batch_size.observe(float(len(live)))
        if stagelat.ENABLED:
            stagelat.record("queue_wait",
                            sum(start - q.timestamp for q in live) / len(live))
        tl = self._timeline
        try:
            # the thread-local current span is how the backend (and, via
            # the propagated traceparent, the remote worker) parents its
            # flatten/H2D/solve spans into this batch's trace without
            # widening the BatchBackend dispatch signature; the
            # thread-local current wave does the same for the timeline's
            # patch/h2d/device-step intervals
            with tracing.use_span(root), \
                    (tl.use_wave(cycle) if tl is not None and tl.enabled
                     else cb_timeline.NULL_STAGE):
                resolve = backend.dispatch([q.pod_info for q in live], view)
                if resolve is FLUSH_FIRST:
                    # the batch needs device-state repair; drain the
                    # in-flight batch and its tail (so the authoritative
                    # state catches up), then re-dispatch clean
                    if root is not None:
                        root.add_event("flush_first_redispatch")
                    self._flush_pending()
                    resolve = backend.dispatch(
                        [q.pod_info for q in live], view)
                    if resolve is FLUSH_FIRST:  # pragma: no cover - nothing in flight
                        raise RuntimeError(
                            "backend demanded flush with empty pipeline")
        except BackendUnavailableError as e:
            if root is not None:
                root.add_event("backend_unavailable", error=str(e))
                root.end()
            self._requeue_batch(live, e)
            return None
        if tl is not None and tl.enabled:
            # batch-form: queue pop through dispatch handed to the device
            # (the host-side formation leg of the wave)
            tl.record("batch-form",
                      pop_window[0] if pop_window is not None else start,
                      time.monotonic(), wave=cycle)
        if stagelat.ENABLED:
            # covers the FLUSH_FIRST re-dispatch too (the flush drain time
            # lands here rather than in pipeline_wait)
            stagelat.record("dispatch_host", time.monotonic() - start)
        return profile, live, resolve, cycle, start, root

    def _drain_backend_telemetry(self, backend) -> dict:
        """Apply the backend's per-batch escape/telemetry tallies as metric
        deltas.  Counter is inc-only, so the backend accumulates per-batch
        (plugin, reason) counts and the scheduler drains them here — the
        only writer of scheduler_tpu_escape_total.  Returns the drained
        escape tallies so the escape-storm breaker can label its deferral
        metric with the dominant reason."""
        escapes: dict = {}
        drain = getattr(backend, "drain_escape_reasons", None)
        if drain is not None:
            escapes = drain()
            for (plugin, reason), cnt in escapes.items():
                self.metrics.prom.tpu_escape_total.inc(
                    float(cnt), plugin, reason)
        drain_t = getattr(backend, "drain_batch_telemetry", None)
        if drain_t is not None:
            for telem in drain_t():
                fn = telem.get("feasible_nodes")
                if fn is not None:
                    self.metrics.prom.tpu_feasible_nodes.observe(float(fn))
                waves = telem.get("waves")
                if waves:
                    self.metrics.prom.tpu_batch_waves.observe(float(waves))
                for plugin, dens in (telem.get("mask_density") or {}).items():
                    if dens is not None:
                        self.metrics.prom.tpu_mask_density.set(
                            float(dens), plugin)
        return escapes

    def _resolve_with_deadline(self, profile: Profile,
                               live: list[QueuedPodInfo], resolve,
                               start: float, deadline: float,
                               span: tracing.Span | None):
        """Stuck-wave watchdog (overload: waveDeadlineSeconds): resolve()
        with a hard wall measured PER WAVE.  A wave whose results have
        not landed by the deadline is cancelled — the backend abandons
        its in-flight bookkeeping (abandon_wave) and the pods requeue
        through the BackendUnavailableError path, exactly as if the seam
        had failed.  Returns the results, or None after a cancel.

        Per-wave means the clock starts when the wave reached the HEAD
        of the device queue (its predecessor retired), not at dispatch:
        a pipelined wave N+1 spends part of its residency parked behind
        wave N's device step, and budgeting that parked time against it
        would let one slow-but-healthy wave falsely cancel every healthy
        successor behind it.

        The overrunning resolve keeps running on an orphan daemon thread
        (there is no portable way to interrupt a device pull); its late
        mutations are harmless because abandon_wave dropped the pipeline
        chain and forced a full state refresh for the next dispatch."""
        remaining = deadline - (time.monotonic()
                                - max(start, self._last_retire_t))
        if remaining > 0.0:
            out: list = []
            done = threading.Event()

            def _run() -> None:
                try:
                    out.append(("ok", resolve()))
                except BaseException as e:
                    out.append(("err", e))
                finally:
                    done.set()

            threading.Thread(target=_run, name="wave-resolve",
                             daemon=True).start()
            if done.wait(remaining) and out:
                kind, val = out[0]
                if kind == "ok":
                    return val
                raise val
        logger.warning("wave of %d pods exceeded watchdog deadline (%.1fs); "
                       "cancelling", len(live), deadline)
        if span is not None:
            span.add_event("watchdog_cancel", deadline_s=deadline,
                           pods=len(live))
        self.metrics.prom.overload_wave_cancel_total.inc(1.0, "deadline")
        abandon = getattr(profile.batch_backend, "abandon_wave", None)
        if abandon is not None:
            abandon()
        if span is not None:
            span.end()
        self._requeue_batch(live, BackendUnavailableError(
            f"wave exceeded watchdog deadline ({deadline:.1f}s)"))
        # abandon_wave dropped the whole resident-state chain, so any
        # pipelined successors still in _pending were dispatched against
        # state that no longer exists — cancel them through the same
        # requeue path instead of letting their resolves land on a dead
        # chain (their orphan device results are ignored the same way
        # this wave's are)
        if self._pending:
            orphans, self._pending = self._pending, []
            for _sp, s_live, _sr, _sc, _ss, s_span in orphans:
                if s_span is not None:
                    s_span.add_event("watchdog_cancel_successor")
                    s_span.end()
                self._requeue_batch(s_live, BackendUnavailableError(
                    "pipelined predecessor exceeded watchdog deadline"))
        return None

    def _finish_batch(self, profile: Profile, live: list[QueuedPodInfo],
                      resolve, cycle: int, start: float,
                      span: tracing.Span | None = None) -> None:
        """Resolve a dispatched batch and run the assume -> Reserve ->
        Permit -> bind tail.

        The backend returns a conflict-free assignment (intra-batch resource
        accounting is its job); each returned assignment then goes through
        the same assume -> Reserve -> Permit -> bind tail as the per-pod
        path, so cache/queue/failure semantics are identical.  Pods whose
        Permit is immediate and whose Bind would be the DefaultBinder are
        written back through one bulk store transaction instead of one
        guaranteed-update per pod."""
        fw = profile.framework
        pol = self.overload_policy
        eng = self._engagement
        # quiescent cost of engagement: this bool — None means legacy
        # always-on (engagement: always) or no policy at all
        shielded = eng is None or eng.engaged
        deadline = (pol.wave_deadline
                    if pol is not None and shielded else 0.0)
        t_enter = time.monotonic()
        tl = self._timeline
        try:
            # resolve() may retry/resync through the remote seam: the
            # current span makes those show up as events on this batch's
            # trace rather than orphans (ops/remote.py _seam_event); the
            # current wave attributes the backend's d2h interval
            with tracing.use_span(span), \
                    (tl.use_wave(cycle) if tl is not None and tl.enabled
                     else cb_timeline.NULL_STAGE):
                if deadline > 0.0:
                    results = self._resolve_with_deadline(
                        profile, live, resolve, start, deadline, span)
                    if results is None:
                        return  # wave cancelled; pods already requeued
                else:
                    results = resolve()
        except BackendUnavailableError as e:
            if span is not None:
                span.add_event("backend_unavailable", error=str(e))
                span.end()
            self._requeue_batch(live, e)
            return
        finally:
            # the head slot is free (results landed, wave cancelled, or
            # the chain failed): successors budget their per-wave
            # watchdog deadline from this instant
            self._last_retire_t = time.monotonic()
        resolve_block = time.monotonic() - t_enter
        if tl is not None and tl.enabled:
            # resolve: blocking on the device result + host decode
            tl.record("resolve", t_enter, time.monotonic(), wave=cycle)
        # Adapt the eager-retirement flight estimate HERE, whichever
        # path retired the batch (eager gate, depth overflow, queue-empty
        # block, or a flush) — adapting only from the eager loop froze
        # the estimate wherever another path did the retiring (age there
        # is always >= the estimate, so the estimate could ratchet up on
        # a compile spike and never come back down).  Did resolve WAIT on
        # the device, or was the result landed and the block pure host
        # decode?  Decode cost scales with batch size (~2µs/pod), so the
        # threshold must too.  When it waited, pipeline residency + block
        # IS the observed flight — a direct, path-independent sample
        # that can pull the estimate in either direction; when it did
        # not, the flight ended somewhere earlier and the estimate decays.
        waited = resolve_block > 0.002 + 2e-6 * len(live)
        if waited:
            self._flight_est = min(
                2.0, 0.5 * self._flight_est
                + 0.5 * (t_enter - start + resolve_block))
        else:
            # result was ready when resolve began: the true flight is AT
            # MOST the batch's residency so far — average toward that
            # upper bound (recovers in a few batches from a compile-spike
            # estimate that plain multiplicative decay would need dozens
            # of samples to unwind), with a slow decay floor for the
            # eager path where residency ~= the estimate by construction
            upper = t_enter - start
            self._flight_est = max(0.05, min(
                self._flight_est * 0.95,
                0.5 * self._flight_est + 0.5 * upper))
        if stagelat.ENABLED:
            stagelat.record("pipeline_wait", t_enter - start)
            stagelat.record("resolve_block", resolve_block)
        escapes = self._drain_backend_telemetry(profile.batch_backend)
        if eng is not None:
            # advance the hysteresis machine one retired wave (burn-rate
            # breach primary, queue-depth growth secondary); this loop
            # thread is the only transition writer, so the counter and
            # the queue's engaged flag see a single mutator
            self._apply_engagement_edges(
                eng.on_wave(self.queue.stats()["active"],
                            profile.batch_size))
            shielded = eng.engaged
        if self._wave_tuner is not None and shielded:
            # wave latency = dispatch -> results in hand; queue depth tells
            # the tuner whether growing the wave is worth anything
            self._wave_tuner.observe(time.monotonic() - start,
                                     self.queue.stats()["active"])
        # escape-storm breaker (overload: escapeRateThreshold): decide where
        # this batch's SKIPs go BEFORE the routing loop below.  Open +
        # probe-not-due -> backoff tier (don't flood the per-pod oracle);
        # any other state routes to the oracle as usual and the batch's
        # storm/calm verdict drives open/re-close.
        defer_escapes = False
        br = self._escape_breaker if shielded else None
        if (br is not None and pol is not None
                and len(live) >= pol.escape_min_batch):
            n_skip = sum(1 for node_name, s in results
                         if node_name is None and s is not None
                         and s.is_skip())
            storm = n_skip / len(live) > pol.escape_rate_threshold
            if br.is_open and not br.probe_due():
                defer_escapes = True
            elif storm:
                # closed: may open at the consecutive threshold (only the
                # OPENING batch defers).  Open probe: the probe failed —
                # re-arm the window, but let this one batch's skips flow to
                # the oracle so a persistent organic escape class still
                # drains at probe pace instead of starving forever.
                defer_escapes = br.record_storm()
                if defer_escapes and span is not None:
                    span.add_event("escape_storm_open", skips=n_skip)
            else:
                if br.record_calm() and span is not None:
                    span.add_event("escape_storm_reclose")
        t_phase = time.monotonic()
        bulk: list[tuple[CycleState, QueuedPodInfo, str, Obj]] = []
        # phase 1: collect placements; failures/escapes handled per pod
        placed_q: list[QueuedPodInfo] = []
        placed_names: list[str] = []
        fit_failures: list[tuple[QueuedPodInfo, Status]] = []
        storm_deferred: list[QueuedPodInfo] = []
        for qpi, (node_name, s) in zip(live, results):
            if node_name is None:
                if s is not None and s.is_skip():
                    # constraint not tensor-encodable: per-pod oracle path,
                    # deferred until nothing is in flight (a pipelined next
                    # batch may already be claiming capacity on device) —
                    # unless the escape-storm breaker is open, in which
                    # case the escape class waits out a backoff instead
                    if defer_escapes:
                        storm_deferred.append(qpi)
                    else:
                        self._deferred.append(qpi)
                    continue
                st = s or Status(UNSCHEDULABLE, "no feasible node (batch)")
                if st.code == UNSCHEDULABLE and fw.post_filter:
                    # FitError: PostFilter (batched preemption) below,
                    # after assume so dry-runs see this batch's claims
                    fit_failures.append((qpi, st))
                    continue
                self._handle_failure(fw, qpi, st, cycle,
                                     {st.plugin} if st.plugin else set(), start)
                continue
            placed_q.append(qpi)
            placed_names.append(node_name)
        if storm_deferred:
            # never scheduled against, so requeue_backoff applies: attempts
            # (bumped at pop) buys each deferral a growing backoff
            self.queue.requeue_backoff(storm_deferred)
            reason = (max(escapes, key=escapes.get)[1] if escapes
                      else "escape_storm")
            self.metrics.prom.overload_deferred_total.inc(
                float(len(storm_deferred)), reason)
            if span is not None:
                span.add_event("escape_storm_deferred",
                               pods=len(storm_deferred), reason=reason)
        # 2-level shallow copies in ONE native pass (utils/fasthost): only
        # spec is replaced; nested values are never mutated in place on
        # this path (store reads hand out copies), so the deep copy the
        # per-pod path does is pure overhead here
        assumed_objs = fasthost.build_assumed(
            [q.pod_info.pod for q in placed_q], placed_names)
        clones = fasthost.clone_podinfos(
            [q.pod_info for q in placed_q], assumed_objs)
        placed: list[tuple[QueuedPodInfo, str, Obj, PodInfo]] = list(
            zip(placed_q, placed_names, assumed_objs, clones))
        if stagelat.ENABLED:
            stagelat.record("finish_collect", time.monotonic() - t_phase)
            t_phase = time.monotonic()
        # phase 2: ONE bulk assume (single cache lock for the whole batch)
        errs = self.cache.assume_pods([(a, pi) for _, _, a, pi in placed])
        if stagelat.ENABLED:
            stagelat.record("finish_assume", time.monotonic() - t_phase)
        ok: list[tuple[QueuedPodInfo, str, Obj]] = []
        for (qpi, node_name, assumed, _pi), err in zip(placed, errs):
            if err is not None:
                self._handle_failure(fw, qpi, Status(ERROR, err), cycle,
                                     set(), start)
            else:
                ok.append((qpi, node_name, assumed))
        if fit_failures:
            self._batch_preempt(profile, fw, fit_failures, cycle, start,
                                span=span)
        if span is not None:
            # the bind child outlives the root on purpose (the binding
            # cycle runs on the binder pool; id-parenting keeps it in the
            # trace) — end the root here so its duration means
            # dispatch -> results applied
            span.set_attribute("placed", len(ok))
            span.end()
        if not ok:
            return
        # turbo tail: with an empty CycleState the hook loops are provably
        # no-ops (batch_tail_trivial) and the Bind step is the DefaultBinder
        # — go straight to the bulk store bind, skipping the per-pod
        # Reserve/Permit/WaitOnPermit/PreBind calls entirely
        if fw.batch_tail_trivial() and self._bulk_bindable(fw):
            self._submit_binding(self._binding_cycle_turbo, fw, ok, cycle,
                                 start, span)
            return
        for qpi, node_name, assumed in ok:
            state = CycleState()
            pod_info = qpi.pod_info
            st = fw.run_reserve_plugins(state, pod_info, node_name)
            if not is_success(st):
                self.cache.forget_pod(assumed)
                self._handle_failure(fw, qpi, st, cycle,
                                     {st.plugin} if st.plugin else set(), start)
                continue
            st = fw.run_permit_plugins(state, pod_info, node_name)
            if st is not None and not (st.is_success() or st.is_wait()):
                fw.run_unreserve_plugins(state, pod_info, node_name)
                self.cache.forget_pod(assumed)
                self._handle_failure(fw, qpi, st, cycle,
                                     {st.plugin} if st.plugin else set(), start)
                continue
            if (st is None or st.is_success()) and self._bulk_bindable(fw):
                bulk.append((state, qpi, node_name, assumed))
            else:
                self._submit_binding(self._binding_cycle, fw, state, qpi,
                                     node_name, cycle, start)
        if bulk:
            self._submit_binding(self._binding_cycle_bulk, fw, bulk,
                                 cycle, start, span)

    def _binding_cycle_turbo(self, fw: Framework,
                             items: list[tuple[QueuedPodInfo, str, Obj]],
                             cycle: int, start: float,
                             span: tracing.Span | None = None) -> None:
        """Bind tail for the provably-trivial case (batch_tail_trivial +
        DefaultBinder): no per-pod plugin hook calls at all — straight to
        the shared bulk commit.  The shared empty CycleState is sound
        because no plugin on this path reads or writes state."""
        state = CycleState()
        self._bulk_bind_commit(
            fw, [(state, qpi, node, assumed) for qpi, node, assumed in items],
            cycle, start, run_post_bind=False, span=span)

    def _submit_binding(self, fn, *args) -> None:
        """Route a binding cycle off the wave critical path.

        Non-blocking commits (bulk/turbo) go to the dedicated binder
        worker — single consumer, bounded queue, optional CPU pin.  The
        per-pod cycle can park in WaitOnPermit (Coscheduling gangs), so
        it keeps the multi-thread pool; a stopped worker or shut-down
        pool degrades to inline so no assumed pod is stranded unbound
        and unrequeued."""
        wired = (Scheduler._binding_cycle_turbo, Scheduler._binding_cycle_bulk)
        if getattr(fn, "__func__", None) in wired \
                and self._binder_worker.submit(fn, *args):
            return
        try:
            self._binder_pool.submit(fn, *args)
        except RuntimeError:
            fn(*args)

    @staticmethod
    def _bulk_bindable(fw: Framework) -> bool:
        """True when the profile's Bind step is exactly the DefaultBinder
        (so a bulk store bind is semantically the same write).  The marker
        must be defined by the plugin's own class: a subclass overriding
        bind() would inherit the attribute but must NOT be bypassed."""
        return (len(fw.bind) == 1
                and type(fw.bind[0]).__dict__.get("is_default_binder", False))

    def _binding_cycle_bulk(self, fw: Framework,
                            items: list[tuple[CycleState, QueuedPodInfo, str, Obj]],
                            cycle: int, start: float,
                            span: tracing.Span | None = None) -> None:
        """Binding cycle for a whole batch: per-pod WaitOnPermit (immediate
        for everything routed here) and PreBind, then ONE bulk bind write,
        then per-pod PostBind/metrics/events.  Failure handling per pod is
        identical to _binding_cycle (Forget + unreserve + requeue)."""
        ready: list[tuple[CycleState, QueuedPodInfo, str, Obj]] = []
        for state, qpi, node_name, assumed in items:
            try:
                s = fw.wait_on_permit(qpi.pod_info)
                if not is_success(s):
                    self._bind_failure(fw, state, qpi, assumed, node_name, s,
                                       cycle)
                    continue
                s = fw.run_pre_bind_plugins(state, qpi.pod_info, node_name)
                if not is_success(s):
                    self._bind_failure(fw, state, qpi, assumed, node_name, s,
                                       cycle)
                    continue
                ready.append((state, qpi, node_name, assumed))
            except Exception as e:  # pragma: no cover
                logger.exception("bulk binding prep error for %s", qpi.key)
                self._bind_failure(fw, state, qpi, assumed, node_name,
                                   Status(ERROR, str(e)), cycle)
        if not ready:
            return
        self._bulk_bind_commit(fw, ready, cycle, start, run_post_bind=True,
                               span=span)

    def _bulk_bind_commit(self, fw: Framework,
                          ready: list[tuple[CycleState, QueuedPodInfo, str, Obj]],
                          cycle: int, start: float,
                          run_post_bind: bool,
                          span: tracing.Span | None = None) -> None:
        """Shared bind/confirm/metrics tail for the bulk paths: ONE bulk
        bind write, bulk cache confirm, bulk metrics/events; per-pod
        failure handling identical to _binding_cycle (Forget + unreserve +
        requeue)."""
        bind_sp: tracing.Span | None = None
        if span is not None and span.sampled:
            # parent has usually already ended (id-parenting stays valid);
            # this span runs on the binder pool thread
            bind_sp = span.tracer.start_span("bind", parent=span)
            bind_sp.set_attribute("pods", len(ready))
        t_bind0 = time.monotonic()
        bindings = fasthost.binding_rows(ready)
        t_phase = time.monotonic()
        if self.scaleout is not None and not self.scaleout.self_live:
            # write fence (scale-out lease lapsed or instance retired):
            # committing now could double-bind against whichever survivor
            # absorbed our partition.  Nothing reached the store — the
            # whole in-flight wave lands in the backoff tier, where the
            # survivors' resync picks the pods up from the shared store.
            self._conflict_requeue(fw, ready, bind_sp, forced="fenced")
            if bind_sp is not None:
                bind_sp.end()
            return
        try:
            results = self.client.bind_many(bindings)
        except kv.FencedError as e:
            # the STORE fenced (replication failover deposed this
            # primary): same contract as the lease fence above
            logger.warning("bind wave fenced by the store: %s", e)
            self._conflict_requeue(fw, ready, bind_sp, forced="fenced")
            if bind_sp is not None:
                bind_sp.end()
            return
        except Exception:
            # whole-call failure (transport, mid-call store error): the old
            # behavior blamed every pod with the same opaque error.  Retry
            # each binding individually instead, so only genuinely failed
            # pods take the Forget+requeue path and each failure event
            # carries its own cause
            logger.exception("bulk bind failed; classifying per binding")
            results = self._classify_bindings(bindings)
        if stagelat.ENABLED:
            stagelat.record("bind_store_write", time.monotonic() - t_phase)
        bound: list[tuple[CycleState, QueuedPodInfo, str, Obj]] = []
        conflicted: list[tuple[CycleState, QueuedPodInfo, str, Obj]] = []
        for (state, qpi, node_name, assumed), (obj, err) in zip(ready, results):
            if err is not None:
                if isinstance(err, kv.NotFoundError):
                    # pod deleted mid-wave: there is nothing to requeue or
                    # status-patch — just release the assumed capacity
                    fw.run_unreserve_plugins(state, qpi.pod_info, node_name)
                    try:
                        self.cache.forget_pod(assumed)
                    except ValueError:  # pragma: no cover - already expired
                        pass
                    continue
                if isinstance(err, kv.ConflictError):
                    if getattr(err, "current_node", None) == node_name:
                        # our own write landed (a half-applied bulk call
                        # retried per binding): the pod IS bound where we
                        # assumed it — take the success tail
                        self.metrics.prom.bind_conflict_total.inc(
                            1.0, "already_bound_same_node")
                        bound.append((state, qpi, node_name, assumed))
                        continue
                    # lost the optimistic race to a peer instance
                    conflicted.append((state, qpi, node_name, assumed))
                    continue
                self._bind_failure(fw, state, qpi, assumed, node_name,
                                   Status(ERROR, f"binding rejected: {err}"),
                                   cycle)
                continue
            bound.append((state, qpi, node_name, assumed))
        if conflicted:
            self._conflict_requeue(fw, conflicted, bind_sp)
        if not bound:
            if bind_sp is not None:
                bind_sp.add_event("all_bindings_rejected")
                bind_sp.end()
            return
        # pods ARE bound in the store at this point: a failure in the
        # confirm/PostBind tail must not abort the rest of the batch or
        # route an already-bound pod through _bind_failure (which would
        # forget + requeue it)
        t_phase = time.monotonic()
        self.cache.finish_bindings([a for _, _, _, a in bound])
        now = time.monotonic()
        latency = now - start
        if stagelat.ENABLED:
            stagelat.record("bind_confirm", now - t_phase)
            stagelat.record("disp_to_bound", latency)
        e2e_lats = [now - q.initial_attempt_timestamp for _, q, _, _ in bound]
        self.metrics.observe_e2e(
            [(lat, q.attempts)
             for lat, (_, q, _, _) in zip(e2e_lats, bound)])
        eng = self._engagement
        if eng is not None:
            # arm-signal feed: the controller owns its SLOTracker so the
            # burn-rate breach fires with or without a profiling: stanza
            eng.note_latencies(e2e_lats, now=now)
        tl = self._timeline
        if tl is not None and tl.enabled:
            tl.record("bind-commit", t_bind0, now, wave=cycle)
            # per-pod e2e decomposition: telescope each pod's enqueue
            # timestamp through the wave's stage marks to the commit.
            # Boundaries are clamped monotone non-decreasing, so every
            # segment is >= 0 and the segments sum EXACTLY to the same
            # e2e observe_e2e just recorded.
            marks = tl.wave_marks(cycle)
            bind_end = tl.wall(now)
            form_mark = marks.get("batch-form")
            dev_end = (marks.get("device-step") or (None, None))[1]
            res_end = (marks.get("resolve") or (None, None))[1]
            # only the enqueue timestamp varies per pod — the wave's
            # stage marks are shared — so the wave records as ONE raw
            # block (keys, enqueue column, bind wall, marks) and the
            # telescoped clamp chain runs lazily at read time
            # (derive_segment_cols).  The ≤5% overhead pin rides on
            # this path staying one fromiter + two appends.
            n_b = len(bound)
            t_enq = np.fromiter(
                (q.initial_attempt_timestamp for _, q, _, _ in bound),
                np.float64, n_b)
            t_enq += bind_end - now
            wave_marks = (form_mark[0] if form_mark else None,
                          form_mark[1] if form_mark else None,
                          dev_end, res_end)
            tl.record_pod_block([q.key for _, q, _, _ in bound], cycle,
                                t_enq, bind_end, marks=wave_marks)
            self.metrics.defer_segments(t_enq, bind_end, wave_marks)
        if self._slo is not None:
            # SLO tracker tap: the submit->bind latencies of this wave
            # feed the rolling windows; a wave that lands past the
            # target while the budget is burning gets a profile slice
            # attached to its bind span (what WAS the host doing?)
            self._slo.observe(e2e_lats, now=now)
            if (bind_sp is not None
                    and max(e2e_lats, default=0.0) > self._slo.target_s
                    and self._slo.breached(now=now)):
                attrs = {"slo_target_ms": self._slo.target_s * 1e3,
                         "wave_p_max_ms": round(max(e2e_lats) * 1e3, 2)}
                if self._profiler is not None:
                    for i, (stack, n) in enumerate(
                            self._profiler.top_stacks(5)):
                        attrs[f"stack_{i}"] = f"{n} {stack}"
                    attrs["stage_seconds"] = str(
                        self._profiler.stage_seconds())
                bind_sp.add_event("slo_breach_profile", **attrs)
        if run_post_bind:
            for state, qpi, node_name, assumed in bound:
                try:
                    fw.run_post_bind_plugins(state, qpi.pod_info, node_name)
                except Exception:
                    logger.exception("post-bind tail failed for %s (pod stays "
                                     "bound to %s)", qpi.key, node_name)
        self.client.create_event_burst(
            [(qpi.pod, "Scheduled",
              f"Successfully assigned {qpi.key} to {node_name}")
             for _, qpi, node_name, _ in bound])
        self.metrics.observe_attempts("scheduled", [latency] * len(bound),
                                      fw.profile_name)
        if bind_sp is not None:
            bind_sp.set_attribute("bound", len(bound))
            bind_sp.end()

    def _classify_bindings(self, bindings: list[tuple[str, str, str]]
                           ) -> list[tuple[Obj | None, Exception | None]]:
        """Per-binding fallback after a whole-call bind_many failure:
        retry each binding on its own so the store classifies it —
        NotFoundError (pod deleted mid-wave), ConflictError (already
        bound, possibly by the half-applied bulk call), or the real
        transport error.  Bind is idempotent per pod at the store level:
        a binding the failed bulk call DID apply comes back as a
        ConflictError naming the same node, which _handle_failure then
        resolves by observing the bound pod."""
        out: list[tuple[Obj | None, Exception | None]] = []
        for ns, nm, node in bindings:
            try:
                # conflicts ship to _bulk_bind_commit by value in `out`,
                # where the taxonomy resolves them
                # ktpulint: disable=bind-conflict-handled
                obj = self.client.bind(
                    {"metadata": {"namespace": ns, "name": nm}}, node)
                out.append((obj, None))
            except Exception as e:
                out.append((None, e))
        return out

    def _conflict_requeue(self, fw: Framework,
                          entries: list[tuple[CycleState, QueuedPodInfo,
                                              str, Obj]],
                          bind_sp, forced: str | None = None) -> None:
        """Resolve pods that lost the optimistic bind race to a peer
        scheduler instance — or a whole in-flight wave caught behind a
        write fence (forced="fenced").  Every entry Forgets its assumed
        capacity first; then the store decides the outcome:

          lost_to_peer   re-fetch shows the pod bound (or gone): a peer
                         owns it now, nothing to requeue
          requeued       pod still unbound (peer's claim evaporated, or
                         the store is unreadable): back through the
                         backoff tiers — compare-and-bind keeps a
                         spurious retry safe
          fenced         forced: nothing reached the store, the whole
                         wave requeues without a re-fetch
        """
        outcomes: dict[str, int] = {}
        requeue: list[QueuedPodInfo] = []
        for state, qpi, node_name, assumed in entries:
            fw.run_unreserve_plugins(state, qpi.pod_info, node_name)
            try:
                self.cache.forget_pod(assumed)
            except ValueError:  # pragma: no cover - already expired
                pass
            if forced is not None:
                outcome = forced
            else:
                outcome = "requeued"
                try:
                    current = self.client.get(PODS, meta.namespace(qpi.pod),
                                              meta.name(qpi.pod))
                    if (current.get("spec") or {}).get("nodeName"):
                        outcome = "lost_to_peer"
                except kv.NotFoundError:
                    outcome = "lost_to_peer"  # bound by a peer, then deleted
                except (kv.StoreError, OSError):
                    pass  # cannot tell: requeue is the safe side
            outcomes[outcome] = outcomes.get(outcome, 0) + 1
            if outcome != "lost_to_peer":
                requeue.append(qpi)
        if requeue:
            self.queue.requeue_backoff(requeue)
        for outcome, n in sorted(outcomes.items()):
            self.metrics.prom.bind_conflict_total.inc(float(n), outcome)
        if bind_sp is not None:
            bind_sp.add_event("bind_conflict", pods=len(entries), **outcomes)
        logger.info("bind conflicts resolved: %s", outcomes)
