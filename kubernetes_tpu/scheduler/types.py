"""Scheduler core types.

Reference: pkg/scheduler/framework/types.go
  NodeInfo  (types.go:375) - per-node aggregate the filters/scores read
  Resource  (types.go:426) - canonical resource vector (api/resources.py)
  PodInfo              - pod + precomputed affinity terms
  QueuedPodInfo        - queue bookkeeping (attempts, timestamps)
  ClusterEvent         - event descriptors for requeue gating
and framework status codes (framework/interface.go Status/Code).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any

from ..api import meta
from ..api.labels import Selector, selector_from_dict
from ..api.meta import Obj
from ..api.resources import (
    Resource, node_allocatable, pod_request, pod_request_nonzero,
    pod_request_pair, request_pair_from_requests,
)
from ..utils import fasthost

# --- Status codes (framework/interface.go:84-120) -------------------------

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
UNSCHEDULABLE_AND_UNRESOLVABLE = 3
WAIT = 4
SKIP = 5

_CODE_NAMES = {
    SUCCESS: "Success", ERROR: "Error", UNSCHEDULABLE: "Unschedulable",
    UNSCHEDULABLE_AND_UNRESOLVABLE: "UnschedulableAndUnresolvable",
    WAIT: "Wait", SKIP: "Skip",
}


class Status:
    """Plugin status. None is treated as Success everywhere (like the reference)."""

    __slots__ = ("code", "reasons", "plugin")

    def __init__(self, code: int = SUCCESS, *reasons: str, plugin: str = ""):
        self.code = code
        self.reasons = list(reasons)
        self.plugin = plugin

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_skip(self) -> bool:
        return self.code == SKIP

    def is_wait(self) -> bool:
        return self.code == WAIT

    def is_rejected(self) -> bool:
        return self.code in (UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return "; ".join(self.reasons)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Status({_CODE_NAMES[self.code]}, {self.reasons}, plugin={self.plugin})"


def status_code(s: Status | None) -> int:
    return SUCCESS if s is None else s.code


def is_success(s: Status | None) -> bool:
    return s is None or s.is_success()


# --- Cluster events (framework/types.go ClusterEvent) ---------------------
# Resource|ActionType strings used by EventsToRegister/queue gating.

@dataclass(frozen=True, slots=True)
class ClusterEvent:
    resource: str   # "Pod", "Node", "PersistentVolumeClaim", ..., "*"
    action: str     # "Add", "Update", "Delete", "UpdateNodeLabel", ..., "*"

    def match(self, other: "ClusterEvent") -> bool:
        return ((self.resource == "*" or self.resource == other.resource)
                and (self.action == "*" or self.action == other.action
                     or other.action.startswith(self.action)))


EVENT_WILDCARD = ClusterEvent("*", "*")
POD_ADD = ClusterEvent("Pod", "Add")
POD_UPDATE = ClusterEvent("Pod", "Update")
ASSIGNED_POD_ADD = ClusterEvent("AssignedPod", "Add")
ASSIGNED_POD_UPDATE = ClusterEvent("AssignedPod", "Update")
ASSIGNED_POD_DELETE = ClusterEvent("AssignedPod", "Delete")
NODE_ADD = ClusterEvent("Node", "Add")
NODE_UPDATE = ClusterEvent("Node", "Update")
NODE_DELETE = ClusterEvent("Node", "Delete")
PVC_ADD = ClusterEvent("PersistentVolumeClaim", "Add")


# --- Affinity terms -------------------------------------------------------

@dataclass(slots=True)
class AffinityTerm:
    """A compiled v1.PodAffinityTerm (framework/types.go AffinityTerm).

    ns_selector is the term's namespaceSelector (PodAffinityNamespace-
    Selector): the effective namespace set is `namespaces` UNION the
    namespaces whose LABELS match ns_selector — resolved at match time
    against a ns_labels map the caller supplies (the reference resolves
    per cycle via a namespace lister, plugins/interpodaffinity).  An
    EMPTY ns_selector matches every namespace.  Callers that cannot
    supply ns_labels treat ns_selector terms as namespace-list-only
    (the TPU encoder instead resolves the term against its informer-fed
    namespace-label cache into a concrete namespace set at flatten time,
    flatten.ClusterTensors.resolve_namespaces)."""

    selector: Selector
    topology_key: str
    namespaces: frozenset[str]
    weight: int = 0  # for preferred terms
    ns_selector: Selector | None = None

    def matches(self, pod: Obj, pod_labels: dict[str, str],
                ns_labels: dict[str, dict] | None = None) -> bool:
        ns = meta.namespace(pod)
        if ns not in self.namespaces:
            if self.ns_selector is None or ns_labels is None:
                return False
            lbl = ns_labels.get(ns)
            if lbl is None or not self.ns_selector.matches(lbl):
                return False
        return self.selector.matches(pod_labels)


def _compile_terms(terms: list[Obj] | None, default_ns: str,
                   weighted: bool = False) -> list[AffinityTerm]:
    out: list[AffinityTerm] = []
    for t in terms or ():
        w = 0
        if weighted:
            w = t.get("weight", 0)
            t = t.get("podAffinityTerm") or {}
        ns_sel = None
        if "namespaceSelector" in t and t["namespaceSelector"] is not None:
            # an explicit (possibly EMPTY = match-all) namespaceSelector;
            # the listed namespaces then default to the empty set, not
            # the pod's own namespace (reference conversion semantics)
            ns_sel = selector_from_dict(t["namespaceSelector"])
            namespaces = frozenset(t.get("namespaces") or ())
        else:
            namespaces = frozenset(t.get("namespaces") or [default_ns])
        out.append(AffinityTerm(
            selector=selector_from_dict(t.get("labelSelector")),
            topology_key=t.get("topologyKey", ""),
            namespaces=namespaces,
            weight=w,
            ns_selector=ns_sel,
        ))
    return out


# --- PodInfo --------------------------------------------------------------

class PodInfo:
    """Pod plus precomputed scheduling attributes (framework/types.go PodInfo).

    Everything the hot path needs is parsed exactly once here: resource
    requests, affinity terms with compiled selectors, tolerations, host
    ports, topology-spread constraints.  The TPU flattener (ops/flatten.py)
    reads these, never the raw dict.
    """

    __slots__ = (
        "pod", "key", "uid", "labels", "priority", "request", "request_nonzero",
        "required_affinity_terms", "required_anti_affinity_terms",
        "preferred_affinity_terms", "preferred_anti_affinity_terms",
        "tolerations", "node_selector", "node_affinity_required",
        "node_affinity_preferred", "host_ports", "topology_spread_constraints",
        "scheduler_name", "nominated_node_name", "plain",
        "has_ns_selector_terms",
    )

    def __init__(self, pod: Obj):
        self.update(pod)

    def update(self, pod: Obj) -> None:
        # native fast path: ONE C pass walks the pod dict and, when it
        # proves the pod "simple" (no affinity/selector/spread/ports/
        # special volumes/nomination/nodeName), fills every slot
        # directly — only the request pair stays in Python (its shared
        # lru-cached instances).  The C `simple` predicate mirrors this
        # method's own branch conditions, so a pod the C side can't
        # prove takes the full path and the two can never diverge on a
        # fast-path pod (differential corpus: tests/test_fasthost.py).
        requests = fasthost.pod_scan_into(pod, self, _FAST_DEFAULTS)
        if requests is not False:
            # simple pods carry no affinity stanza, hence no
            # namespaceSelector terms (the C fill covers only the slots
            # it lists)
            self.has_ns_selector_terms = False
            # `requests` is only a dict for the proven single-container
            # shape; multi-container/initContainer pods still need the
            # general sum/max computation
            self.request, self.request_nonzero = (
                request_pair_from_requests(requests)
                if requests is not None else pod_request_pair(pod))
            if self.request.scalar or self.request_nonzero.scalar:
                self.plain = False
            return
        spec = pod.get("spec") or {}
        self.pod = pod
        self.key = meta.namespaced_name(pod)
        self.uid = meta.uid(pod)
        self.labels = meta.labels(pod)
        self.priority = spec.get("priority") or 0
        # shared frozen instances for the common shape (see
        # resources.pod_request_pair) — never mutated by consumers
        self.request, self.request_nonzero = pod_request_pair(pod)
        self.scheduler_name = spec.get("schedulerName", "default-scheduler")
        self.nominated_node_name = (pod.get("status") or {}).get("nominatedNodeName", "")

        affinity = spec.get("affinity")
        self.node_selector = spec.get("nodeSelector") or {}
        if not affinity:
            # hot path: most pods carry no affinity stanza at all
            self.required_affinity_terms = _EMPTY_TERMS
            self.required_anti_affinity_terms = _EMPTY_TERMS
            self.preferred_affinity_terms = _EMPTY_TERMS
            self.preferred_anti_affinity_terms = _EMPTY_TERMS
            self.node_affinity_required = _EMPTY_TERMS
            self.node_affinity_preferred = _EMPTY_TERMS
            self.has_ns_selector_terms = False
        else:
            ns = meta.namespace(pod)
            pa = affinity.get("podAffinity") or {}
            paa = affinity.get("podAntiAffinity") or {}
            self.required_affinity_terms = _compile_terms(
                pa.get("requiredDuringSchedulingIgnoredDuringExecution"), ns)
            self.required_anti_affinity_terms = _compile_terms(
                paa.get("requiredDuringSchedulingIgnoredDuringExecution"), ns)
            self.preferred_affinity_terms = _compile_terms(
                pa.get("preferredDuringSchedulingIgnoredDuringExecution"), ns,
                weighted=True)
            self.preferred_anti_affinity_terms = _compile_terms(
                paa.get("preferredDuringSchedulingIgnoredDuringExecution"),
                ns, weighted=True)

            na = affinity.get("nodeAffinity") or {}
            req = na.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
            self.node_affinity_required = [
                _compile_node_selector_term(t)
                for t in req.get("nodeSelectorTerms") or ()]
            self.node_affinity_preferred = [
                (p.get("weight", 0),
                 _compile_node_selector_term(p.get("preference") or {}))
                for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or ()]

        self.has_ns_selector_terms = any(
            t.ns_selector is not None
            for t in self.required_affinity_terms
            + self.required_anti_affinity_terms
            + self.preferred_affinity_terms
            + self.preferred_anti_affinity_terms)
        self.tolerations = spec.get("tolerations") or []
        self.host_ports = _collect_host_ports(spec)
        self.topology_spread_constraints = spec.get("topologySpreadConstraints") or []
        # plain == touches none of the constraint-side tensor fields:
        # the TPU flattener's fast-path predicate, computed ONCE here
        # (where every input is already in hand) instead of per encode.
        # The checks mirror flatten._encode_pod's write sites exactly.
        plain = not (
            self.nominated_node_name or self.node_selector
            or self.node_affinity_required or self.node_affinity_preferred
            or self.required_affinity_terms
            or self.required_anti_affinity_terms
            or self.preferred_affinity_terms
            or self.preferred_anti_affinity_terms
            or self.topology_spread_constraints or self.host_ports
            or self.request.scalar or self.request_nonzero.scalar
            or spec.get("nodeName"))
        if plain:
            for v in spec.get("volumes") or ():
                if (v.get("persistentVolumeClaim")
                        or v.get("gcePersistentDisk")
                        or v.get("awsElasticBlockStore")
                        or v.get("azureDisk") or v.get("iscsi")
                        or v.get("csi")):
                    # volume binding/zones/limits are deeply stateful:
                    # oracle path (flatten._encode_pod escapes these)
                    plain = False
                    break
        self.plain = plain

    def clone_with_pod(self, pod: Obj) -> "PodInfo":
        """Copy of this PodInfo pointing at `pod` WITHOUT re-parsing.

        For the assume path: the assumed object differs from the parsed one
        only in spec.nodeName, which none of the precomputed attributes
        derive from — re-running update() for every pod in a 2k batch is
        pure overhead."""
        c = PodInfo.__new__(PodInfo)
        for slot in PodInfo.__slots__:
            setattr(c, slot, getattr(self, slot))
        c.pod = pod
        return c

    def has_required_anti_affinity(self) -> bool:
        return bool(self.required_anti_affinity_terms)

    def has_affinity(self) -> bool:
        return bool(self.required_affinity_terms or self.required_anti_affinity_terms
                    or self.preferred_affinity_terms or self.preferred_anti_affinity_terms)


def _compile_node_selector_term(term: Obj) -> tuple[Selector, Selector]:
    """A NodeSelectorTerm = (matchExpressions on labels, matchFields on metadata.name)."""
    lab = Selector(tuple(
        _req_from_expr(e) for e in term.get("matchExpressions") or ()))
    fields = Selector(tuple(
        _req_from_expr(e) for e in term.get("matchFields") or ()))
    return lab, fields


def _req_from_expr(e: Obj):
    from ..api.labels import Requirement
    return Requirement(e["key"], e["operator"], tuple(e.get("values") or ()))


def node_selector_terms_match(terms: list[tuple[Selector, Selector]], node: Obj) -> bool:
    """OR over terms, AND within a term (nodeaffinity.go semantics).
    Empty terms list means no restriction."""
    if not terms:
        return True
    node_labels = meta.labels(node)
    node_fields = {"metadata.name": meta.name(node)}
    for lab, fields in terms:
        if lab.matches(node_labels) and fields.matches(node_fields):
            return True
    return False


# process-local: read-only empties (contract below) — a per-process
# copy is exactly as good as a shared one
_EMPTY_PORTS: list[tuple[str, str, int]] = []
# shared empties for the no-affinity fast path; treated as immutable
_EMPTY_TERMS: list = []  # process-local: same read-only contract
_EMPTY_DICT: dict = {}  # process-local: same read-only contract
_EMPTY_LIST: list = []  # process-local: same read-only contract
# singletons handed to the C fast path (fasthost.pod_scan_into): shared
# across every simple PodInfo, read-only by the same contract as
# _EMPTY_TERMS (consumers only iterate/read these fields)
_FAST_DEFAULTS = (_EMPTY_TERMS, _EMPTY_PORTS, _EMPTY_DICT, _EMPTY_LIST,
                  "default-scheduler")


def _collect_host_ports(spec: Obj) -> list[tuple[str, str, int]]:
    """[(protocol, hostIP, hostPort)] for all containers.  Fast path: most
    pods declare no container ports at all (PodInfo hot path)."""
    containers = spec.get("containers") or ()
    inits = spec.get("initContainers")
    if not inits and not any("ports" in c for c in containers):
        return _EMPTY_PORTS
    out = []
    for c in itertools.chain(containers, inits or ()):
        for p in c.get("ports") or ():
            hp = p.get("hostPort", 0)
            if hp:
                out.append((p.get("protocol", "TCP"), p.get("hostIP", "0.0.0.0"), hp))
    return out


# --- NodeInfo -------------------------------------------------------------

_generation = itertools.count(1)


class NodeInfo:
    """Aggregated per-node state (framework/types.go:375).

    Tracks requested/non-zero-requested resources incrementally as pods are
    added/removed, the host-port set, affinity pod sublists, and image states.
    `generation` bumps on every mutation — the cache's incremental snapshot
    (cache.py) and the TPU flattener's dirty-row re-encode key off it.
    """

    __slots__ = ("node", "pods", "pods_with_affinity", "pods_with_required_anti_affinity",
                 "requested", "non_zero_requested", "allocatable", "used_ports",
                 "image_sizes", "pvc_ref_counts", "generation", "node_generation")

    def __init__(self, node: Obj | None = None):
        self.node = node
        self.pods: list[PodInfo] = []
        self.pods_with_affinity: list[PodInfo] = []
        self.pods_with_required_anti_affinity: list[PodInfo] = []
        self.requested = Resource()
        self.non_zero_requested = Resource()
        self.allocatable = node_allocatable(node) if node else Resource()
        self.used_ports: set[tuple[str, str, int]] = set()
        self.image_sizes: dict[str, int] = {}
        self.pvc_ref_counts: dict[str, int] = {}
        self.generation = next(_generation)
        # node_generation advances only when the node OBJECT changes (labels,
        # taints, allocatable) — not on pod add/remove.  The TPU flattener
        # keys its static-field re-encode off this, so routine binds touch
        # only the dynamic arrays.
        self.node_generation = self.generation
        if node is not None:
            for img in (node.get("status") or {}).get("images") or ():
                size = img.get("sizeBytes", 0)
                for name in img.get("names") or ():
                    self.image_sizes[name] = size

    @property
    def name(self) -> str:
        return meta.name(self.node) if self.node else ""

    def set_node(self, node: Obj) -> None:
        self.node = node
        self.allocatable = node_allocatable(node)
        self.image_sizes = {}
        for img in (node.get("status") or {}).get("images") or ():
            size = img.get("sizeBytes", 0)
            for name in img.get("names") or ():
                self.image_sizes[name] = size
        self.generation = next(_generation)
        self.node_generation = self.generation

    def add_pod(self, pi: PodInfo) -> None:
        self.pods.append(pi)
        if pi.has_affinity():
            self.pods_with_affinity.append(pi)
        if pi.has_required_anti_affinity():
            self.pods_with_required_anti_affinity.append(pi)
        self.requested.add(pi.request)
        self.non_zero_requested.add(pi.request_nonzero)
        self.used_ports.update(pi.host_ports)
        for v in (pi.pod.get("spec") or {}).get("volumes") or ():
            pvc = (v.get("persistentVolumeClaim") or {}).get("claimName")
            if pvc:
                key = f"{meta.namespace(pi.pod)}/{pvc}"
                self.pvc_ref_counts[key] = self.pvc_ref_counts.get(key, 0) + 1
        self.generation = next(_generation)

    def remove_pod(self, pod: Obj) -> bool:
        key = meta.namespaced_name(pod)
        removed: PodInfo | None = None
        for i, pi in enumerate(self.pods):
            if pi.key == key:
                removed = pi
                del self.pods[i]
                break
        if removed is None:
            return False
        for lst in (self.pods_with_affinity, self.pods_with_required_anti_affinity):
            for i, pi in enumerate(lst):
                if pi.key == key:
                    del lst[i]
                    break
        self.requested.sub(removed.request)
        self.non_zero_requested.sub(removed.request_nonzero)
        self.used_ports.difference_update(removed.host_ports)
        for v in (removed.pod.get("spec") or {}).get("volumes") or ():
            pvc = (v.get("persistentVolumeClaim") or {}).get("claimName")
            if pvc:
                k = f"{meta.namespace(removed.pod)}/{pvc}"
                n = self.pvc_ref_counts.get(k, 0) - 1
                if n <= 0:
                    self.pvc_ref_counts.pop(k, None)
                else:
                    self.pvc_ref_counts[k] = n
        self.generation = next(_generation)
        return True

    def clone(self) -> "NodeInfo":
        c = NodeInfo.__new__(NodeInfo)
        c.node = self.node
        c.pods = list(self.pods)
        c.pods_with_affinity = list(self.pods_with_affinity)
        c.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        c.requested = self.requested.clone()
        c.non_zero_requested = self.non_zero_requested.clone()
        c.allocatable = self.allocatable
        c.used_ports = set(self.used_ports)
        c.image_sizes = dict(self.image_sizes)
        c.pvc_ref_counts = dict(self.pvc_ref_counts)
        c.generation = self.generation
        c.node_generation = self.node_generation
        return c


# --- queue bookkeeping ----------------------------------------------------

@dataclass(slots=True)
class QueuedPodInfo:
    """Queue wrapper (framework/types.go QueuedPodInfo)."""

    pod_info: PodInfo
    timestamp: float = field(default_factory=time.monotonic)
    initial_attempt_timestamp: float = field(default_factory=time.monotonic)
    attempts: int = 0
    unschedulable_plugins: set[str] = field(default_factory=set)
    gated: bool = False

    @property
    def pod(self) -> Obj:
        return self.pod_info.pod

    @property
    def key(self) -> str:
        return self.pod_info.key


@dataclass(slots=True)
class Diagnosis:
    """Why scheduling failed (framework/types.go Diagnosis)."""

    node_to_status: dict[str, Status] = field(default_factory=dict)
    unschedulable_plugins: set[str] = field(default_factory=set)
    pre_filter_msg: str = ""


class FitError(Exception):
    """No node fits (framework/types.go FitError)."""

    def __init__(self, pod: Obj, num_all_nodes: int, diagnosis: Diagnosis):
        self.pod = pod
        self.num_all_nodes = num_all_nodes
        self.diagnosis = diagnosis
        super().__init__(self.message())

    def message(self) -> str:
        reasons: dict[str, int] = {}
        for s in self.diagnosis.node_to_status.values():
            for r in s.reasons or [_CODE_NAMES[s.code]]:
                reasons[r] = reasons.get(r, 0) + 1
        detail = "; ".join(f"{n} {r}" for r, n in sorted(reasons.items()))
        return (f"0/{self.num_all_nodes} nodes are available: {detail or self.diagnosis.pre_filter_msg}")
