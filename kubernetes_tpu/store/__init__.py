"""Versioned store with watch (etcd-equivalent)."""

from .kv import (  # noqa: F401
    ADDED, MODIFIED, DELETED, BOOKMARK,
    AlreadyExistsError, ConflictError, MemoryStore, NotFoundError, StoreError,
    TooOldError, Watch, WatchEvent,
)
