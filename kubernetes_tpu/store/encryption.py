"""Envelope encryption at rest (the KMS provider integration).

Reference:
  staging/src/k8s.io/kms/ (KMS v2 gRPC EncryptRequest/DecryptRequest)
  staging/src/k8s.io/apiserver/pkg/storage/value/encrypt/envelope/ —
    per-object data-encryption keys (DEK) wrapped by the KMS-held
    key-encryption key (KEK); EncryptionConfiguration selects which
    resources are transformed (typically `secrets`).

Here: LocalKMS is the in-process stand-in for the external KMS plugin
(AES-256-GCM KEK, key id rotation supported); EnvelopeTransformer does
DEK-per-object envelope encryption of the VALUE while the store keeps
`metadata` in the clear (our etcd3-equivalent peeks at metadata for CAS /
key bookkeeping; the sensitive payload of a Secret lives under data/).
"""

from __future__ import annotations

import base64
import json
import os
import threading

ENVELOPE_KEY = "__k8s_tpu_envelope__"


class DecryptError(Exception):
    pass


class LocalKMS:
    """In-process KMS plugin: holds KEKs by key id (kms v2 Encrypt/Decrypt).

    rotate() adds a new KEK and makes it current; old key ids keep
    decrypting (the reference's multi-key DecryptRequest behavior).

    key_file (optional) persists the KEK ring so sealed objects recovered
    from a durable store stay decryptable across restarts — the role the
    external KMS's own storage plays for the reference; without it the
    keys are process-lifetime only (fine for a memory-only store)."""

    def __init__(self, key_file: str | None = None) -> None:
        self._lock = threading.Lock()
        self._keys: dict[str, bytes] = {}
        self._current = ""
        self._key_file = key_file
        if key_file and os.path.exists(key_file):
            with open(key_file) as f:
                ring = json.load(f)
            self._keys = {k: base64.b64decode(v) for k, v in
                          ring["keys"].items()}
            self._current = ring["current"]
        else:
            self.rotate()

    def rotate(self) -> str:
        with self._lock:
            kid = f"key-{len(self._keys) + 1}"
            self._keys[kid] = os.urandom(32)
            self._current = kid
            if self._key_file:
                tmp = self._key_file + ".tmp"
                with open(tmp, "w") as f:
                    os.fchmod(f.fileno(), 0o600)
                    json.dump({"current": kid,
                               "keys": {k: base64.b64encode(v).decode()
                                        for k, v in self._keys.items()}}, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._key_file)
            return kid

    @property
    def current_key_id(self) -> str:
        with self._lock:
            return self._current

    def encrypt(self, plaintext: bytes) -> tuple[str, bytes]:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        with self._lock:
            kid, kek = self._current, self._keys[self._current]
        nonce = os.urandom(12)
        return kid, nonce + AESGCM(kek).encrypt(nonce, plaintext, None)

    def decrypt(self, key_id: str, blob: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        with self._lock:
            kek = self._keys.get(key_id)
        if kek is None:
            raise DecryptError(f"unknown KMS key id {key_id!r}")
        try:
            return AESGCM(kek).decrypt(blob[:12], blob[12:], None)
        except Exception as e:
            raise DecryptError(str(e)) from e


class EnvelopeTransformer:
    """value/encrypt/envelope semantics: fresh DEK per write, DEK wrapped
    by the KMS KEK, AES-GCM for the payload."""

    def __init__(self, kms: LocalKMS):
        self.kms = kms

    def encrypt_obj(self, obj: dict) -> dict:
        """Returns the at-rest form: clear metadata + sealed payload."""
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        payload = {k: v for k, v in obj.items() if k != "metadata"}
        dek = os.urandom(32)
        nonce = os.urandom(12)
        ct = AESGCM(dek).encrypt(nonce,
                                 json.dumps(payload).encode(), None)
        kid, edek = self.kms.encrypt(dek)
        return {
            "metadata": obj.get("metadata", {}),
            ENVELOPE_KEY: {
                "kid": kid,
                "edek": base64.b64encode(edek).decode("ascii"),
                "nonce": base64.b64encode(nonce).decode("ascii"),
                "ct": base64.b64encode(ct).decode("ascii"),
            },
        }

    def decrypt_obj(self, stored: dict) -> dict:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        env = stored.get(ENVELOPE_KEY)
        if env is None:
            return stored  # written before encryption was enabled
        dek = self.kms.decrypt(env["kid"], base64.b64decode(env["edek"]))
        try:
            payload = json.loads(AESGCM(dek).decrypt(
                base64.b64decode(env["nonce"]),
                base64.b64decode(env["ct"]), None))
        except Exception as e:
            raise DecryptError(str(e)) from e
        out = dict(payload)
        out["metadata"] = stored.get("metadata", {})
        return out

    def is_encrypted(self, stored: dict) -> bool:
        return ENVELOPE_KEY in stored
