"""Versioned in-memory MVCC store with watch — the etcd3-equivalent.

Reference semantics this reproduces (not the implementation):
  staging/src/k8s.io/apiserver/pkg/storage/interfaces.go:159 (storage.Interface)
  staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:154,331,526,798
    (Create / GuaranteedUpdate CAS / GetList / Watch)
  staging/src/k8s.io/apiserver/pkg/storage/cacher/ (watch ring buffer,
    "too old resource version" -> client relists)

Design:
  * single monotonically-increasing int64 revision shared by all resources
    (like etcd's store revision); every write stamps the object's
    metadata.resourceVersion with it.
  * per-resource maps keyed by "ns/name".
  * optimistic concurrency: update/delete take an expected resourceVersion and
    raise ConflictError on mismatch (the CAS txn in etcd3/store.go:331).
  * watch: per-watcher unbounded-ish queue fed synchronously under the write
    lock (so event order == revision order); a bounded history ring lets
    watchers resume from a recent revision, older resumes raise TooOldError
    which informers answer by re-listing (reflector.go:256 semantics).

Thread-safe; all blocking happens in Watch.next(), never under the lock.

Durability (etcd WAL + snapshot equivalent, store/wal.py): pass
durable_dir= to persist every mutation to an append-only checksummed log
with periodic snapshot compaction; a restarted store recovers state + the
revision counter from disk, and watch resumes below the recovery floor
raise TooOldError (the serving history ring is process-local, exactly
like the reference's cacher atop a persistent etcd).

Object-sharing contract (same as client-go's informer cache): objects
RETURNED by get/list/watch are shared references and MUST NOT be mutated by
callers — mutate a deep copy and write it back.  Inbound objects on
create/update are deep-copied by the store, so the stored state is always
private.  This removes a deep copy from every read, which profiling shows
dominates end-to-end scheduling throughput.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Iterator

from ..api import meta
from ..api.meta import Obj
from . import wal as wal_mod

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"


class StoreError(Exception):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class FencedError(StoreError):
    """The store was fenced: a newer primary epoch exists (replication
    failover promoted a follower) and this instance must not accept
    writes — the split-brain guard etcd gets from raft terms
    (etcd3/store.go:798 sits on a raft log whose deposed leaders cannot
    commit).  Reads keep serving; rejoin as a follower to resume."""


class ConflictError(StoreError):
    """resourceVersion mismatch — caller should re-get and retry."""


class BindConflict(ConflictError):
    """A bind lost the optimistic race: the pod was claimed by a peer
    scheduler instance (Omega-style shared-state scheduling resolves
    multi-scheduler contention at commit time, not at dispatch time).

    Subclasses ConflictError so every existing 409/retry path keeps
    working; carries structured fields so the losing scheduler can
    classify the outcome without parsing the message — ``current_node``
    is who actually owns the pod now (None when only the resourceVersion
    precondition failed), ``wanted_node`` is where the caller tried to
    put it."""

    def __init__(self, message: str, *, key: str = "",
                 current_node: str | None = None,
                 wanted_node: str | None = None):
        super().__init__(message)
        self.key = key
        self.current_node = current_node
        self.wanted_node = wanted_node


class TooOldError(StoreError):
    """Requested watch revision has been compacted — caller must re-list."""


class WatchEvent:
    # _wire: lazily-cached serialized form ({"type","object"} JSON line).
    # One WatchEvent instance fans out to EVERY watcher of a resource
    # (plus the history ring), so the apiserver's watch streams used to
    # re-encode the same ~1KB object once per watcher per event; the
    # cache makes it once per event (server.py _serve_watch fills it for
    # the plain-identity case only — field-selected or version-converted
    # streams bypass it).
    __slots__ = ("type", "object", "revision", "_wire")

    def __init__(self, type_: str, obj: Obj, revision: int):
        self.type = type_
        self.object = obj
        self.revision = revision
        self._wire = None

    def __repr__(self) -> str:  # pragma: no cover
        return f"WatchEvent({self.type}, rv={self.revision}, {meta.namespaced_name(self.object)})"


class Watch:
    """A single watch stream. Iterate or call next(timeout)."""

    def __init__(self, store: "MemoryStore", resource: str):
        self._store = store
        self._resource = resource
        self._cond = threading.Condition()
        self._queue: deque[WatchEvent] = deque()
        self._stopped = False

    def _push(self, ev: WatchEvent) -> None:
        with self._cond:
            if not self._stopped:
                self._queue.append(ev)
                self._cond.notify()

    def _push_many(self, evs: list[WatchEvent]) -> None:
        """Deliver a write burst with ONE wakeup.  Waking a blocked consumer
        is a futex syscall (~10-20µs); per-event delivery made that the
        dominant cost of bulk store writes at bench scale."""
        with self._cond:
            if not self._stopped:
                self._queue.extend(evs)
                self._cond.notify()

    def next(self, timeout: float | None = None) -> WatchEvent | None:
        with self._cond:
            if not self._queue and not self._stopped:
                self._cond.wait(timeout)
            if self._queue:
                return self._queue.popleft()
            return None

    def next_batch(self, timeout: float | None = None) -> list[WatchEvent]:
        """Drain everything queued (blocking up to timeout for the first
        event).  Consumers that can apply events in bulk (informers) use
        this to amortize their own locking over a write burst."""
        with self._cond:
            if not self._queue and not self._stopped:
                self._cond.wait(timeout)
            if not self._queue:
                return []
            out = list(self._queue)
            self._queue.clear()
            return out

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._store._remove_watch(self._resource, self)

    @property
    def stopped(self) -> bool:
        return self._stopped

    def __iter__(self) -> Iterator[WatchEvent]:
        while True:
            ev = self.next()
            if ev is None:
                return
            yield ev


class MemoryStore:
    """The cluster store. One instance == one 'etcd'."""

    def __init__(self, history: int = 100_000, transformers: dict | None = None,
                 durable_dir: str | None = None, wal_fsync: bool = False,
                 compact_every: int = 200_000):
        self._lock = threading.RLock()
        self._rev = 0
        # resource -> {"ns/name": obj}
        self._data: dict[str, dict[str, Obj]] = {}
        # resource -> ring of WatchEvent for resumable watches
        self._history: dict[str, deque[WatchEvent]] = {}
        self._history_len = history
        # resource -> oldest revision still in history (compaction floor)
        self._watchers: dict[str, list[Watch]] = {}
        # resource -> EnvelopeTransformer (encryption.py): values of these
        # resources are sealed AT REST in the table; reads/watches serve
        # plaintext (the watch ring is a serving cache, like the reference's
        # cacher, and holds decrypted objects — at-rest covers the table)
        self._transformers = dict(transformers or {})
        # watch completeness floor: resumes below it must relist.  Starts
        # at the recovered revision after a restart (the in-memory history
        # ring did not survive, so pre-crash revisions are unobservable —
        # etcd compaction semantics).
        self._floor = 0
        self._wal = None
        self._repl = None  # replication hub (replica.ReplicationHub)
        # fencing state (replica.py failover): epoch is this store's
        # primary term — promotion bumps it; a fenced store rejects
        # writes until it rejoins the new primary as a follower
        self.epoch = 0
        self._fenced = False
        self._fence_reason = ""
        self._compact_every = compact_every
        self._snapshot_thread: threading.Thread | None = None
        if durable_dir is not None:
            rev, data, valid, replayed = wal_mod.WriteAheadLog.recover(
                durable_dir)
            self._rev = rev
            self._floor = rev
            self._data = {res: dict(tbl) for res, tbl in data.items()}
            self._wal = wal_mod.WriteAheadLog(durable_dir, fsync=wal_fsync,
                                              truncate_log_to=valid,
                                              pending_records=replayed)

    # -- internals -------------------------------------------------------

    def _table(self, resource: str) -> dict[str, Obj]:
        return self._data.setdefault(resource, {})

    def _seal(self, resource: str, obj: Obj) -> Obj:
        t = self._transformers.get(resource)
        return t.encrypt_obj(obj) if t is not None else obj

    def _open(self, resource: str, stored: Obj) -> Obj:
        t = self._transformers.get(resource)
        return t.decrypt_obj(stored) if t is not None else stored

    def _emit(self, resource: str, type_: str, obj: Obj) -> None:
        ev = WatchEvent(type_, obj, self._rev)
        hist = self._history.setdefault(resource, deque(maxlen=self._history_len))
        hist.append(ev)
        for w in self._watchers.get(resource, ()):  # synchronous, ordered
            w._push(ev)

    def _emit_many(self, resource: str, evs: list[WatchEvent]) -> None:
        """Bulk _emit: one history extend + one wakeup per watcher."""
        if not evs:
            return
        hist = self._history.setdefault(resource,
                                        deque(maxlen=self._history_len))
        hist.extend(evs)
        for w in self._watchers.get(resource, ()):
            w._push_many(evs)

    def _remove_watch(self, resource: str, w: Watch) -> None:
        with self._lock:
            try:
                self._watchers.get(resource, []).remove(w)
            except ValueError:
                pass

    @staticmethod
    def _key(obj_or_ns: Obj | str, nm: str | None = None) -> str:
        if isinstance(obj_or_ns, dict):
            return meta.namespaced_name(obj_or_ns)
        return f"{obj_or_ns}/{nm}" if obj_or_ns else (nm or "")

    @property
    def _logging(self) -> bool:
        """Should mutation sites build commit records?"""
        return self._wal is not None or self._repl is not None

    def _check_fence(self) -> None:
        """Raise on a fenced store (replica.py failover).  Called at the
        top of every write verb; the flag read is GIL-atomic so the
        un-fenced fast path costs one attribute load."""
        if self._fenced:
            raise FencedError(f"store fenced: {self._fence_reason}")

    def fence(self, reason: str) -> None:
        """Stop accepting writes (idempotent).  Reads/watches continue —
        a fenced deposed primary can still serve stale reads while the
        operator or failover logic re-points clients."""
        self._fence_reason = reason
        self._fenced = True

    def _commit(self, recs: list[tuple]) -> None:
        """Route committed mutation records (op, rev, resource, key, obj)
        to the WAL and any attached replication hub, under the store
        lock.  DELETE records carry the tombstone for the hub (follower
        watches need it); the WAL stores only the key.  Tombstones of
        encrypted-at-rest resources are stripped to metadata before they
        leave the process: PUTs ship sealed, and a plaintext delete tomb
        would defeat the envelope exactly once per object."""
        if self._wal is not None:
            self._wal.append_many(
                [r if r[0] == wal_mod.PUT else r[:4] for r in recs])
            self._maybe_compact()
        if self._repl is not None:
            if self._transformers:
                recs = [
                    r if (r[0] == wal_mod.PUT
                          or r[2] not in self._transformers
                          or len(r) < 5 or r[4] is None)
                    else (*r[:4], {"metadata": dict(r[4]["metadata"])})
                    for r in recs]
            self._repl.ship(recs)

    def _maybe_compact(self) -> None:
        """Kick off a snapshot once the log holds enough records that a
        replay would cost more than a snapshot load.  Called under the
        store lock right after an append.  Only the log rotation + a
        2-level state copy happen under the lock; serialization and disk
        writes run on a background thread (objects in the tables are
        immutable by the sharing contract, so the copy stays a consistent
        image of this revision).
        """
        if self._wal.records_since_snapshot < self._compact_every:
            return
        if self._snapshot_thread is not None and self._snapshot_thread.is_alive():
            return  # one snapshot in flight is enough
        rev, image = self._begin_snapshot_locked()
        t = threading.Thread(target=self._wal.finish_snapshot,
                             args=(rev, image), name="store-snapshot",
                             daemon=True)
        self._snapshot_thread = t
        t.start()

    def _begin_snapshot_locked(self) -> tuple[int, dict]:
        self._wal.begin_snapshot()
        return self._rev, {res: dict(tbl) for res, tbl in self._data.items()}

    # -- durability ------------------------------------------------------

    @property
    def durable(self) -> bool:
        return self._wal is not None

    def checkpoint(self) -> None:
        """Force a snapshot now (etcd `snapshot` / compaction); returns
        once it is on disk."""
        if self._wal is None:
            return
        t = None
        with self._lock:
            t = self._snapshot_thread
        if t is not None and t.is_alive():
            t.join()
        with self._lock:
            rev, image = self._begin_snapshot_locked()
        self._wal.finish_snapshot(rev, image)

    def close(self) -> None:
        t = self._snapshot_thread
        if t is not None and t.is_alive():
            t.join()
        with self._lock:
            if self._wal is not None:
                self._wal.close()

    # -- storage.Interface -----------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._rev

    def create(self, resource: str, obj: Obj) -> Obj:
        self._check_fence()
        with self._lock:
            key = meta.namespaced_name(obj)
            table = self._table(resource)
            if key in table:
                raise AlreadyExistsError(f"{resource} {key!r} already exists")
            obj = meta.deep_copy(obj)
            meta.finalize_new(obj)
            self._rev += 1
            meta.set_resource_version(obj, self._rev)
            sealed = self._seal(resource, obj)
            table[key] = sealed
            if self._logging:
                self._commit([(wal_mod.PUT, self._rev, resource, key,
                               sealed)])
            self._emit(resource, ADDED, obj)
            return obj

    def create_many(self, resource: str, objs: list[Obj],
                    copy: bool = True
                    ) -> list[tuple[Obj | None, StoreError | None]]:
        """Bulk create: one lock round trip, per-entry results.  Used by the
        event broadcaster to flush its buffer without taking the store lock
        once per event (the reference's EventBroadcaster batches through a
        single sink goroutine; here the lock is the serialization point).

        copy=False skips the inbound deep copy for callers that hand over
        OWNERSHIP of freshly-built objects they never touch again (the
        event broadcaster); the caller must guarantee no later mutation."""
        self._check_fence()
        out: list[tuple[Obj | None, StoreError | None]] = []
        evs: list[WatchEvent] = []
        recs: list[tuple] = []
        now = time.time()  # one clock read per burst (finalize semantics)
        transform = self._transformers.get(resource)
        with self._lock:
            logging_on = self._logging  # invariant while the lock is held
            table = self._table(resource)
            rev = self._rev
            for obj in objs:
                md = obj["metadata"]
                ns = md.get("namespace", "")
                key = f"{ns}/{md['name']}" if ns else md["name"]
                if key in table:
                    out.append((None, AlreadyExistsError(
                        f"{resource} {key!r} already exists")))
                    continue
                if copy:
                    obj = meta.deep_copy(obj)
                    md = obj["metadata"]
                if not md.get("uid"):
                    md["uid"] = meta.new_uid()
                if not md.get("creationTimestamp"):
                    md["creationTimestamp"] = now
                rev += 1
                md["resourceVersion"] = rev
                sealed = (transform.encrypt_obj(obj)
                          if transform is not None else obj)
                table[key] = sealed
                if logging_on:
                    recs.append((wal_mod.PUT, rev, resource, key, sealed))
                evs.append(WatchEvent(ADDED, obj, rev))
                out.append((obj, None))
            self._rev = rev
            if recs:
                self._commit(recs)
            self._emit_many(resource, evs)
        return out

    def get(self, resource: str, namespace: str, name: str) -> Obj:
        with self._lock:
            table = self._table(resource)
            key = self._key(namespace, name)
            if key not in table:
                raise NotFoundError(f"{resource} {key!r} not found")
            return self._open(resource, table[key])

    def update(self, resource: str, obj: Obj, expect_rv: int | None = None) -> Obj:
        """CAS update: expect_rv defaults to the object's own resourceVersion."""
        self._check_fence()
        with self._lock:
            table = self._table(resource)
            key = meta.namespaced_name(obj)
            if key not in table:
                raise NotFoundError(f"{resource} {key!r} not found")
            cur = table[key]
            want = expect_rv if expect_rv is not None else meta.resource_version(obj)
            if want and want != meta.resource_version(cur):
                raise ConflictError(
                    f"{resource} {key!r}: rv {want} != current {meta.resource_version(cur)}")
            obj = meta.deep_copy(obj)
            obj["metadata"]["uid"] = meta.uid(cur) or meta.uid(obj)
            obj["metadata"].setdefault("creationTimestamp", meta.creation_timestamp(cur))
            self._rev += 1
            meta.set_resource_version(obj, self._rev)
            # deleteWithoutFinalizers: stripping the last finalizer off a
            # terminating object completes its deletion
            if (obj["metadata"].get("deletionTimestamp")
                    and not obj["metadata"].get("finalizers")):
                del table[key]
                if self._logging:
                    self._commit([(wal_mod.DELETE, self._rev, resource,
                                   key, obj)])
                self._emit(resource, DELETED, obj)
                return obj
            sealed = self._seal(resource, obj)
            table[key] = sealed
            if self._logging:
                self._commit([(wal_mod.PUT, self._rev, resource, key,
                               sealed)])
            self._emit(resource, MODIFIED, obj)
            return obj

    def guaranteed_update(self, resource: str, namespace: str, name: str,
                          fn: Callable[[Obj], Obj], max_retries: int = 16) -> Obj:
        """GuaranteedUpdate (etcd3/store.go:331): get -> transform -> CAS, retry on conflict."""
        self._check_fence()
        for _ in range(max_retries):
            cur = self.get(resource, namespace, name)
            updated = fn(meta.deep_copy(cur))
            try:
                return self.update(resource, updated, expect_rv=meta.resource_version(cur))
            except ConflictError:
                continue
        raise ConflictError(f"{resource} {namespace}/{name}: too many CAS retries")

    def delete(self, resource: str, namespace: str, name: str,
               expect_rv: int | None = None) -> Obj:
        self._check_fence()
        with self._lock:
            table = self._table(resource)
            key = self._key(namespace, name)
            if key not in table:
                raise NotFoundError(f"{resource} {key!r} not found")
            cur = table[key]
            if expect_rv is not None and expect_rv != meta.resource_version(cur):
                raise ConflictError(f"{resource} {key!r}: stale delete")
            # finalizer semantics (registry/generic/registry/store.go Delete):
            # an object carrying finalizers is not removed — it gets a
            # deletionTimestamp and stays until a controller strips the last
            # finalizer (the update() path below then really deletes it)
            if cur["metadata"].get("finalizers"):
                if cur["metadata"].get("deletionTimestamp"):
                    return self._open(resource, cur)  # already terminating
                marked = dict(self._open(resource, cur))
                marked["metadata"] = dict(cur["metadata"])
                marked["metadata"]["deletionTimestamp"] = time.time()
                self._rev += 1
                meta.set_resource_version(marked, self._rev)
                sealed = self._seal(resource, marked)
                table[key] = sealed
                if self._logging:
                    self._commit([(wal_mod.PUT, self._rev, resource, key,
                                   sealed)])
                self._emit(resource, MODIFIED, marked)
                return marked
            del table[key]
            self._rev += 1
            # tombstone: shallow copy with fresh metadata (readers may still
            # hold the stored object; never mutate it in place)
            tomb = dict(self._open(resource, cur))
            tomb["metadata"] = dict(cur["metadata"])
            meta.set_resource_version(tomb, self._rev)
            if self._logging:
                self._commit([(wal_mod.DELETE, self._rev, resource, key,
                               tomb)])
            self._emit(resource, DELETED, tomb)
            return tomb

    def bind_many(self, resource: str,
                  bindings: list[tuple]
                  ) -> list[tuple[Obj | None, StoreError | None]]:
        """Bulk Binding write: one lock round trip for a whole TPU batch.

        Each (namespace, name, node_name[, expect_rv]) entry follows
        BindingREST semantics (pkg/registry/core/pod/storage — fail if the
        pod is already bound); results are per-entry so one conflict doesn't
        poison the batch.  The reference has no bulk verb (scheduler binds
        one pod per goroutine); batched assignment makes the 1-write-per-pod
        pattern the bottleneck, so the store grows a transactional
        multi-bind instead.

        Compare-and-bind: an entry whose pod already carries spec.nodeName —
        or, when the optional 4th element expect_rv is given, whose stored
        resourceVersion moved past it — returns a structured BindConflict
        instead of silently double-binding, so N scheduler instances can
        commit optimistically against one shared store and losers detect it.
        """
        self._check_fence()
        out: list[tuple[Obj | None, StoreError | None]] = []
        evs: list[WatchEvent] = []
        recs: list[tuple] = []
        transform = self._transformers.get(resource)
        with self._lock:
            logging_on = self._logging  # invariant while the lock is held
            table = self._table(resource)
            rev = self._rev
            for entry in bindings:
                ns, nm, node = entry[0], entry[1], entry[2]
                expect_rv = entry[3] if len(entry) > 3 else None
                key = f"{ns}/{nm}" if ns else nm
                if not node:
                    # a falsy nodeName would store a bind that every
                    # reader treats as "unbound" — the pod is silently
                    # lost (seen under churn when a caller resolves a
                    # name across a node's in-place removal).  Refuse
                    # loudly; the scheduler's failure path requeues.
                    out.append((None, StoreError(
                        f"bind {key!r}: empty node name refused")))
                    continue
                cur = table.get(key)
                if cur is None:
                    out.append((None, NotFoundError(
                        f"{resource} {key!r} not found")))
                    continue
                if transform is not None:
                    cur = transform.decrypt_obj(cur)
                if (cur.get("spec") or {}).get("nodeName"):
                    bound_to = cur["spec"]["nodeName"]
                    out.append((None, BindConflict(
                        f"pod {key!r} is already bound to {bound_to!r}",
                        key=key, current_node=bound_to, wanted_node=node)))
                    continue
                if expect_rv is not None and \
                        cur["metadata"].get("resourceVersion") != expect_rv:
                    out.append((None, BindConflict(
                        f"pod {key!r} moved past resourceVersion "
                        f"{expect_rv!r} (now "
                        f"{cur['metadata'].get('resourceVersion')!r})",
                        key=key, current_node=None, wanted_node=node)))
                    continue
                # 2-level copy, not deep: only metadata/spec/status own
                # mutated slots; nested values are shared with the prior
                # stored object, which is safe under the read contract
                # (returned objects are never mutated in place — the store
                # itself always writes fresh containers)
                status = cur.get("status") or {}
                rev += 1
                obj = {**cur,
                       "metadata": {**cur["metadata"], "resourceVersion": rev},
                       "spec": {**(cur.get("spec") or {}), "nodeName": node},
                       "status": {**status,
                                  "conditions": list(status.get(
                                      "conditions") or ()) + [
                                      {"type": "PodScheduled",
                                       "status": "True"}]}}
                sealed = (transform.encrypt_obj(obj)
                          if transform is not None else obj)
                table[key] = sealed
                if logging_on:
                    recs.append((wal_mod.PUT, rev, resource, key, sealed))
                evs.append(WatchEvent(MODIFIED, obj, rev))
                out.append((obj, None))
            self._rev = rev
            if recs:
                self._commit(recs)
            self._emit_many(resource, evs)
        return out

    def list(self, resource: str, namespace: str | None = None) -> tuple[list[Obj], int]:
        """GetList (etcd3/store.go:526): returns (items, list revision)."""
        with self._lock:
            table = self._table(resource)
            t = self._transformers.get(resource)
            if namespace:
                prefix = namespace + "/"
                items = [o for k, o in table.items() if k.startswith(prefix)]
            else:
                items = list(table.values())
            if t is not None:  # decrypt only transformed resources
                items = [t.decrypt_obj(o) for o in items]
            return items, self._rev

    def count(self, resource: str) -> int:
        with self._lock:
            return len(self._table(resource))

    def watch(self, resource: str, since_rv: int | None = None) -> Watch:
        """Open a watch delivering every event with revision > since_rv.

        since_rv=None means "from now".  since_rv=0 is a real revision (the
        rv an empty-store list returns) and replays ALL retained history —
        conflating it with "from now" loses events created between a client's
        list and the watch registration.  Raises TooOldError if since_rv
        predates the retained history (client must re-list, reflector.go
        semantics).
        """
        with self._lock:
            w = Watch(self, resource)
            hist = self._history.get(resource)
            if since_rv is not None and since_rv < self._floor:
                # revisions below the floor predate this process (the
                # history ring died with the previous one) — the client
                # cannot be given a complete replay, so it must relist
                raise TooOldError(
                    f"watch {resource} from rv {since_rv}: compacted "
                    f"(recovery floor {self._floor})")
            if since_rv is not None and hist:
                # If the ring is full, events older than hist[0] were dropped;
                # we can only guarantee completeness for since_rv at or past
                # hist[0].revision - 1 (conservative, like etcd compaction).
                if len(hist) == hist.maxlen and since_rv < hist[0].revision - 1:
                    raise TooOldError(f"watch {resource} from rv {since_rv}: compacted")
                for ev in hist:
                    if ev.revision > since_rv:
                        w._push(ev)
            self._watchers.setdefault(resource, []).append(w)
            return w
