"""WAL-shipping store replication: a follower that serves reads/watches
and can be promoted when the primary dies.

Reference: the reference's storage.Interface lands on a raft-replicated
etcd quorum (staging/.../storage/etcd3/store.go; etcd's raft log), so a
member loss never loses committed writes and watches survive failover.
This is the single-follower equivalent for the single-writer store
(store/kv.py): every committed mutation record (the same tuples the WAL
appends) is SHIPPED to connected followers; in sync mode (default) the
primary's commit blocks until the follower acknowledges the record's
revision, so an acknowledged client write is on at least two stores —
kill the primary, promote the follower, and informers relist against it
with zero lost committed writes (tests/test_store_replica.py runs that
chaos sequence).

Protocol (length-prefixed JSON frames over TCP):
  follower -> primary   {"type": "hello", "rev": <highest applied>}
  primary  -> follower  {"type": "snapshot", "rev": N, "data": {...}}
  primary  -> follower  {"type": "recs", "recs": [[op, rev, res, key,
                         obj], ...]}
  follower -> primary   {"type": "ack", "rev": N}
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading

from . import kv
from . import wal as wal_mod

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 512 << 20


def _send_frame(sock: socket.socket, payload: dict) -> None:
    data = json.dumps(payload).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> dict | None:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (size,) = _LEN.unpack(head)
    if size > MAX_FRAME:
        raise OSError(f"replication frame {size} exceeds cap")
    body = _recv_exact(sock, size)
    if body is None:
        return None
    return json.loads(body)


class _FollowerConn:
    """Primary-side state for one connected follower."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.acked_rev = 0
        self.lock = threading.Lock()  # serializes sends
        self.dead = False


class ReplicationHub:
    """Attached to the PRIMARY store: accepts follower connections,
    bootstraps them with a snapshot, ships commit records, and (in sync
    mode) blocks the committing writer until the newest record is
    acknowledged.

    sync_timeout bounds how long a commit waits for a follower: a dead
    or lagging follower degrades the primary to async shipping (logged)
    instead of freezing the cluster — the availability/durability trade
    etcd resolves with quorum, degraded here to primary-keeps-serving.
    """

    def __init__(self, store: kv.MemoryStore, host: str = "127.0.0.1",
                 port: int = 0, sync: bool = True,
                 sync_timeout: float = 2.0):
        self.store = store
        self.sync = sync
        self.sync_timeout = sync_timeout
        self._followers: list[_FollowerConn] = []
        self._flock = threading.Lock()
        self._ack_cond = threading.Condition(self._flock)
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(4)
        self.address = self._listener.getsockname()
        self._stopped = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="repl-accept", daemon=True)

    def start(self) -> "ReplicationHub":
        self.store._repl = self
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        if self.store._repl is self:
            self.store._repl = None
        try:
            self._listener.close()
        except OSError:
            pass
        with self._flock:
            for f in self._followers:
                f.dead = True
                try:
                    f.sock.close()
                except OSError:
                    pass
            self._followers.clear()
            self._ack_cond.notify_all()

    @property
    def follower_count(self) -> int:
        with self._flock:
            return len(self._followers)

    # -- primary side -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_follower,
                             args=(sock, addr), daemon=True,
                             name="repl-follower").start()

    def _serve_follower(self, sock: socket.socket, addr) -> None:
        conn = _FollowerConn(sock, addr)
        try:
            hello = _recv_frame(sock)
            if not hello or hello.get("type") != "hello":
                sock.close()
                return
            # Registration and the snapshot send happen under conn.lock:
            # a commit racing the bootstrap blocks in ship() on that lock
            # until the snapshot frame is fully on the wire, so the
            # stream can neither interleave bytes mid-frame nor deliver
            # 'recs' before 'snapshot'.  The image itself is captured
            # under the store lock (consistent at one revision), and the
            # follower registers before that lock drops, so nothing
            # committed after the image can be missed.
            with conn.lock:
                with self.store._lock:
                    image = {res: dict(tbl)
                             for res, tbl in self.store._data.items()}
                    rev = self.store._rev
                    with self._flock:
                        self._followers.append(conn)
                _send_frame(sock, {"type": "snapshot", "rev": rev,
                                   "data": image})
            conn.acked_rev = rev
        except OSError:
            self._drop(conn)
            return
        # ack reader loop
        try:
            while not conn.dead:
                try:
                    frame = _recv_frame(sock)
                except TimeoutError:
                    # a concurrent ship() temporarily put a send timeout
                    # on the shared socket; ack frames are single-write
                    # tiny, so a quiet-stream timeout is retryable
                    continue
                if frame is None:
                    break
                if frame.get("type") == "ack":
                    with self._flock:
                        conn.acked_rev = max(conn.acked_rev,
                                             int(frame.get("rev", 0)))
                        self._ack_cond.notify_all()
        except OSError:
            pass
        finally:
            self._drop(conn)

    def _drop(self, conn: _FollowerConn) -> None:
        conn.dead = True
        try:
            conn.sock.close()
        except OSError:
            pass
        with self._flock:
            if conn in self._followers:
                self._followers.remove(conn)
                logger.warning("replication follower %s dropped",
                               conn.addr)
            self._ack_cond.notify_all()

    def ship(self, recs: list[tuple]) -> None:
        """Called by the store under ITS lock for every commit.  Sends
        the records to every follower; in sync mode, waits until some
        follower acknowledges the newest revision (or the timeout
        passes — degraded async, logged)."""
        with self._flock:
            followers = list(self._followers)
        if not followers:
            return
        top_rev = max(r[1] for r in recs)
        payload = {"type": "recs", "recs": [list(r) for r in recs]}
        for f in followers:
            try:
                with f.lock:
                    # bound the SEND too: a stalled (SIGSTOPped) follower
                    # fills its TCP window and an untimed sendall would
                    # freeze the whole store under its lock.  The ack
                    # reader tolerates the transient recv timeout this
                    # may impose (frames are tiny/atomic in practice).
                    f.sock.settimeout(self.sync_timeout)
                    try:
                        _send_frame(f.sock, payload)
                    finally:
                        try:
                            f.sock.settimeout(None)
                        except OSError:
                            pass
            except OSError:
                self._drop(f)
        if not self.sync:
            return
        import time
        deadline = time.monotonic() + self.sync_timeout
        with self._flock:
            while not self._stopped:
                live = [f for f in self._followers if not f.dead]
                if not live:
                    return  # no follower left: primary-only, keep serving
                if any(f.acked_rev >= top_rev for f in live):
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        "replication sync ack timed out at rev %d; "
                        "degrading this commit to async", top_rev)
                    return
                self._ack_cond.wait(remaining)


class FollowerStore(kv.MemoryStore):
    """A read-only replica fed by a ReplicationHub stream.

    Serves get/list/watch like any MemoryStore (informers point at it
    via LocalClient or an APIServer); every write verb raises until
    promote() flips it into a writable primary that continues from the
    last applied revision.  A promoted follower can carry its own WAL
    (durable_dir) and its own ReplicationHub — the next follower in the
    chain."""

    def __init__(self, history: int = 100_000,
                 transformers: dict | None = None,
                 durable_dir: str | None = None):
        super().__init__(history=history, transformers=transformers,
                         durable_dir=durable_dir)
        self._promoted = False
        self._conn: socket.socket | None = None
        self._follow_thread: threading.Thread | None = None
        self._synced = threading.Event()

    # -- write fencing ----------------------------------------------------

    def _check_writable(self) -> None:
        if not self._promoted:
            raise kv.StoreError("store is a read-only replica "
                                "(promote() to accept writes)")

    def create(self, *a, **k):
        self._check_writable()
        return super().create(*a, **k)

    def create_many(self, *a, **k):
        self._check_writable()
        return super().create_many(*a, **k)

    def update(self, *a, **k):
        self._check_writable()
        return super().update(*a, **k)

    def delete(self, *a, **k):
        self._check_writable()
        return super().delete(*a, **k)

    def bind_many(self, *a, **k):
        self._check_writable()
        return super().bind_many(*a, **k)

    def guaranteed_update(self, *a, **k):
        self._check_writable()
        return super().guaranteed_update(*a, **k)

    # -- following --------------------------------------------------------

    def follow(self, host: str, port: int,
               timeout: float = 10.0) -> "FollowerStore":
        """Connect to the primary's ReplicationHub and start applying
        its stream; returns once the bootstrap snapshot is installed."""
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conn = sock
        _send_frame(sock, {"type": "hello", "rev": self._rev})
        snap = _recv_frame(sock)
        if not snap or snap.get("type") != "snapshot":
            raise kv.StoreError("replication bootstrap failed")
        with self._lock:
            self._data = {res: dict(tbl)
                          for res, tbl in (snap.get("data") or {}).items()}
            self._rev = int(snap.get("rev", 0))
            self._floor = self._rev  # pre-snapshot revisions unobservable
        sock.settimeout(None)
        self._synced.set()
        self._follow_thread = threading.Thread(
            target=self._follow_loop, name="repl-follow", daemon=True)
        self._follow_thread.start()
        return self

    def _follow_loop(self) -> None:
        sock = self._conn
        try:
            while not self._promoted:
                frame = _recv_frame(sock)
                if frame is None:
                    logger.warning("replication stream closed by primary")
                    return
                if frame.get("type") != "recs":
                    continue
                recs = frame.get("recs") or []
                self._apply_records(recs)
                top = max((int(r[1]) for r in recs), default=0)
                if top:
                    _send_frame(sock, {"type": "ack", "rev": top})
        except OSError as e:
            if not self._promoted:
                logger.warning("replication stream error: %s", e)

    def _apply_records(self, recs: list) -> None:
        """Replay shipped commit records: table writes + watch emission,
        exactly the primary's commit effects (objects arrive sealed; the
        watch ring serves opened plaintext like the primary's).  The
        records also re-enter _commit, so a follower with its own WAL
        persists them and a chained downstream follower receives them."""
        with self._lock:
            for rec in recs:
                op, rev, resource, key = rec[0], int(rec[1]), rec[2], rec[3]
                obj = rec[4] if len(rec) > 4 else None
                table = self._table(resource)
                self._rev = max(self._rev, rev)
                if op == wal_mod.PUT:
                    existed = key in table
                    table[key] = obj
                    self._emit(resource,
                               kv.MODIFIED if existed else kv.ADDED,
                               self._open(resource, obj))
                else:  # DELETE; obj is the tombstone (may be None from
                    table.pop(key, None)       # an old-format primary)
                    tomb = obj or {"metadata": {
                        "name": key.rpartition("/")[2],
                        "namespace": key.rpartition("/")[0],
                        "resourceVersion": rev}}
                    self._emit(resource, kv.DELETED, tomb)
            if self._logging:
                self._commit([tuple(r) for r in recs])

    # -- promotion --------------------------------------------------------

    def promote(self) -> "FollowerStore":
        """Become the writable primary: stop following, accept writes,
        continue the revision sequence from the last applied record.
        Watches opened against this store stay attached; informers of
        clients that re-point here relist and resume."""
        self._promoted = True
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        logger.warning("follower promoted to primary at rev %d", self._rev)
        return self
