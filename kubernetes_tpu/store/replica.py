"""WAL-shipping store replication: a follower that serves reads/watches
and can be promoted when the primary dies.

Reference: the reference's storage.Interface lands on a raft-replicated
etcd quorum (staging/.../storage/etcd3/store.go; etcd's raft log), so a
member loss never loses committed writes and watches survive failover.
This is the single-follower equivalent for the single-writer store
(store/kv.py): every committed mutation record (the same tuples the WAL
appends) is SHIPPED to connected followers; in sync mode (default) the
primary's commit blocks until the follower acknowledges the record's
revision, so an acknowledged client write is on at least two stores —
kill the primary, promote the follower, and informers relist against it
with zero lost committed writes (tests/test_store_replica.py runs that
chaos sequence).

Protocol (length-prefixed JSON frames over TCP):
  follower -> primary   {"type": "hello", "rev": <highest applied>,
                         "epoch": <highest seen>}
  primary  -> follower  {"type": "snapshot", "rev": N, "epoch": E,
                         "data": {...}}
  primary  -> follower  {"type": "recs", "epoch": E, "recs": [[op, rev,
                         res, key, obj], ...]}
  primary  -> follower  {"type": "ping", "epoch": E}      (heartbeat)
  primary  -> follower  {"type": "fenced", "epoch": E}    (refusal)
  follower -> primary   {"type": "ack", "rev": N}

Failover (round 5; the etcd-raft capability the single-follower seam
was missing — VERDICT r4 item #6):

  * Every frame carries the primary's EPOCH (its term).  A follower
    tracks the highest epoch it has seen and drops a stream whose epoch
    is lower — a deposed primary's records can never be applied.
  * auto_promote_after(grace): a follower-side failure detector — when
    the stream (recs OR heartbeat pings) goes silent for `grace`
    seconds, the follower promotes itself with epoch+1.
  * fencing=True on the hub: an acked write is then GUARANTEED on the
    follower — a sync-ack timeout FENCES the primary (store raises
    FencedError to that writer and every later one) instead of
    degrading to async.  The fenced table may hold a tail of dirty
    never-acked writes; they are discarded by the snapshot when the
    deposed primary rejoins.  Pick grace > sync_timeout so the old
    primary stops acking before the follower starts a new term.
  * rejoin(): a deposed (fenced) primary re-enters as a follower of the
    new primary; a hello claiming a HIGHER epoch than the hub's own
    fences the HUB instead (it is the stale side of the partition).
"""

from __future__ import annotations

import json
import logging
import socket
import struct
import threading
import time

from . import kv
from . import wal as wal_mod

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 512 << 20


def _send_frame(sock: socket.socket, payload: dict) -> None:
    data = json.dumps(payload).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """Read exactly n bytes.  TimeoutError mid-buffer RETRIES instead of
    discarding: a concurrent ship()/heartbeat legitimately toggles a
    send timeout on the shared socket, and dropping partial bytes would
    desync the frame stream permanently (observed: primary ack reader
    lost framing under load and fenced a healthy pair).  A timeout at a
    clean frame boundary propagates so callers can treat it as 'no
    frame right now'."""
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except (TimeoutError, BlockingIOError):
            # BlockingIOError: defense against a concurrent settimeout
            # flipping the fd's O_NONBLOCK under a blocking-mode recv
            if buf:
                continue  # mid-frame: keep what we have, keep reading
            raise TimeoutError("no frame")
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> dict | None:
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (size,) = _LEN.unpack(head)
    if size > MAX_FRAME:
        raise OSError(f"replication frame {size} exceeds cap")
    while True:
        try:
            body = _recv_exact(sock, size)
            break
        except TimeoutError:
            continue  # head consumed: the body MUST be read to keep framing
    if body is None:
        return None
    return json.loads(body)


class _FollowerConn:
    """Primary-side state for one connected follower."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = sock
        self.addr = addr
        self.acked_rev = 0
        self.lock = threading.Lock()  # serializes sends
        self.dead = False


class ReplicationHub:
    """Attached to the PRIMARY store: accepts follower connections,
    bootstraps them with a snapshot, ships commit records, and (in sync
    mode) blocks the committing writer until the newest record is
    acknowledged.

    sync_timeout bounds how long a commit waits for a follower: a dead
    or lagging follower degrades the primary to async shipping (logged)
    instead of freezing the cluster — the availability/durability trade
    etcd resolves with quorum, degraded here to primary-keeps-serving.
    """

    def __init__(self, store: kv.MemoryStore, host: str = "127.0.0.1",
                 port: int = 0, sync: bool = True,
                 sync_timeout: float = 2.0, fencing: bool = False,
                 heartbeat_interval: float = 0.25):
        self.store = store
        self.sync = sync
        self.sync_timeout = sync_timeout
        # fencing mode: an acked write is guaranteed replicated — a sync
        # ack timeout fences this primary instead of degrading to async
        self.fencing = fencing
        self.heartbeat_interval = heartbeat_interval
        self._followers: list[_FollowerConn] = []
        self._flock = threading.Lock()
        self._ack_cond = threading.Condition(self._flock)
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(4)
        self.address = self._listener.getsockname()
        self._stopped = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="repl-accept", daemon=True)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="repl-heartbeat", daemon=True)

    @property
    def epoch(self) -> int:
        return self.store.epoch

    def start(self) -> "ReplicationHub":
        self.store._repl = self
        self._accept_thread.start()
        self._hb_thread.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        if self.store._repl is self:
            self.store._repl = None
        try:
            self._listener.close()
        except OSError:
            pass
        with self._flock:
            for f in self._followers:
                f.dead = True
                try:
                    f.sock.close()
                except OSError:
                    pass
            self._followers.clear()
            self._ack_cond.notify_all()

    def _heartbeat_loop(self) -> None:
        """Liveness signal for follower-side failure detectors: followers
        promote on stream SILENCE, so an idle-but-healthy primary must
        keep the stream warm."""
        while not self._stopped:
            time.sleep(self.heartbeat_interval)
            with self._flock:
                followers = list(self._followers)
            ping = {"type": "ping", "epoch": self.epoch}
            for f in followers:
                try:
                    with f.lock:
                        _send_frame(f.sock, ping)
                except OSError:
                    self._drop(f)

    @property
    def follower_count(self) -> int:
        with self._flock:
            return len(self._followers)

    # -- primary side -----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_follower,
                             args=(sock, addr), daemon=True,
                             name="repl-follower").start()

    def _serve_follower(self, sock: socket.socket, addr) -> None:
        conn = _FollowerConn(sock, addr)
        try:
            hello = _recv_frame(sock)
            if not hello or hello.get("type") != "hello":
                sock.close()
                return
            claimed = int(hello.get("epoch", 0))
            if claimed > self.epoch:
                # the connecting "follower" has seen a newer primary term
                # than ours: WE are the stale side of a healed partition.
                # Fence ourselves and refuse the stream.
                self.store.fence(
                    f"follower {addr} reports epoch {claimed} > "
                    f"our {self.epoch}")
                try:
                    _send_frame(sock, {"type": "fenced",
                                       "epoch": claimed})
                finally:
                    sock.close()
                return
            # Registration and the snapshot send happen under conn.lock:
            # a commit racing the bootstrap blocks in ship() on that lock
            # until the snapshot frame is fully on the wire, so the
            # stream can neither interleave bytes mid-frame nor deliver
            # 'recs' before 'snapshot'.  The image itself is captured
            # under the store lock (consistent at one revision), and the
            # follower registers before that lock drops, so nothing
            # committed after the image can be missed.
            with conn.lock:
                with self.store._lock:
                    image = {res: dict(tbl)
                             for res, tbl in self.store._data.items()}
                    rev = self.store._rev
                    with self._flock:
                        self._followers.append(conn)
                _send_frame(sock, {"type": "snapshot", "rev": rev,
                                   "epoch": self.epoch, "data": image})
            conn.acked_rev = rev
            # ONE permanent timeout for this connection from here on:
            # ship()/heartbeat sends are bounded by it, and the ack
            # reader retries through it.  Toggling settimeout per send
            # (the old scheme) flips O_NONBLOCK under the reader's feet
            # — a recv that starts in the toggle window gets EAGAIN.
            sock.settimeout(self.sync_timeout)
        except OSError:
            self._drop(conn)
            return
        # ack reader loop
        try:
            while not conn.dead:
                try:
                    frame = _recv_frame(sock)
                except TimeoutError:
                    # a concurrent ship() temporarily put a send timeout
                    # on the shared socket; ack frames are single-write
                    # tiny, so a quiet-stream timeout is retryable
                    continue
                if frame is None:
                    break
                if frame.get("type") == "ack":
                    with self._flock:
                        conn.acked_rev = max(conn.acked_rev,
                                             int(frame.get("rev", 0)))
                        self._ack_cond.notify_all()
        except OSError:
            pass
        finally:
            self._drop(conn)

    def _drop(self, conn: _FollowerConn) -> None:
        conn.dead = True
        try:
            conn.sock.close()
        except OSError:
            pass
        with self._flock:
            if conn in self._followers:
                self._followers.remove(conn)
                logger.warning("replication follower %s dropped",
                               conn.addr)
            self._ack_cond.notify_all()

    def ship(self, recs: list[tuple]) -> None:
        """Called by the store under ITS lock for every commit.  Sends
        the records to every follower; in sync mode, waits until some
        follower acknowledges the newest revision (or the timeout
        passes — degraded async, logged)."""
        with self._flock:
            followers = list(self._followers)
        if not followers:
            if self.fencing:
                # fencing contract: an acked write IS on a follower; with
                # none connected this commit cannot be guaranteed — fence
                # now so the writer sees the failure instead of an ack
                # (the already-applied table mutation is a dirty
                # never-acked tail, discarded at rejoin())
                self.store.fence("no follower connected for a fencing-"
                                 "mode commit")
                raise kv.FencedError(
                    "store fenced: no follower to guarantee the write")
            return
        top_rev = max(r[1] for r in recs)
        payload = {"type": "recs", "epoch": self.epoch,
                   "recs": [list(r) for r in recs]}
        for f in followers:
            try:
                with f.lock:
                    # the connection's permanent timeout bounds this
                    # send: a stalled (SIGSTOPped) follower fills its
                    # TCP window and an untimed sendall would freeze
                    # the whole store under its lock
                    _send_frame(f.sock, payload)
            except OSError:
                self._drop(f)
        if not self.sync:
            return
        deadline = time.monotonic() + self.sync_timeout
        with self._flock:
            while not self._stopped:
                live = [f for f in self._followers if not f.dead]
                if not live:
                    break  # all followers died mid-wait
                if any(f.acked_rev >= top_rev for f in live):
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._ack_cond.wait(remaining)
            else:
                return  # hub stopped: shutdown path, not a failure
        if self.fencing:
            # the ack never came: fence so THIS writer (and all later
            # ones) fail instead of acking a write the new primary may
            # never have — raft's "deposed leader cannot commit"
            self.store.fence(
                f"replication ack timeout at rev {top_rev}")
            raise kv.FencedError(
                f"store fenced: rev {top_rev} unacknowledged")
        logger.warning("replication sync ack timed out at rev %d; "
                       "degrading this commit to async", top_rev)


class FollowerStore(kv.MemoryStore):
    """A read-only replica fed by a ReplicationHub stream.

    Serves get/list/watch like any MemoryStore (informers point at it
    via LocalClient or an APIServer); every write verb raises until
    promote() flips it into a writable primary that continues from the
    last applied revision.  A promoted follower can carry its own WAL
    (durable_dir) and its own ReplicationHub — the next follower in the
    chain."""

    def __init__(self, history: int = 100_000,
                 transformers: dict | None = None,
                 durable_dir: str | None = None):
        super().__init__(history=history, transformers=transformers,
                         durable_dir=durable_dir)
        self._promoted = False
        self._conn: socket.socket | None = None
        self._follow_thread: threading.Thread | None = None
        self._synced = threading.Event()
        # failover state: highest primary epoch observed on the stream,
        # last time any frame arrived (the failure detector's signal),
        # and the watchdog thread auto_promote_after starts
        self._seen_epoch = 0
        self._last_frame = 0.0
        self._watchdog: threading.Thread | None = None
        self._watchdog_grace: float | None = None
        self._watchdog_stop = threading.Event()
        self.promoted_event = threading.Event()

    # -- write fencing ----------------------------------------------------

    def _check_writable(self) -> None:
        if not self._promoted:
            raise kv.StoreError("store is a read-only replica "
                                "(promote() to accept writes)")

    def create(self, *a, **k):
        self._check_writable()
        return super().create(*a, **k)

    def create_many(self, *a, **k):
        self._check_writable()
        return super().create_many(*a, **k)

    def update(self, *a, **k):
        self._check_writable()
        return super().update(*a, **k)

    def delete(self, *a, **k):
        self._check_writable()
        return super().delete(*a, **k)

    def bind_many(self, *a, **k):
        self._check_writable()
        return super().bind_many(*a, **k)

    def guaranteed_update(self, *a, **k):
        self._check_writable()
        return super().guaranteed_update(*a, **k)

    # -- following --------------------------------------------------------

    def follow(self, host: str, port: int,
               timeout: float = 10.0) -> "FollowerStore":
        """Connect to the primary's ReplicationHub and start applying
        its stream; returns once the bootstrap snapshot is installed."""
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conn = sock
        _send_frame(sock, {"type": "hello", "rev": self._rev,
                           "epoch": max(self._seen_epoch, self.epoch)})
        snap = _recv_frame(sock)
        if not snap:
            raise kv.StoreError("replication bootstrap failed")
        if snap.get("type") == "fenced":
            raise kv.FencedError(
                "primary refused the stream: it fenced itself against "
                f"our epoch {max(self._seen_epoch, self.epoch)}")
        if snap.get("type") != "snapshot":
            raise kv.StoreError("replication bootstrap failed")
        new_data = {res: dict(tbl)
                    for res, tbl in (snap.get("data") or {}).items()}
        snap_rev = int(snap.get("rev", 0))
        with self._lock:
            old_data = self._data
            if self._rev > 0 or any(old_data.values()):
                # Rejoin (this store served — or followed — a previous
                # term): attached watchers hold a view that may contain a
                # dirty never-acked tail, and the new primary's revision
                # sequence can numerically overlap it.  Installing the
                # snapshot wholesale would leave those watchers with
                # stale keys forever and let later emissions run
                # backwards.  Instead: restart the watch ring (resumes
                # from old-term revisions must relist — TooOldError), and
                # converge attached watchers onto the snapshot with
                # synthesized diff events stamped ABOVE everything they
                # have seen, so the stream stays strictly monotonic.
                base = max(self._rev, snap_rev)
                self._history.clear()
                self._floor = base
                for res in sorted(set(old_data) | set(new_data)):
                    old_tbl = old_data.get(res) or {}
                    new_tbl = new_data.get(res) or {}
                    evs: list[kv.WatchEvent] = []
                    for key in sorted(old_tbl):
                        if key in new_tbl:
                            continue
                        base += 1
                        opened = self._open(res, old_tbl[key])
                        tomb = dict(opened)
                        tomb["metadata"] = dict(
                            opened.get("metadata") or {})
                        tomb["metadata"]["resourceVersion"] = base
                        evs.append(kv.WatchEvent(kv.DELETED, tomb, base))
                    for key in sorted(new_tbl):
                        stored = new_tbl[key]
                        stale = old_tbl.get(key)
                        opened = self._open(res, stored)
                        if stale is not None:
                            stale_rv = (self._open(res, stale).get(
                                "metadata") or {}).get("resourceVersion")
                            if stale_rv == (opened.get("metadata")
                                            or {}).get("resourceVersion"):
                                continue  # watcher view already current
                        base += 1
                        evs.append(kv.WatchEvent(
                            kv.MODIFIED if stale is not None else kv.ADDED,
                            opened, base))
                    self._emit_many(res, evs)
                self._data = new_data
                self._rev = base
            else:
                # first bootstrap of a fresh follower: nobody watched the
                # empty store, plain install
                self._data = new_data
                self._rev = snap_rev
                self._floor = snap_rev  # pre-snapshot revs unobservable
            self._seen_epoch = max(self._seen_epoch,
                                   int(snap.get("epoch", 0)))
        self._last_frame = time.monotonic()
        sock.settimeout(None)
        self._synced.set()
        self._follow_thread = threading.Thread(
            target=self._follow_loop, name="repl-follow", daemon=True)
        self._follow_thread.start()
        return self

    def auto_promote_after(self, grace: float) -> "FollowerStore":
        """Start the failure detector: when the replication stream goes
        silent (no recs and no heartbeat pings) for `grace` seconds,
        promote this follower with a new epoch.  Pick grace > the hub's
        sync_timeout so a fencing-mode primary stops acking writes
        before the new term starts (the zero-acked-loss ordering)."""
        self._watchdog_grace = grace

        def watch() -> None:
            while not self._watchdog_stop.wait(grace / 4):
                if self._promoted:
                    return
                if time.monotonic() - self._last_frame > grace:
                    logger.warning(
                        "replication stream silent %.1fs: auto-promoting "
                        "at epoch %d", grace, self._seen_epoch + 1)
                    self.promote()
                    return

        self._watchdog = threading.Thread(target=watch,
                                          name="repl-watchdog", daemon=True)
        self._watchdog.start()
        return self

    def _follow_loop(self) -> None:
        sock = self._conn
        try:
            while not self._promoted:
                frame = _recv_frame(sock)
                if frame is None:
                    logger.warning("replication stream closed by primary")
                    return
                epoch = int(frame.get("epoch", self._seen_epoch))
                if epoch < self._seen_epoch:
                    # a deposed primary's stream: its records must never
                    # apply (fencing).  Drop the connection; the stale
                    # primary discovers the new term when it rejoins.
                    logger.warning(
                        "dropping replication stream at stale epoch %d "
                        "(seen %d)", epoch, self._seen_epoch)
                    return
                self._seen_epoch = max(self._seen_epoch, epoch)
                self._last_frame = time.monotonic()
                if frame.get("type") != "recs":
                    continue  # ping / unknown: liveness only
                recs = frame.get("recs") or []
                self._apply_records(recs)
                top = max((int(r[1]) for r in recs), default=0)
                if top:
                    _send_frame(sock, {"type": "ack", "rev": top})
        except OSError as e:
            if not self._promoted:
                logger.warning("replication stream error: %s", e)

    def _apply_records(self, recs: list) -> None:
        """Replay shipped commit records: table writes + watch emission,
        exactly the primary's commit effects (objects arrive sealed; the
        watch ring serves opened plaintext like the primary's).  The
        records also re-enter _commit, so a follower with its own WAL
        persists them and a chained downstream follower receives them."""
        with self._lock:
            for rec in recs:
                op, rev, resource, key = rec[0], int(rec[1]), rec[2], rec[3]
                obj = rec[4] if len(rec) > 4 else None
                table = self._table(resource)
                if rev > self._rev:
                    self._rev = rev
                else:
                    # post-rejoin plateau: the new primary's sequence is
                    # still below what attached watchers observed (old
                    # term's dirty tail or the synthesized rejoin diff) —
                    # step past it so the emitted stream stays strictly
                    # monotonic until the primary's numbering catches up
                    self._rev += 1
                if op == wal_mod.PUT:
                    existed = key in table
                    table[key] = obj
                    self._emit(resource,
                               kv.MODIFIED if existed else kv.ADDED,
                               self._open(resource, obj))
                else:  # DELETE; obj is the tombstone (may be None from
                    table.pop(key, None)       # an old-format primary)
                    tomb = obj or {"metadata": {
                        "name": key.rpartition("/")[2],
                        "namespace": key.rpartition("/")[0],
                        "resourceVersion": self._rev}}
                    self._emit(resource, kv.DELETED, tomb)
            if self._logging:
                self._commit([tuple(r) for r in recs])

    # -- promotion --------------------------------------------------------

    def promote(self) -> "FollowerStore":
        """Become the writable primary: stop following, accept writes,
        continue the revision sequence from the last applied record —
        under a NEW epoch (seen+1), so the deposed primary's stream and
        rejoin attempts are recognizably stale (fencing).  Watches
        opened against this store stay attached; informers of clients
        that re-point here relist and resume."""
        self.epoch = self._seen_epoch + 1
        self._seen_epoch = self.epoch
        self._fenced = False  # a new term clears any old fence
        self._promoted = True
        self._watchdog_stop.set()
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        logger.warning("follower promoted to primary at rev %d epoch %d",
                       self._rev, self.epoch)
        self.promoted_event.set()
        return self

    def rejoin(self, host: str, port: int,
               timeout: float = 10.0) -> "FollowerStore":
        """Re-enter the cluster as a follower of the (new) primary: a
        deposed/fenced primary calls this after a partition heals.  Any
        dirty never-acked tail in the table is discarded by the
        bootstrap snapshot — follow() converges attached watchers onto
        it with synthesized DELETED/ADDED/MODIFIED diff events and
        restarts the watch ring, so a watcher spanning fence→rejoin
        sees vanished keys deleted and strictly monotonic revisions.
        The write fence flips back on (this store is a replica again)."""
        self._promoted = False
        self._fenced = False
        self._fence_reason = ""
        self.promoted_event.clear()
        self._watchdog_stop = threading.Event()
        self._synced = threading.Event()
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
        self.follow(host, port, timeout=timeout)
        if getattr(self, "_watchdog_grace", None):
            # the failure detector died with the old term (promote()
            # stops it); a rejoined replica keeps the automatic-failover
            # contract it was configured with
            self.auto_promote_after(self._watchdog_grace)
        return self
