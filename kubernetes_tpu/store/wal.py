"""Write-ahead log + snapshots: disk durability for the cluster store.

The reference's crash-only control plane works because etcd persists every
revision (staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:154,331;
etcd's WAL + periodic snapshots).  This module gives MemoryStore the same
property without an external process:

  * every mutation appends a checksummed, length-prefixed record to an
    append-only log (one os.write per store operation — batched ops like
    create_many/bind_many append the whole burst in a single write);
  * a snapshot is a full table dump at one revision.  Taking one is split
    so the expensive part runs OFF the store lock: begin_snapshot()
    (called under the lock) rotates the live log to a numbered segment and
    returns instantly; finish_snapshot() (any thread, no lock) serializes
    the captured state, writes a temp file, fsyncs, atomically renames,
    fsyncs the directory, and only then drops the rotated segments whose
    records the snapshot now covers (compaction — etcd snapshot + WAL
    segment drop);
  * recovery loads the snapshot (if any) and replays rotated segments in
    order, then the live log, skipping records at or below the snapshot
    revision and stopping cleanly at the first torn or corrupt record (a
    crash mid-append loses at most the torn tail, never the prefix — etcd
    WAL CRC semantics);
  * an exclusive flock on the directory rejects a second process pointed
    at the same data dir (etcd's member-dir lock) before it can interleave
    records.

Values land on disk exactly as the table holds them, i.e. AFTER the
at-rest envelope transformer ran (store/encryption.py), so encrypted
resources stay encrypted in both log and snapshot.

Durability level: by default records reach the OS page cache (survives
process SIGKILL, the failure mode the control plane plans for); pass
fsync=True to survive machine power loss at a heavy per-write cost.

Record wire format (little-endian):
    u32 payload_len | u32 crc32(payload) | payload
payload = compact JSON, one of
    ["P", rev, resource, key, obj]   -- put (create/update/bind)
    ["D", rev, resource, key]        -- delete
"""

from __future__ import annotations

import fcntl
import json
import os
import re
import struct
import zlib

_HDR = struct.Struct("<II")

PUT = "P"
DELETE = "D"


def _encode(payload: bytes) -> bytes:
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def _fsync_dir(directory: str) -> None:
    """Make a rename in `directory` itself durable (fsyncing the file is
    not enough: the new directory entry lives in the parent's pages)."""
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class LockedError(Exception):
    """Another live process holds this data directory."""


class WriteAheadLog:
    """Append-only log + snapshot pair rooted at one directory.

    Appends and begin_snapshot() are called by MemoryStore under its own
    lock, which guarantees file order == revision order; finish_snapshot()
    and recover() are safe without it.
    """

    LOG = "wal.log"
    SNAP = "snapshot.json"
    LOCK = "LOCK"
    _SEG = re.compile(r"^wal\.log\.(\d+)$")

    def __init__(self, directory: str, fsync: bool = False,
                 truncate_log_to: int | None = None,
                 pending_records: int = 0):
        self.dir = directory
        self.fsync = fsync
        # records written since the last completed snapshot (a recovered
        # log's replayed records count toward it, so a process that
        # restarts often still compacts)
        self.records_since_snapshot = pending_records
        os.makedirs(directory, exist_ok=True)
        # one writer per data dir (etcd member-dir flock): held for the
        # process lifetime, released by the OS on any exit
        self._lock_f = open(os.path.join(directory, self.LOCK), "w")
        try:
            fcntl.flock(self._lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._lock_f.close()
            raise LockedError(
                f"data dir {directory!r} is locked by another process")
        self._path = os.path.join(directory, self.LOG)
        if truncate_log_to is not None and os.path.exists(self._path) \
                and os.path.getsize(self._path) > truncate_log_to:
            # drop a torn tail found during recovery so new appends start
            # at a record boundary
            with open(self._path, "r+b") as f:
                f.truncate(truncate_log_to)
        self._f = open(self._path, "ab")

    # -- append ----------------------------------------------------------

    def append_put(self, rev: int, resource: str, key: str, obj) -> None:
        self.append_many([(PUT, rev, resource, key, obj)])

    def append_delete(self, rev: int, resource: str, key: str) -> None:
        self.append_many([(DELETE, rev, resource, key)])

    def append_many(self, entries) -> None:
        """entries: iterable of (op, rev, resource, key[, obj]) tuples."""
        chunks = []
        for e in entries:
            payload = json.dumps(list(e), separators=(",", ":"),
                                 default=_jsonify).encode()
            chunks.append(_encode(payload))
        if not chunks:
            return
        self._f.write(b"".join(chunks))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self.records_since_snapshot += len(chunks)

    # -- snapshot / compaction -------------------------------------------

    def _segments(self) -> list[str]:
        """Rotated log segments, oldest first."""
        segs = []
        for name in os.listdir(self.dir):
            m = self._SEG.match(name)
            if m:
                segs.append((int(m.group(1)), name))
        return [os.path.join(self.dir, n) for _, n in sorted(segs)]

    def begin_snapshot(self) -> None:
        """Rotate the live log to a numbered segment (cheap; called under
        the store lock so no append can race the rotation).  Every record
        so far is now frozen in segments; finish_snapshot() covers them."""
        self._f.close()
        segs = self._segments()
        nxt = 1
        if segs:
            nxt = int(segs[-1].rsplit(".", 1)[1]) + 1
        os.replace(self._path, f"{self._path}.{nxt}")
        self._f = open(self._path, "ab")
        if self.fsync:
            _fsync_dir(self.dir)
        self.records_since_snapshot = 0

    def finish_snapshot(self, rev: int, data: dict) -> None:
        """Serialize + persist state at `rev`, then drop covered segments.

        `data` must be a shallow copy captured at the same moment
        begin_snapshot() rotated the log (object values are immutable by
        the store's sharing contract, so a 2-level copy is a consistent
        image).  Runs without the store lock — this is the expensive part.

        Crash ordering: tmp write + fsync, atomic rename, DIRECTORY fsync
        (so the rename itself is durable), and only then segment removal.
        A crash at any point leaves either old-snapshot + all segments or
        new-snapshot + possibly-some segments, both of which recover().
        """
        body = json.dumps({"rev": rev, "data": data},
                          separators=(",", ":"), default=_jsonify).encode()
        blob = _encode(body)  # same len+crc framing guards the snapshot
        tmp = os.path.join(self.dir, self.SNAP + ".tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.dir, self.SNAP))
        _fsync_dir(self.dir)
        for seg in self._segments():
            os.remove(seg)

    # -- recovery --------------------------------------------------------

    @classmethod
    def recover(cls, directory: str) -> tuple[int, dict, int, int]:
        """Load (rev, {resource: {key: obj}}, valid_log_bytes, n_replayed).

        Missing files mean a fresh store.  A corrupt snapshot is a hard
        error (it was fsynced + atomically renamed; damage is real).  A
        corrupt or torn record tail is expected after a crash and stops
        that file's replay; valid_log_bytes marks the boundary in the LIVE
        log so the caller can cut the tail before appending again.
        """
        rev = 0
        data: dict[str, dict] = {}
        snap_path = os.path.join(directory, cls.SNAP)
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                blob = f.read()
            body = _next_record(blob, 0, strict=True)[0]
            snap = json.loads(body)
            rev = snap["rev"]
            data = snap["data"]
        # rotated segments (a snapshot that never finished), then live log
        segs = []
        if os.path.isdir(directory):
            for name in os.listdir(directory):
                m = cls._SEG.match(name)
                if m:
                    segs.append((int(m.group(1)), name))
        paths = [os.path.join(directory, n) for _, n in sorted(segs)]
        live = os.path.join(directory, cls.LOG)
        if os.path.exists(live):
            paths.append(live)
        valid = 0
        replayed = 0
        for path in paths:
            with open(path, "rb") as f:
                blob = f.read()
            off = 0
            while True:
                rec = _next_record(blob, off, strict=False)
                if rec is None:
                    break
                body, off = rec
                if path == live:
                    valid = off
                entry = json.loads(body)
                op, erev = entry[0], entry[1]
                if erev <= rev:
                    continue  # already in the snapshot
                rev = erev
                replayed += 1
                if op == PUT:
                    _, _, resource, key, obj = entry
                    data.setdefault(resource, {})[key] = obj
                else:
                    _, _, resource, key = entry
                    data.get(resource, {}).pop(key, None)
        return rev, data, valid, replayed

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:  # pragma: no cover
            pass
        try:
            self._lock_f.close()  # releases the flock
        except OSError:  # pragma: no cover
            pass


def _next_record(blob: bytes, off: int, strict: bool):
    """Decode one framed record at `off`; None on clean EOF or torn tail."""
    if off == len(blob):
        return None
    if off + _HDR.size > len(blob):
        if strict:
            raise CorruptRecord("truncated header")
        return None
    length, crc = _HDR.unpack_from(blob, off)
    start = off + _HDR.size
    end = start + length
    if end > len(blob):
        if strict:
            raise CorruptRecord("truncated payload")
        return None
    payload = blob[start:end]
    if zlib.crc32(payload) != crc:
        if strict:
            raise CorruptRecord("checksum mismatch")
        return None
    return payload, end


class CorruptRecord(Exception):
    pass


def _jsonify(o):
    """Last-resort encoder for non-JSON scalars that leak into objects
    (the API layer keeps objects JSON-shaped; this guards test fixtures)."""
    return str(o)
