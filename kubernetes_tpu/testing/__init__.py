"""Testing kit (reference: pkg/scheduler/testing)."""

from .wrappers import NodeWrapper, PodWrapper, make_node, make_pod  # noqa: F401
