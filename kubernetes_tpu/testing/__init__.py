"""Testing kit (reference: pkg/scheduler/testing)."""

from .wrappers import (  # noqa: F401
    NodeWrapper, PodWrapper, make_node, make_pod, make_pv, make_pvc,
    make_storage_class,
)
from .fake import FakeInformer, FakeInformerFactory  # noqa: F401,E402
