"""Testing kit (reference: pkg/scheduler/testing)."""

from .wrappers import (  # noqa: F401
    NodeWrapper, PodWrapper, make_node, make_pod, make_pv, make_pvc,
    make_storage_class,
)
from .fake import FakeInformer, FakeInformerFactory  # noqa: F401,E402


def wait_for(predicate, timeout: float = 30.0, interval: float = 0.02) -> bool:
    """Poll until predicate() is truthy; the shared test/e2e helper
    (test/e2e/framework wait.go shape)."""
    import time
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False
