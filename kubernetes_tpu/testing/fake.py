"""Fake informer factory for plugin unit tests.

Mirrors the role of framework/fake/listers.go: hand-populated listers with
the Informer get/list surface, no watch machinery.
"""

from __future__ import annotations

from ..api import meta
from ..api.meta import Obj


class FakeInformer:
    def __init__(self):
        self._indexer: dict[str, Obj] = {}

    def add(self, obj: Obj) -> None:
        self._indexer[meta.namespaced_name(obj)] = obj

    def get(self, namespace: str, name: str) -> Obj | None:
        key = f"{namespace}/{name}" if namespace else name
        return self._indexer.get(key)

    def get_by_key(self, key: str) -> Obj | None:
        return self._indexer.get(key)

    def list(self, namespace: str | None = None) -> list[Obj]:
        if namespace:
            prefix = namespace + "/"
            return [o for k, o in self._indexer.items()
                    if k.startswith(prefix)]
        return list(self._indexer.values())

    def __len__(self) -> int:
        return len(self._indexer)


class FakeInformerFactory:
    def __init__(self):
        self._informers: dict[str, FakeInformer] = {}

    def informer(self, resource: str) -> FakeInformer:
        inf = self._informers.get(resource)
        if inf is None:
            inf = self._informers[resource] = FakeInformer()
        return inf

    def add(self, resource: str, obj: Obj) -> None:
        self.informer(resource).add(obj)
