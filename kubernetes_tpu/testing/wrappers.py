"""Fluent test-object builders.

Reference: pkg/scheduler/testing/wrappers.go (MakePod().Name("p").Req(...)...)
"""

from __future__ import annotations

from typing import Any

from ..api import meta
from ..api.meta import Obj
from ..api.resources import make_resource_list


class PodWrapper:
    def __init__(self, name: str, namespace: str = "default"):
        self.obj: Obj = meta.new_object("Pod", name, namespace)
        self.obj["spec"] = {"containers": [], "schedulerName": "default-scheduler"}
        self.obj["status"] = {}

    def req(self, cpu: str | None = None, mem: str | None = None,
            **scalar: str) -> "PodWrapper":
        requests: dict[str, Any] = {}
        if cpu is not None:
            requests["cpu"] = cpu
        if mem is not None:
            requests["memory"] = mem
        requests.update(scalar)
        self.obj["spec"]["containers"].append(
            {"name": f"c{len(self.obj['spec']['containers'])}",
             "image": "img", "resources": {"requests": requests}})
        return self

    def container(self, image: str) -> "PodWrapper":
        self.obj["spec"]["containers"].append(
            {"name": f"c{len(self.obj['spec']['containers'])}", "image": image})
        return self

    def priority(self, p: int) -> "PodWrapper":
        self.obj["spec"]["priority"] = p
        return self

    def node(self, name: str) -> "PodWrapper":
        self.obj["spec"]["nodeName"] = name
        return self

    def scheduler(self, name: str) -> "PodWrapper":
        self.obj["spec"]["schedulerName"] = name
        return self

    def labels(self, **kv: str) -> "PodWrapper":
        self.obj["metadata"].setdefault("labels", {}).update(kv)
        return self

    def node_selector(self, **kv: str) -> "PodWrapper":
        self.obj["spec"].setdefault("nodeSelector", {}).update(kv)
        return self

    def node_affinity_in(self, key: str, values: list[str]) -> "PodWrapper":
        terms = (self.obj["spec"].setdefault("affinity", {})
                 .setdefault("nodeAffinity", {})
                 .setdefault("requiredDuringSchedulingIgnoredDuringExecution", {})
                 .setdefault("nodeSelectorTerms", []))
        terms.append({"matchExpressions": [
            {"key": key, "operator": "In", "values": values}]})
        return self

    def pod_affinity(self, topology_key: str, match_labels: dict[str, str],
                     anti: bool = False, preferred_weight: int | None = None
                     ) -> "PodWrapper":
        kind = "podAntiAffinity" if anti else "podAffinity"
        aff = self.obj["spec"].setdefault("affinity", {}).setdefault(kind, {})
        term = {"topologyKey": topology_key,
                "labelSelector": {"matchLabels": match_labels}}
        if preferred_weight is None:
            aff.setdefault("requiredDuringSchedulingIgnoredDuringExecution",
                           []).append(term)
        else:
            aff.setdefault("preferredDuringSchedulingIgnoredDuringExecution",
                           []).append({"weight": preferred_weight,
                                       "podAffinityTerm": term})
        return self

    def topology_spread(self, topology_key: str, max_skew: int = 1,
                        when: str = "DoNotSchedule",
                        match_labels: dict[str, str] | None = None) -> "PodWrapper":
        self.obj["spec"].setdefault("topologySpreadConstraints", []).append({
            "maxSkew": max_skew, "topologyKey": topology_key,
            "whenUnsatisfiable": when,
            "labelSelector": {"matchLabels": match_labels or meta.labels(self.obj)},
        })
        return self

    def toleration(self, key: str, value: str = "", effect: str = "",
                   operator: str = "Equal") -> "PodWrapper":
        tol: dict[str, Any] = {"key": key, "operator": operator}
        if value:
            tol["value"] = value
        if effect:
            tol["effect"] = effect
        self.obj["spec"].setdefault("tolerations", []).append(tol)
        return self

    def host_port(self, port: int, protocol: str = "TCP") -> "PodWrapper":
        if not self.obj["spec"]["containers"]:
            self.container("img")
        self.obj["spec"]["containers"][0].setdefault("ports", []).append(
            {"containerPort": port, "hostPort": port, "protocol": protocol})
        return self

    def pvc(self, claim_name: str, volume_name: str | None = None) -> "PodWrapper":
        self.obj["spec"].setdefault("volumes", []).append(
            {"name": volume_name or claim_name,
             "persistentVolumeClaim": {"claimName": claim_name}})
        return self

    def inline_volume(self, volume: dict) -> "PodWrapper":
        self.obj["spec"].setdefault("volumes", []).append(volume)
        return self

    def build(self) -> Obj:
        if not self.obj["spec"]["containers"]:
            self.container("img")
        return self.obj


class NodeWrapper:
    def __init__(self, name: str):
        self.obj: Obj = meta.new_object("Node", name, None)
        self.obj["spec"] = {}
        self.obj["status"] = {
            "allocatable": make_resource_list(cpu_milli=4000, mem=16 * 2**30),
            "capacity": make_resource_list(cpu_milli=4000, mem=16 * 2**30),
        }

    def capacity(self, cpu: str = "4", mem: str = "16Gi", pods: int = 110,
                 **scalar: str) -> "NodeWrapper":
        rl: dict[str, Any] = {"cpu": cpu, "memory": mem, "pods": str(pods)}
        rl.update(scalar)
        self.obj["status"]["allocatable"] = rl
        self.obj["status"]["capacity"] = dict(rl)
        return self

    def labels(self, **kv: str) -> "NodeWrapper":
        self.obj["metadata"].setdefault("labels", {}).update(kv)
        return self

    def zone(self, zone: str) -> "NodeWrapper":
        return self.labels(**{"topology.kubernetes.io/zone": zone})

    def taint(self, key: str, value: str = "", effect: str = "NoSchedule"
              ) -> "NodeWrapper":
        self.obj["spec"].setdefault("taints", []).append(
            {"key": key, "value": value, "effect": effect})
        return self

    def unschedulable(self) -> "NodeWrapper":
        self.obj["spec"]["unschedulable"] = True
        return self

    def image(self, name: str, size: int) -> "NodeWrapper":
        self.obj["status"].setdefault("images", []).append(
            {"names": [name], "sizeBytes": size})
        return self

    def build(self) -> Obj:
        return self.obj


def make_pod(name: str, namespace: str = "default") -> PodWrapper:
    return PodWrapper(name, namespace)


def make_node(name: str) -> NodeWrapper:
    return NodeWrapper(name)


def make_pvc(name: str, namespace: str = "default", storage: str = "1Gi",
             storage_class: str | None = None, volume_name: str | None = None,
             access_modes: list[str] | None = None) -> Obj:
    pvc = meta.new_object("PersistentVolumeClaim", name, namespace)
    pvc["spec"] = {
        "accessModes": access_modes or ["ReadWriteOnce"],
        "resources": {"requests": {"storage": storage}},
    }
    if storage_class:
        pvc["spec"]["storageClassName"] = storage_class
    if volume_name:
        pvc["spec"]["volumeName"] = volume_name
    return pvc


def make_pv(name: str, storage: str = "1Gi",
            storage_class: str | None = None,
            access_modes: list[str] | None = None,
            zone: str | None = None,
            node_affinity_hostname: str | None = None) -> Obj:
    pv = meta.new_object("PersistentVolume", name, None)
    pv["spec"] = {
        "capacity": {"storage": storage},
        "accessModes": access_modes or ["ReadWriteOnce"],
    }
    if storage_class:
        pv["spec"]["storageClassName"] = storage_class
    if zone:
        pv["metadata"].setdefault("labels", {})[
            "topology.kubernetes.io/zone"] = zone
    if node_affinity_hostname:
        pv["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
            {"matchExpressions": [{"key": "kubernetes.io/hostname",
                                   "operator": "In",
                                   "values": [node_affinity_hostname]}]}]}}
    return pv


def make_storage_class(name: str, provisioner: str = "example.com/prov",
                       wait_for_first_consumer: bool = False) -> Obj:
    sc = meta.new_object("StorageClass", name, None)
    sc["provisioner"] = provisioner
    sc["volumeBindingMode"] = ("WaitForFirstConsumer"
                               if wait_for_first_consumer else "Immediate")
    return sc
