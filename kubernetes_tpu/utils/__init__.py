"""Shared utilities."""

from .fastcopy import deep_copy_json, is_native  # noqa: F401
