"""Deep copy for JSON-shaped objects: native extension with pure fallback.

The reference generates per-type DeepCopyObject via deepcopy-gen
(staging/src/k8s.io/code-generator); our objects are plain dict trees, so
one native copier covers every type.

native/fastcopy builds `_fastcopy` (CPython C API); the store's write path
(store/kv.py via api.meta.deep_copy) is the consumer.  Objects here are
always dict/list/scalar trees, so the C path shares immutable scalars and
skips deepcopy's memo machinery.
"""

from __future__ import annotations

import copy
import glob
import os
import sys

_native = None


def _load_native():
    global _native
    here = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..",
                                        "native", "fastcopy"))
    sos = glob.glob(os.path.join(here, "_fastcopy*.so"))
    if not sos and os.path.isdir(here) and not os.environ.get(
            "KTPU_NO_NATIVE_BUILD"):
        # first use on this machine: build the extension in place (quiet)
        import subprocess
        try:
            subprocess.run([sys.executable, "setup.py", "build_ext",
                            "--inplace"], cwd=here, capture_output=True,
                           timeout=120, check=False)
        except (OSError, subprocess.TimeoutExpired):
            pass
        sos = glob.glob(os.path.join(here, "_fastcopy*.so"))
    for path in sos:
        d = os.path.dirname(path)
        if d not in sys.path:
            sys.path.insert(0, d)
    try:
        import _fastcopy  # type: ignore
        _native = _fastcopy
    except ImportError:
        _native = None


_load_native()


def deep_copy_json(obj):
    if _native is not None:
        try:
            return _native.deepcopy_json(obj)
        except TypeError:
            pass  # non-JSON node: fall through
    return copy.deepcopy(obj)


def is_native() -> bool:
    return _native is not None
