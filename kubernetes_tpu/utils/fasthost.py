"""Native per-pod host helpers with pure-Python fallbacks.

Reference analog: the reference spends this per-pod host constant in
parallel Go — one goroutine per binding cycle
(pkg/scheduler/schedule_one.go:100-110) and a 16-worker parallel-for
(pkg/scheduler/framework/parallelize/parallelism.go:13); CPython claws
the throughput back by making the per-pod constant native instead.

native/fasthost builds `_fasthost` (CPython C API) — one C pass each for
the scheduler's per-pod host loops (see fasthost.c header for the
inventory and the reference's goroutine/parallel-for analog).  Consumers:

  scheduler/scheduler.py  _finish_batch  -> build_assumed, clone_podinfos
  scheduler/scheduler.py  _bulk_bind_commit -> binding_rows
  client/informer.py      _list_and_watch -> watch_apply
  ops/flatten.py          encode         -> req_columns
  scheduler/types.py      PodInfo.update -> pod_scan_into

Every helper has a byte-identical pure-Python fallback so the framework
runs unchanged where the toolchain is absent (KTPU_NO_NATIVE_BUILD=1
skips the in-place build, like fastcopy)."""

from __future__ import annotations

import glob
import os
import sys

_native = None


def _load_native():
    global _native
    here = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..",
                                        "native", "fasthost"))
    sos = glob.glob(os.path.join(here, "_fasthost*.so"))
    if not sos and os.path.isdir(here) and not os.environ.get(
            "KTPU_NO_NATIVE_BUILD"):
        import subprocess
        try:
            subprocess.run([sys.executable, "setup.py", "build_ext",
                            "--inplace"], cwd=here, capture_output=True,
                           timeout=120, check=False)
        except (OSError, subprocess.TimeoutExpired):
            pass
        sos = glob.glob(os.path.join(here, "_fasthost*.so"))
    for path in sos:
        d = os.path.dirname(path)
        if d not in sys.path:
            sys.path.insert(0, d)
    try:
        import _fasthost  # type: ignore
        _native = _fasthost
    except ImportError:
        _native = None


_load_native()


def is_native() -> bool:
    return _native is not None


def build_assumed(pods: list, node_names: list) -> list:
    """[{**pod, "spec": {**spec, "nodeName": n}}] for each (pod, n).
    2-level shallow copies: nested values are never mutated in place on
    this path (store reads hand out copies), matching the Python
    original in scheduler._finish_batch."""
    if _native is not None:
        return _native.build_assumed(pods, node_names)
    return [{**pod, "spec": {**(pod.get("spec") or {}), "nodeName": n}}
            for pod, n in zip(pods, node_names)]


def req_columns(pod_infos: list, req, req_nz) -> None:
    """Fill req[i,0:3] / req_nz[i,0:3] (float32 C-contiguous) from
    pod_infos[i].request / .request_nonzero."""
    if _native is not None:
        _native.req_columns(pod_infos, req, req_nz)
        return
    req[:len(pod_infos), 0] = [pi.request.milli_cpu for pi in pod_infos]
    req[:len(pod_infos), 1] = [pi.request.memory for pi in pod_infos]
    req[:len(pod_infos), 2] = [pi.request.ephemeral_storage
                               for pi in pod_infos]
    req_nz[:len(pod_infos), 0] = [pi.request_nonzero.milli_cpu
                                  for pi in pod_infos]
    req_nz[:len(pod_infos), 1] = [pi.request_nonzero.memory
                                  for pi in pod_infos]
    req_nz[:len(pod_infos), 2] = [pi.request_nonzero.ephemeral_storage
                                  for pi in pod_infos]


def pod_scan_into(pod: dict, pi, defaults: tuple):
    """Whole PodInfo fast path in C: fills pi's slots when the pod is
    simple.  Returns False (not simple / native absent — take the full
    Python path), a requests dict (single-container fast shape), or
    None (simple but requests need the general computation)."""
    if _native is not None:
        return _native.pod_scan_into(pod, pi, defaults)
    return False


def clone_podinfos(infos: list, pods: list) -> list:
    """Batch clone_with_pod (scheduler batch tail): one C pass when
    built, per-pod Python clones otherwise."""
    if _native is not None:
        return _native.clone_podinfos(infos, pods)
    return [pi.clone_with_pod(pod) for pi, pod in zip(infos, pods)]


# The two round-12 helpers use getattr guards, not bare _native checks: a
# stale .so built before this round imports fine but lacks the symbols.


def watch_apply(events: list, indexer: dict) -> list:
    """Informer watch-burst apply: update the indexer from a batch of
    watch events and return the (type, obj, prev) dispatch triples.
    Caller holds the informer locks (single C pass replaces the
    per-event bytecode loop in Informer._list_and_watch)."""
    from ..api import meta
    from ..store import kv
    fn = getattr(_native, "watch_apply", None)
    if fn is not None:
        return fn(events, indexer, kv.DELETED, kv.ADDED, kv.MODIFIED)
    triples = []
    for ev in events:
        key = meta.namespaced_name(ev.object)
        if ev.type == kv.DELETED:
            prev = indexer.pop(key, None)
            triples.append((kv.DELETED, ev.object, prev))
        else:
            prev = indexer.get(key)
            indexer[key] = ev.object
            triples.append((kv.MODIFIED if prev is not None
                            else kv.ADDED, ev.object, prev))
    return triples


def binding_rows(ready: list) -> list:
    """(namespace, name, node) wire rows from the bulk-bind ready
    tuples (state, qpi, node, assumed) — the binder-worker half of the
    bind critical path in one C pass."""
    from ..api import meta
    fn = getattr(_native, "binding_rows", None)
    if fn is not None:
        return fn(ready)
    return [(meta.namespace(q.pod), meta.name(q.pod), node)
            for _, q, node, _ in ready]
