"""Garbage-collector tuning for long-running control-plane processes.

The reference's components run on the Go runtime, whose concurrent GC
never stops the world for more than fractions of a millisecond; CPython's
generational cyclic collector, by contrast, stops everything — and with a
million live acyclic objects (stored pods, watch history, informer
indexers) a gen-2 pass costs hundreds of milliseconds and fires often at
default thresholds (700, 10, 10).  At bench scale that was ~35% of
scheduler throughput.

Control-plane state here is overwhelmingly acyclic (dict/list trees freed
by refcounting), so delaying cycle detection is safe: reference cycles
are rare (exception tracebacks, some framework closures) and still get
collected, just less often.

Reference analog: the scheduler's throughput assumptions in
test/integration/scheduler_perf (util.go:288-355) are calibrated against
Go's pauseless collector; this is the CPython-native equivalent knob.
"""

from __future__ import annotations

import gc

_tuned = False


def tune_for_throughput(freeze_startup: bool = True) -> None:
    """Raise collection thresholds for steady-state serving and move
    everything allocated so far into the permanent generation (it is
    module/config state that will never become garbage).

    Idempotent: only the FIRST call freezes/tunes.  Repeated freezing
    (e.g. per-cluster setup inside one pytest process) would move earlier
    clusters' cyclic garbage into the permanent generation where it can
    never be reclaimed."""
    global _tuned
    if _tuned:
        return
    _tuned = True
    if freeze_startup:
        gc.collect()
        gc.freeze()
    gc.set_threshold(200_000, 100, 100)
    # Fewer GIL handoffs: the pipeline runs 5-6 cooperating threads
    # (sched loop, binder, informer, event broadcaster, collector) that
    # each do long CPU bursts; the default 5ms switch interval forces
    # ~40 forced preemptions per batch tail, each costing a futex
    # round-trip plus cache refill.  20ms keeps bursts intact; blocking
    # calls (device waits, condition waits) still release the GIL
    # immediately, so latency-sensitive handoffs are unaffected.
    import sys
    sys.setswitchinterval(0.02)
