"""Batch-path stage-latency collector (diagnostic, env-gated).

KTPU_STAGE_DEBUG=1 turns on per-batch stage timing in the scheduler's
TPU batch path so a paced-latency run can show WHERE pod latency
accrues:

    queue_wait     pod sat in activeQ before its batch dispatched
    dispatch_host  host time inside backend.dispatch (encode + upload)
    pipeline_wait  dispatch call -> resolve begins (host dispatch time
                   plus depth-D pipeline residency; subtract
                   dispatch_host for residency alone)
    resolve_block  host blocked in resolve() (device wait + decode)
    disp_to_bound  dispatch -> binding committed (device + tail)

Zero overhead when disabled: callers guard on `ENABLED` (module constant
read once at import).  The collector keeps bounded reservoirs; summary()
reports count/mean/p50/p99 per stage in milliseconds.

Reference analog: the per-extension-point latency histograms the
scheduler exports (pkg/scheduler/metrics/metrics.go:137-157) — this is
the TPU-batch-path equivalent, split along the pipeline's stage
boundaries instead of plugin extension points.
"""

from __future__ import annotations

import os
import threading

ENABLED = os.environ.get("KTPU_STAGE_DEBUG", "0") not in ("", "0")

_CAP = 4096  # per-stage reservoir bound (newest kept, oldest dropped)
_lock = threading.Lock()
# process-local: latency reservoir; each scheduler process reports
# its own stages, federation happens at the /metrics text layer
_stages: dict[str, list[float]] = {}


def record(stage: str, seconds: float) -> None:
    with _lock:
        vals = _stages.setdefault(stage, [])
        vals.append(seconds)
        if len(vals) > _CAP:
            del vals[: len(vals) - _CAP]


def reset() -> None:
    with _lock:
        _stages.clear()


def summary() -> dict[str, dict[str, float]]:
    """{stage: {count, mean_ms, p50_ms, p99_ms}} over recorded samples."""
    out: dict[str, dict[str, float]] = {}
    with _lock:
        snap = {k: list(v) for k, v in _stages.items()}
    for stage, vals in snap.items():
        if not vals:
            continue
        vals.sort()
        n = len(vals)
        out[stage] = {
            "count": n,
            "mean_ms": round(sum(vals) / n * 1e3, 2),
            "p50_ms": round(vals[n // 2] * 1e3, 2),
            "p99_ms": round(vals[min(n - 1, int(n * 0.99))] * 1e3, 2),
        }
    return out
