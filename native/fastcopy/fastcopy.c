/* fastcopy — C deep copy for JSON-shaped Python objects.
 *
 * The control plane stores objects as plain dict/list/scalar trees (the
 * wire shape); every store write deep-copies the inbound object so stored
 * state stays private (store/kv.py).  copy.deepcopy pays for generality
 * (memo dict, reduce protocol, type dispatch per node); this extension
 * recurses only over dict/list/tuple and shares immutable scalars, which
 * profiling showed is the dominant host cost of the write path at
 * scheduler_perf scale.
 *
 * Reference context: the reference's Go apiserver gets the same effect
 * from generated DeepCopy methods (zz_generated.deepcopy.go) — this is
 * the TPU build's native runtime equivalent (SURVEY.md §2: native surface).
 *
 * Falls back transparently: kubernetes_tpu/utils/fastcopy.py uses
 * copy.deepcopy when the extension isn't built.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *deepcopy_json_obj(PyObject *obj, int depth);

static PyObject *
deepcopy_json_obj(PyObject *obj, int depth)
{
    if (depth > 200) {
        PyErr_SetString(PyExc_RecursionError, "fastcopy: object too deep");
        return NULL;
    }
    if (PyDict_CheckExact(obj)) {
        PyObject *out = PyDict_New();
        if (out == NULL)
            return NULL;
        PyObject *key, *value;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &key, &value)) {
            PyObject *cv = deepcopy_json_obj(value, depth + 1);
            if (cv == NULL || PyDict_SetItem(out, key, cv) < 0) {
                Py_XDECREF(cv);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(cv);
        }
        return out;
    }
    if (PyList_CheckExact(obj)) {
        Py_ssize_t n = PyList_GET_SIZE(obj);
        PyObject *out = PyList_New(n);
        if (out == NULL)
            return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *cv = deepcopy_json_obj(PyList_GET_ITEM(obj, i), depth + 1);
            if (cv == NULL) {
                Py_DECREF(out);
                return NULL;
            }
            PyList_SET_ITEM(out, i, cv); /* steals */
        }
        return out;
    }
    if (PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PyTuple_GET_SIZE(obj);
        PyObject *out = PyTuple_New(n);
        if (out == NULL)
            return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *cv = deepcopy_json_obj(PyTuple_GET_ITEM(obj, i), depth + 1);
            if (cv == NULL) {
                Py_DECREF(out);
                return NULL;
            }
            PyTuple_SET_ITEM(out, i, cv); /* steals */
        }
        return out;
    }
    /* scalars (str/int/float/bool/None/bytes) are immutable: share */
    if (obj == Py_None || PyUnicode_CheckExact(obj) || PyLong_CheckExact(obj)
        || PyFloat_CheckExact(obj) || PyBool_Check(obj)
        || PyBytes_CheckExact(obj)) {
        Py_INCREF(obj);
        return obj;
    }
    /* non-JSON node: signal so the wrapper falls back to copy.deepcopy */
    PyErr_Format(PyExc_TypeError, "fastcopy: unsupported type %s",
                 Py_TYPE(obj)->tp_name);
    return NULL;
}

static PyObject *
fastcopy_deepcopy_json(PyObject *self, PyObject *obj)
{
    return deepcopy_json_obj(obj, 0);
}

static PyMethodDef FastcopyMethods[] = {
    {"deepcopy_json", fastcopy_deepcopy_json, METH_O,
     "Deep copy a JSON-shaped object tree (dict/list/tuple/scalars)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastcopymodule = {
    PyModuleDef_HEAD_INIT, "_fastcopy",
    "C deep copy for JSON-shaped objects", -1, FastcopyMethods,
};

PyMODINIT_FUNC
PyInit__fastcopy(void)
{
    return PyModule_Create(&fastcopymodule);
}
