from setuptools import Extension, setup

setup(
    name="fastcopy",
    version="1.0",
    ext_modules=[Extension("_fastcopy", sources=["fastcopy.c"],
                           extra_compile_args=["-O2"])],
)
