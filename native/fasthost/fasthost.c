/* fasthost — C helpers for the scheduler's per-pod host hot paths.
 *
 * The TPU moved the node-axis work off the host; what remains is a
 * per-POD stream of small dict/attribute operations spread across the
 * informer, sched-loop, and binder threads.  At 100k-node bench scale
 * these Python-level loops are the single-interpreter wall's biggest
 * line items (VERDICT r4 item #1); each helper here collapses one of
 * them into a single C pass:
 *
 *   build_assumed(pods, node_names)  the batch tail's per-pod
 *       {**pod, "spec": {**spec, "nodeName": n}} construction
 *       (scheduler._finish_batch phase 1)
 *   req_columns(infos, req, req_nz)  the encoder's six per-pod
 *       attribute-read list comprehensions -> two [P,3]-ish float32
 *       column fills (ops/flatten.BatchEncoder.encode)
 *   pod_scan(pod)                    the informer-side PodInfo field
 *       extraction: one dict walk instead of ~15 .get chains
 *       (scheduler/types.PodInfo.update fast path)
 *
 * Reference context: the reference spreads this work over goroutines
 * (one binding cycle each, pkg/scheduler/schedule_one.go:100) and a
 * 16-worker parallel-for (parallelize/parallelism.go:13); CPython gets
 * the equivalent throughput back by making the per-pod constant native.
 *
 * Falls back transparently: kubernetes_tpu/utils/fasthost.py uses the
 * pure-Python paths when the extension isn't built.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

/* interned key cache (module-lifetime) */
static PyObject *s_spec, *s_nodeName, *s_metadata, *s_name, *s_namespace,
    *s_uid, *s_labels, *s_priority, *s_schedulerName, *s_status,
    *s_nominatedNodeName, *s_affinity, *s_nodeSelector, *s_tolerations,
    *s_topologySpreadConstraints, *s_containers, *s_initContainers,
    *s_overhead, *s_volumes, *s_resources, *s_requests, *s_ports,
    *s_request, *s_request_nonzero, *s_milli_cpu, *s_memory,
    *s_ephemeral_storage, *s_deletionTimestamp;
static PyObject *s_pvc, *s_gce, *s_aws, *s_azure, *s_iscsi, *s_csi;
static PyObject *empty_unicode, *zero_long;

static int
intern_all(void)
{
#define I(var, str) if (!(var = PyUnicode_InternFromString(str))) return -1
    I(s_spec, "spec"); I(s_nodeName, "nodeName"); I(s_metadata, "metadata");
    I(s_name, "name"); I(s_namespace, "namespace"); I(s_uid, "uid");
    I(s_labels, "labels"); I(s_priority, "priority");
    I(s_schedulerName, "schedulerName"); I(s_status, "status");
    I(s_nominatedNodeName, "nominatedNodeName"); I(s_affinity, "affinity");
    I(s_nodeSelector, "nodeSelector"); I(s_tolerations, "tolerations");
    I(s_topologySpreadConstraints, "topologySpreadConstraints");
    I(s_containers, "containers"); I(s_initContainers, "initContainers");
    I(s_overhead, "overhead"); I(s_volumes, "volumes");
    I(s_resources, "resources"); I(s_requests, "requests");
    I(s_ports, "ports");
    I(s_request, "request"); I(s_request_nonzero, "request_nonzero");
    I(s_milli_cpu, "milli_cpu"); I(s_memory, "memory");
    I(s_ephemeral_storage, "ephemeral_storage");
    I(s_deletionTimestamp, "deletionTimestamp");
    I(s_pvc, "persistentVolumeClaim"); I(s_gce, "gcePersistentDisk");
    I(s_aws, "awsElasticBlockStore"); I(s_azure, "azureDisk");
    I(s_iscsi, "iscsi"); I(s_csi, "csi");
#undef I
    if (!(empty_unicode = PyUnicode_InternFromString("")))
        return -1;
    if (!(zero_long = PyLong_FromLong(0)))
        return -1;
    return 0;
}

/* dict.get(k) that tolerates a non-dict (returns NULL borrowed, no err) */
static inline PyObject *
dget(PyObject *d, PyObject *k)
{
    if (d == NULL || !PyDict_CheckExact(d))
        return NULL;
    return PyDict_GetItemWithError(d, k); /* borrowed */
}

/* ---- build_assumed(pods, node_names) -> list[dict] ------------------- */

static PyObject *
fasthost_build_assumed(PyObject *self, PyObject *args)
{
    PyObject *pods, *names;
    if (!PyArg_ParseTuple(args, "OO", &pods, &names))
        return NULL;
    if (!PyList_CheckExact(pods) || !PyList_CheckExact(names)
        || PyList_GET_SIZE(pods) != PyList_GET_SIZE(names)) {
        PyErr_SetString(PyExc_TypeError,
                        "build_assumed: two equal-length lists required");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(pods);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);
        PyObject *node = PyList_GET_ITEM(names, i);
        if (!PyDict_CheckExact(pod)) {
            PyErr_SetString(PyExc_TypeError, "build_assumed: pod not a dict");
            goto fail;
        }
        PyObject *assumed = PyDict_Copy(pod);           /* 1-level copy */
        if (assumed == NULL)
            goto fail;
        PyObject *spec = dget(pod, s_spec);             /* borrowed */
        PyObject *nspec = spec != NULL && PyDict_CheckExact(spec)
                              ? PyDict_Copy(spec) : PyDict_New();
        if (nspec == NULL) {
            Py_DECREF(assumed);
            goto fail;
        }
        if (PyDict_SetItem(nspec, s_nodeName, node) < 0
            || PyDict_SetItem(assumed, s_spec, nspec) < 0) {
            Py_DECREF(nspec);
            Py_DECREF(assumed);
            goto fail;
        }
        Py_DECREF(nspec);
        PyList_SET_ITEM(out, i, assumed);               /* steals */
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

/* ---- req_columns(pod_infos, req, req_nz) ----------------------------- */
/* Fill req[i,0..2] and req_nz[i,0..2] (float32, C-contiguous, width >= 3)
 * from pod_infos[i].request / .request_nonzero in one C loop. */

static int
fill_from(PyObject *res, float *row, Py_ssize_t stride_ok)
{
    (void)stride_ok;
    PyObject *v;
    v = PyObject_GetAttr(res, s_milli_cpu);
    if (v == NULL) return -1;
    row[0] = (float)PyLong_AsDouble(v);
    Py_DECREF(v);
    v = PyObject_GetAttr(res, s_memory);
    if (v == NULL) return -1;
    row[1] = (float)PyLong_AsDouble(v);
    Py_DECREF(v);
    v = PyObject_GetAttr(res, s_ephemeral_storage);
    if (v == NULL) return -1;
    row[2] = (float)PyLong_AsDouble(v);
    Py_DECREF(v);
    if (PyErr_Occurred()) return -1;
    return 0;
}

static PyObject *
fasthost_req_columns(PyObject *self, PyObject *args)
{
    PyObject *infos, *req_obj, *nz_obj;
    if (!PyArg_ParseTuple(args, "OOO", &infos, &req_obj, &nz_obj))
        return NULL;
    if (!PyList_CheckExact(infos)) {
        PyErr_SetString(PyExc_TypeError, "req_columns: infos must be a list");
        return NULL;
    }
    Py_buffer req, nz;
    if (PyObject_GetBuffer(req_obj, &req, PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE
                                              | PyBUF_FORMAT) < 0)
        return NULL;
    if (PyObject_GetBuffer(nz_obj, &nz, PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE
                                            | PyBUF_FORMAT) < 0) {
        PyBuffer_Release(&req);
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(infos);
    if (req.ndim != 2 || nz.ndim != 2 || req.shape[0] < n || nz.shape[0] < n
        || req.shape[1] < 3 || nz.shape[1] < 3
        || req.itemsize != 4 || nz.itemsize != 4) {
        PyErr_SetString(PyExc_ValueError,
                        "req_columns: need float32 [>=P, >=3] arrays");
        goto fail;
    }
    Py_ssize_t wr = req.shape[1], wn = nz.shape[1];
    float *rp = (float *)req.buf, *np_ = (float *)nz.buf;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pi = PyList_GET_ITEM(infos, i);
        PyObject *r = PyObject_GetAttr(pi, s_request);
        if (r == NULL)
            goto fail;
        int rc = fill_from(r, rp + i * wr, 0);
        Py_DECREF(r);
        if (rc < 0)
            goto fail;
        r = PyObject_GetAttr(pi, s_request_nonzero);
        if (r == NULL)
            goto fail;
        rc = fill_from(r, np_ + i * wn, 0);
        Py_DECREF(r);
        if (rc < 0)
            goto fail;
    }
    PyBuffer_Release(&req);
    PyBuffer_Release(&nz);
    Py_RETURN_NONE;
fail:
    PyBuffer_Release(&req);
    PyBuffer_Release(&nz);
    return NULL;
}

/* ---- pod_scan_into(pod, pi, defaults) -------------------------------- */
/* The whole PodInfo.update fast path in one C pass: walks the pod dict
 * (same predicate as pod_scan) and, when the pod is "simple", SETS the
 * PodInfo slots directly — the Python side only computes the request
 * pair from the returned requests dict.  Returns:
 *     False          not simple — caller takes the full Python path
 *     requests dict  simple, single-container fast shape
 *     None           simple, but requests need the general computation
 * `defaults` is (EMPTY_TERMS, EMPTY_PORTS, EMPTY_DICT, EMPTY_LIST,
 * default_scheduler_name) — module-level singletons shared across pods
 * (read-only by contract, like types._EMPTY_TERMS).
 */

static PyObject *s_a_pod, *s_a_key, *s_a_uid, *s_a_labels, *s_a_priority,
    *s_a_scheduler_name, *s_a_nominated, *s_a_node_selector,
    *s_a_tolerations, *s_a_host_ports, *s_a_tsc, *s_a_plain,
    *s_a_req_aff, *s_a_req_anti, *s_a_pref_aff, *s_a_pref_anti,
    *s_a_node_aff_req, *s_a_node_aff_pref, *s_a_type, *s_a_object;

static int
intern_attrs(void)
{
#define I(var, str) if (!(var = PyUnicode_InternFromString(str))) return -1
    I(s_a_pod, "pod"); I(s_a_key, "key"); I(s_a_uid, "uid");
    I(s_a_labels, "labels"); I(s_a_priority, "priority");
    I(s_a_scheduler_name, "scheduler_name");
    I(s_a_nominated, "nominated_node_name");
    I(s_a_node_selector, "node_selector");
    I(s_a_tolerations, "tolerations"); I(s_a_host_ports, "host_ports");
    I(s_a_tsc, "topology_spread_constraints"); I(s_a_plain, "plain");
    I(s_a_req_aff, "required_affinity_terms");
    I(s_a_req_anti, "required_anti_affinity_terms");
    I(s_a_pref_aff, "preferred_affinity_terms");
    I(s_a_pref_anti, "preferred_anti_affinity_terms");
    I(s_a_node_aff_req, "node_affinity_required");
    I(s_a_node_aff_pref, "node_affinity_preferred");
    I(s_a_type, "type"); I(s_a_object, "object");
#undef I
    return 0;
}

static PyObject *
fasthost_pod_scan_into(PyObject *self, PyObject *args)
{
    PyObject *pod, *pi, *defaults;
    if (!PyArg_ParseTuple(args, "OOO", &pod, &pi, &defaults))
        return NULL;
    if (!PyDict_CheckExact(pod) || !PyTuple_CheckExact(defaults)
        || PyTuple_GET_SIZE(defaults) != 5) {
        PyErr_SetString(PyExc_TypeError,
                        "pod_scan_into(pod_dict, pi, 5-tuple defaults)");
        return NULL;
    }
    PyObject *empty_terms = PyTuple_GET_ITEM(defaults, 0);
    PyObject *empty_ports = PyTuple_GET_ITEM(defaults, 1);
    PyObject *empty_dict = PyTuple_GET_ITEM(defaults, 2);
    PyObject *empty_list = PyTuple_GET_ITEM(defaults, 3);
    PyObject *default_sched = PyTuple_GET_ITEM(defaults, 4);

    PyObject *md = dget(pod, s_metadata);
    PyObject *spec = dget(pod, s_spec);
    PyObject *status = dget(pod, s_status);
    PyObject *name = dget(md, s_name);
    PyObject *ns = dget(md, s_namespace);
    PyObject *uid = dget(md, s_uid);
    PyObject *labels = dget(md, s_labels);
    PyObject *priority = dget(spec, s_priority);
    PyObject *sched = dget(spec, s_schedulerName);
    PyObject *nominated = dget(status, s_nominatedNodeName);
    PyObject *affinity = dget(spec, s_affinity);
    PyObject *nodesel = dget(spec, s_nodeSelector);
    PyObject *tols = dget(spec, s_tolerations);
    PyObject *tsc = dget(spec, s_topologySpreadConstraints);
    PyObject *node_name = dget(spec, s_nodeName);
    PyObject *containers = dget(spec, s_containers);
    PyObject *inits = dget(spec, s_initContainers);
    PyObject *overhead = dget(spec, s_overhead);
    PyObject *volumes = dget(spec, s_volumes);
    if (PyErr_Occurred())
        return NULL;

    PyObject *requests = NULL;
    int has_ports = 0;
    if (containers != NULL && PyList_CheckExact(containers)) {
        Py_ssize_t nc = PyList_GET_SIZE(containers);
        for (Py_ssize_t i = 0; i < nc && !has_ports; i++) {
            PyObject *p = dget(PyList_GET_ITEM(containers, i), s_ports);
            if (p != NULL && p != Py_None)
                has_ports = 1;
        }
        if (nc == 1 && (inits == NULL || inits == Py_None)
            && (overhead == NULL || overhead == Py_None)) {
            PyObject *res = dget(PyList_GET_ITEM(containers, 0), s_resources);
            requests = dget(res, s_requests);
        }
    }
    /* initContainers can declare hostPorts too (_collect_host_ports
     * chains them): a ports key on ANY of them disqualifies the fast
     * path, same as for main containers */
    if (inits != NULL && PyList_CheckExact(inits)) {
        Py_ssize_t ni = PyList_GET_SIZE(inits);
        for (Py_ssize_t i = 0; i < ni && !has_ports; i++) {
            PyObject *p = dget(PyList_GET_ITEM(inits, i), s_ports);
            if (p != NULL && p != Py_None)
                has_ports = 1;
        }
    }
    int special_vol = 0;
    if (volumes != NULL && PyList_CheckExact(volumes)) {
        Py_ssize_t nv = PyList_GET_SIZE(volumes);
        for (Py_ssize_t i = 0; i < nv && !special_vol; i++) {
            PyObject *v = PyList_GET_ITEM(volumes, i);
            if (dget(v, s_pvc) || dget(v, s_gce) || dget(v, s_aws)
                || dget(v, s_azure) || dget(v, s_iscsi) || dget(v, s_csi))
                special_vol = 1;
        }
    }
    if (PyErr_Occurred())
        return NULL;
    int truthy_nominated = nominated != NULL && nominated != Py_None
                           && PyObject_IsTrue(nominated);
    int simple = (affinity == NULL || affinity == Py_None)
                 && (nodesel == NULL || nodesel == Py_None
                     || (PyDict_CheckExact(nodesel)
                         && PyDict_GET_SIZE(nodesel) == 0))
                 && (tsc == NULL || tsc == Py_None
                     || (PyList_CheckExact(tsc) && PyList_GET_SIZE(tsc) == 0))
                 && !has_ports && !special_vol && !truthy_nominated
                 && (node_name == NULL || node_name == Py_None
                     || !PyObject_IsTrue(node_name))
                 /* explicit JSON null (Py_None) for these keys is NOT the
                    same as the key being absent: the Python path's
                    spec.get("schedulerName", default) returns None, not
                    the default.  Punt nulls to Python instead of
                    guessing a coalescence it doesn't perform. */
                 && sched != Py_None && uid != Py_None && labels != Py_None;
    if (PyErr_Occurred())
        return NULL;
    if (!simple)
        Py_RETURN_FALSE;

    /* key = "ns/name" (namespaced) or name */
    PyObject *key;
    if (name == NULL)
        key = Py_NewRef(empty_unicode);
    else if (ns != NULL && ns != Py_None && PyObject_IsTrue(ns))
        key = PyUnicode_FromFormat("%U/%U", ns, name);
    else
        key = Py_NewRef(name);
    if (key == NULL)
        return NULL;

    int rc = 0;
    rc |= PyObject_SetAttr(pi, s_a_pod, pod);
    rc |= PyObject_SetAttr(pi, s_a_key, key);
    Py_DECREF(key);
    rc |= PyObject_SetAttr(pi, s_a_uid,
                           uid != NULL && uid != Py_None ? uid
                                                         : empty_unicode);
    rc |= PyObject_SetAttr(pi, s_a_labels,
                           labels != NULL && labels != Py_None ? labels
                                                               : empty_dict);
    rc |= PyObject_SetAttr(pi, s_a_priority,
                           priority != NULL && priority != Py_None
                               ? priority : zero_long);
    rc |= PyObject_SetAttr(pi, s_a_scheduler_name,
                           sched != NULL && sched != Py_None ? sched
                                                             : default_sched);
    rc |= PyObject_SetAttr(pi, s_a_nominated, empty_unicode);
    rc |= PyObject_SetAttr(pi, s_a_node_selector, empty_dict);
    rc |= PyObject_SetAttr(pi, s_a_tolerations,
                           tols != NULL && tols != Py_None ? tols
                                                           : empty_list);
    rc |= PyObject_SetAttr(pi, s_a_host_ports, empty_ports);
    rc |= PyObject_SetAttr(pi, s_a_tsc, empty_list);
    rc |= PyObject_SetAttr(pi, s_a_req_aff, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_req_anti, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_pref_aff, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_pref_anti, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_node_aff_req, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_node_aff_pref, empty_terms);
    rc |= PyObject_SetAttr(pi, s_a_plain, Py_True);
    if (rc != 0)
        return NULL;
    if (requests != NULL)
        return Py_NewRef(requests);
    Py_RETURN_NONE;
}

/* ---- clone_podinfos(infos, pods) -> list[PodInfo] -------------------- */
/* Batch clone_with_pod: for each (pi, pod) allocate a new instance of
 * type(pi), copy every slot named in __slots__, then point .pod at the
 * assumed object — the batch tail's per-pod PodInfo copy in one pass. */

static PyObject *
fasthost_clone_podinfos(PyObject *self, PyObject *args)
{
    PyObject *infos, *pods;
    if (!PyArg_ParseTuple(args, "OO", &infos, &pods))
        return NULL;
    if (!PyList_CheckExact(infos) || !PyList_CheckExact(pods)
        || PyList_GET_SIZE(infos) != PyList_GET_SIZE(pods)) {
        PyErr_SetString(PyExc_TypeError,
                        "clone_podinfos: two equal-length lists required");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(infos);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    PyObject *slots = NULL;  /* borrowed from the first pi's type */
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pi = PyList_GET_ITEM(infos, i);
        PyTypeObject *tp = Py_TYPE(pi);
        if (slots == NULL) {
            slots = PyObject_GetAttrString((PyObject *)tp, "__slots__");
            if (slots == NULL)
                goto fail;
        }
        PyObject *clone = tp->tp_alloc(tp, 0);
        if (clone == NULL)
            goto fail;
        Py_ssize_t ns_ = PyTuple_Check(slots) ? PyTuple_GET_SIZE(slots) : 0;
        for (Py_ssize_t j = 0; j < ns_; j++) {
            PyObject *sname = PyTuple_GET_ITEM(slots, j);
            PyObject *v = PyObject_GetAttr(pi, sname);
            if (v == NULL) {
                Py_DECREF(clone);
                goto fail;
            }
            int rc = PyObject_SetAttr(clone, sname, v);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(clone);
                goto fail;
            }
        }
        if (PyObject_SetAttr(clone, s_a_pod, PyList_GET_ITEM(pods, i)) < 0) {
            Py_DECREF(clone);
            goto fail;
        }
        PyList_SET_ITEM(out, i, clone);
    }
    Py_XDECREF(slots);
    return out;
fail:
    Py_XDECREF(slots);
    Py_DECREF(out);
    return NULL;
}

/* ---- watch_apply(events, indexer, deleted, added, modified) ---------- */
/* The informer's watch-burst hot loop (informer._list_and_watch) in one
 * C pass: per event, key = namespaced_name(ev.object); DELETED ->
 * indexer.pop(key, None); else prev = indexer.get(key) then
 * indexer[key] = ev.object.  Returns the (type, obj, prev) dispatch
 * triples.  The event-type sentinels come in from store.kv so C never
 * hardcodes protocol strings; the caller holds the informer locks, so
 * this runs the whole burst under ONE GIL-held stretch with no bytecode
 * dispatch between events (LATENCY r4-r5 item: informer front door). */

static PyObject *
namespaced_key(PyObject *obj)
{
    /* meta.namespaced_name semantics: metadata["name"] (KeyError when
     * absent, same as the Python path), namespace via .get(..., "") */
    PyObject *md = dget(obj, s_metadata);
    PyObject *name = dget(md, s_name);
    if (name == NULL) {
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_KeyError, "metadata.name");
        return NULL;
    }
    PyObject *ns = dget(md, s_namespace);
    if (PyErr_Occurred())
        return NULL;
    if (ns != NULL && ns != Py_None && PyUnicode_CheckExact(ns)
        && PyUnicode_GET_LENGTH(ns) > 0)
        return PyUnicode_FromFormat("%U/%U", ns, name);
    return Py_NewRef(name);
}

static PyObject *
fasthost_watch_apply(PyObject *self, PyObject *args)
{
    PyObject *events, *indexer, *t_deleted, *t_added, *t_modified;
    if (!PyArg_ParseTuple(args, "OOOOO", &events, &indexer, &t_deleted,
                          &t_added, &t_modified))
        return NULL;
    if (!PyList_CheckExact(events) || !PyDict_CheckExact(indexer)) {
        PyErr_SetString(PyExc_TypeError,
                        "watch_apply: (event list, indexer dict) required");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(events);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *ev = PyList_GET_ITEM(events, i);
        PyObject *evtype = NULL, *obj = NULL, *key = NULL, *prev = NULL;
        PyObject *ttype;                            /* borrowed sentinel */
        evtype = PyObject_GetAttr(ev, s_a_type);
        if (evtype == NULL)
            goto evfail;
        obj = PyObject_GetAttr(ev, s_a_object);
        if (obj == NULL)
            goto evfail;
        key = namespaced_key(obj);
        if (key == NULL)
            goto evfail;
        int is_del = PyObject_RichCompareBool(evtype, t_deleted, Py_EQ);
        if (is_del < 0)
            goto evfail;
        prev = PyDict_GetItemWithError(indexer, key);   /* borrowed */
        if (prev == NULL && PyErr_Occurred())
            goto evfail;
        Py_XINCREF(prev);
        if (is_del) {
            if (prev != NULL && PyDict_DelItem(indexer, key) < 0)
                goto evfail;
            ttype = t_deleted;
        } else {
            if (PyDict_SetItem(indexer, key, obj) < 0)
                goto evfail;
            ttype = prev != NULL ? t_modified : t_added;
        }
        PyObject *triple = PyTuple_Pack(3, ttype, obj,
                                        prev != NULL ? prev : Py_None);
        if (triple == NULL)
            goto evfail;
        Py_DECREF(evtype); Py_DECREF(obj); Py_DECREF(key); Py_XDECREF(prev);
        PyList_SET_ITEM(out, i, triple);                /* steals */
        continue;
    evfail:
        Py_XDECREF(evtype); Py_XDECREF(obj); Py_XDECREF(key);
        Py_XDECREF(prev);
        Py_DECREF(out);
        return NULL;
    }
    return out;
}

/* ---- binding_rows(ready) -> list[(ns, name, node)] ------------------- */
/* The bulk-bind submit loop (scheduler._bulk_bind_commit): one C pass
 * building the (namespace, name, node) wire rows from the ready
 * (state, qpi, node, assumed) tuples — this list comprehension runs on
 * the binder worker, i.e. directly on the bind critical path. */

static PyObject *
fasthost_binding_rows(PyObject *self, PyObject *args)
{
    PyObject *ready;
    if (!PyArg_ParseTuple(args, "O", &ready))
        return NULL;
    if (!PyList_CheckExact(ready)) {
        PyErr_SetString(PyExc_TypeError, "binding_rows: list required");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(ready);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(ready, i);
        if (!PyTuple_CheckExact(item) || PyTuple_GET_SIZE(item) < 3) {
            PyErr_SetString(PyExc_TypeError,
                            "binding_rows: (state, qpi, node, ...) tuples");
            goto fail;
        }
        PyObject *qpi = PyTuple_GET_ITEM(item, 1);
        PyObject *node = PyTuple_GET_ITEM(item, 2);
        PyObject *pod = PyObject_GetAttr(qpi, s_a_pod);
        if (pod == NULL)
            goto fail;
        PyObject *md = dget(pod, s_metadata);
        PyObject *name = dget(md, s_name);
        if (name == NULL) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_KeyError, "metadata.name");
            Py_DECREF(pod);
            goto fail;
        }
        PyObject *ns = dget(md, s_namespace);
        if (PyErr_Occurred()) {
            Py_DECREF(pod);
            goto fail;
        }
        /* meta.namespace: .get(..., "") — absent key -> "", an explicit
         * null passes through as None (same as the Python original) */
        PyObject *row = PyTuple_Pack(3, ns != NULL ? ns : empty_unicode,
                                     name, node);
        Py_DECREF(pod);
        if (row == NULL)
            goto fail;
        PyList_SET_ITEM(out, i, row);                   /* steals */
    }
    return out;
fail:
    Py_DECREF(out);
    return NULL;
}

static PyMethodDef FasthostMethods[] = {
    {"watch_apply", fasthost_watch_apply, METH_VARARGS,
     "Apply a watch burst to the indexer; return dispatch triples."},
    {"binding_rows", fasthost_binding_rows, METH_VARARGS,
     "Build (namespace, name, node) bind rows from ready tuples."},
    {"pod_scan_into", fasthost_pod_scan_into, METH_VARARGS,
     "Fill a PodInfo's slots from a simple pod in one C pass."},
    {"clone_podinfos", fasthost_clone_podinfos, METH_VARARGS,
     "Batch clone_with_pod over slot classes."},
    {"build_assumed", fasthost_build_assumed, METH_VARARGS,
     "Per-pod 2-level copy with spec.nodeName set, in one C pass."},
    {"req_columns", fasthost_req_columns, METH_VARARGS,
     "Fill float32 request columns from PodInfo.request(_nonzero)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fasthostmodule = {
    PyModuleDef_HEAD_INIT, "_fasthost",
    "C helpers for scheduler per-pod host hot paths", -1, FasthostMethods,
};

PyMODINIT_FUNC
PyInit__fasthost(void)
{
    if (intern_all() < 0 || intern_attrs() < 0)
        return NULL;
    return PyModule_Create(&fasthostmodule);
}
