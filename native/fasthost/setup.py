from setuptools import Extension, setup

setup(
    name="fasthost",
    version="1.0",
    ext_modules=[Extension("_fasthost", sources=["fasthost.c"],
                           extra_compile_args=["-O2"])],
)
