/* pause — the pod-sandbox init process.
 *
 * Reference behavior: build/pause/linux/pause.c (68 LoC) — the only native
 * program in the reference tree.  It holds a pod's shared namespaces open
 * and reaps zombies re-parented to it:
 *   - SIGINT/SIGTERM -> exit cleanly
 *   - SIGCHLD        -> waitpid(-1, ..., WNOHANG) loop
 *   - otherwise      -> pause() forever
 * Built via native/Makefile; the hollow runtime doesn't exec it (sandboxes
 * are simulated), but a real CRI integration points its sandbox image here.
 */

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define VERSION "tpu-pause-1.0"

static void sigdown(int signo) {
  psignal(signo, "shutting down, got signal");
  exit(0);
}

static void sigreap(int signo) {
  (void)signo;
  while (waitpid(-1, NULL, WNOHANG) > 0)
    ;
}

int main(int argc, char **argv) {
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "-v") || !strcmp(argv[i], "--version")) {
      printf("%s\n", VERSION);
      return 0;
    }
  }
  if (getpid() != 1)
    fprintf(stderr, "warning: pause should be the first process\n");

  if (sigaction(SIGINT, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 1;
  if (sigaction(SIGTERM, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 2;
  if (sigaction(SIGCHLD,
                &(struct sigaction){.sa_handler = sigreap,
                                    .sa_flags = SA_NOCLDSTOP},
                NULL) < 0)
    return 3;

  for (;;)
    pause();
  fprintf(stderr, "error: infinite loop terminated\n");
  return 42;
}
