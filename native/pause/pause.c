/* ktpu-pause: minimal init for a simulated pod sandbox.
 *
 * Role (behavioral parity with the reference's sandbox init,
 * build/pause/linux/pause.c): keep the pod's shared namespaces alive,
 * reap orphaned children, and terminate on the runtime's stop signal.
 *
 * Design (deliberately different from the reference): instead of
 * installing async signal handlers and sleeping in pause(), we block the
 * signals of interest and drain them synchronously with sigwaitinfo().
 * This keeps all logic on the main thread — no handler reentrancy rules
 * to respect — and makes the state machine a plain loop:
 *
 *     mask {TERM, INT, CHLD}  ->  wait  ->  reap | quit
 *
 * Built via native/Makefile.  The hollow CRI runtime never execs this
 * (sandboxes are simulated); a real CRI integration would use it as the
 * sandbox image's entrypoint.
 */

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

static const char kVersion[] = "ktpu-pause 2.0";

/* Collect every exited child without blocking; orphans in the pid
 * namespace re-parent to us, so this doubles as the zombie reaper. */
static void reap_children(void) {
  pid_t done;
  do {
    done = waitpid(-1, NULL, WNOHANG);
  } while (done > 0);
}

int main(int argc, char **argv) {
  sigset_t watched;
  int arg;

  for (arg = 1; arg < argc; ++arg) {
    if (strcmp(argv[arg], "--version") == 0 || strcmp(argv[arg], "-V") == 0) {
      puts(kVersion);
      return 0;
    }
  }

  if (getpid() != 1)
    fprintf(stderr,
            "ktpu-pause: running as pid %d (expected to be the sandbox "
            "init)\n",
            (int)getpid());

  sigemptyset(&watched);
  sigaddset(&watched, SIGTERM);
  sigaddset(&watched, SIGINT);
  sigaddset(&watched, SIGCHLD);
  if (sigprocmask(SIG_BLOCK, &watched, NULL) != 0) {
    perror("ktpu-pause: sigprocmask");
    return 10;
  }

  for (;;) {
    siginfo_t info;
    if (sigwaitinfo(&watched, &info) < 0) {
      if (errno == EINTR)
        continue;
      perror("ktpu-pause: sigwaitinfo");
      return 11;
    }
    switch (info.si_signo) {
    case SIGCHLD:
      reap_children();
      break;
    case SIGTERM:
    case SIGINT:
      fprintf(stderr, "ktpu-pause: exiting on %s\n", strsignal(info.si_signo));
      /* final sweep so no zombie outlives the sandbox */
      reap_children();
      return 0;
    default:
      break;
    }
  }
}
