"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(mesh/shard_map over the node axis) is exercised without TPU hardware, per
the driver contract.  The environment pins the real TPU platform via a
sitecustomize (JAX_PLATFORMS=axon), so env vars alone don't stick — we
override through jax.config before any backend initializes.
"""

import pytest

# the shared bootstrap (also used by tools/collective_census.py and the
# runtime census): sets XLA_FLAGS/JAX_PLATFORMS before the jax import
# AND forces the platform through jax.config
from kubernetes_tpu.component_base.profiling import ensure_virtual_mesh

ensure_virtual_mesh(8)


@pytest.fixture
def no_implicit_transfers():
    """Scope under jax's device->host transfer guard: any implicit pull
    raises; explicit jax.device_get (the `# sync-point:` idiom the
    device-sync lint rule enforces) stays allowed.  On CPU the arrays
    are host-resident so the guard can't trip, but the wiring is what
    TPU CI inherits — see tools/ktpulint/sanitizers.py."""
    from tools.ktpulint.sanitizers import transfer_guard

    with transfer_guard():
        yield


@pytest.fixture
def compile_counter():
    """Factory for CompileCounter contexts (zero-per-wave-recompile
    assertions in device-path suites)."""
    from tools.ktpulint.sanitizers import CompileCounter

    return CompileCounter


def pytest_configure(config):
    # registered here because the repo carries no pytest.ini; without this,
    # -m 'not slow' (the tier-1 selector) relies on unregistered markers
    config.addinivalue_line(
        "markers",
        "slow: long-running scheduling/e2e tests, excluded from tier-1 "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests for the remote TPU seam "
        "(tests/test_chaos_seam.py; deterministic, seeded)")
    config.addinivalue_line(
        "markers",
        "scaleout: multi-instance scheduler tests (tests/test_scaleout.py); "
        "tier-1 runs the shrunk 2-instance chaos case, the full "
        "churn matrix is additionally marked slow")
    config.addinivalue_line(
        "markers",
        "churn: randomized incremental-flatten parity tests "
        "(tests/test_churn_parity.py; seeded event streams pinned "
        "against from-scratch re-flatten — large tier is also slow)")
    config.addinivalue_line(
        "markers",
        "proc: process-true topology tests that spawn real apiserver + "
        "scheduler OS processes (scheduler/procrun.py); every such test "
        "takes the proc_reaper fixture so a hung child can never wedge "
        "tier-1")
    config.addinivalue_line(
        "markers",
        "upgrade: zero-downtime-operations tests (rolling restart, "
        "checkpointed warm-start, config hot-reload); tier-1 runs the "
        "shrunk 2-process rolling-restart pass, the full churn matrix "
        "is additionally marked slow")
    config.addinivalue_line(
        "markers",
        "pipeline: depth-2 wave-pipeline tests (fenced dispatch, "
        "pipelined churn parity, per-wave watchdog deadlines, timeline "
        "overhead with overlapping waves)")
    config.addinivalue_line(
        "markers",
        "storm: churn-storm chaos tier (tests/test_churn_storm.py; "
        "seeded node add/drain/relabel floods mid-wave with a bind "
        "ledger on top); tier-1 runs the shrunk storm, the full-size "
        "run is additionally marked slow")


@pytest.fixture
def proc_reaper():
    """Hard-timeout + orphan-reaping belt for process-topology tests.

    Yields a `register(cluster_or_popen)` function.  On teardown — pass
    OR fail — everything registered is force-reaped (ProcCluster via
    shutdown(), bare Popens via kill), and a watchdog thread SIGKILLs
    the registered children if the test body itself outlives the hard
    deadline, so a wedged child can't hold the suite past its timeout.
    """
    import subprocess
    import threading

    registered: list = []
    reaped = threading.Event()

    def _reap():
        for item in registered:
            try:
                if isinstance(item, subprocess.Popen):
                    if item.poll() is None:
                        item.kill()
                        item.wait(timeout=10.0)
                else:
                    item.shutdown()
            except Exception:  # noqa: BLE001 - reaping is best-effort
                pass

    def _watchdog():
        # hard ceiling per proc test; generous next to the per-call
        # readiness timeouts, tiny next to the tier-1 driver timeout
        if not reaped.wait(240.0):
            _reap()

    threading.Thread(target=_watchdog, name="proc-reaper", daemon=True).start()
    try:
        yield registered.append
    finally:
        _reap()
        reaped.set()
