"""Test configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding
(mesh/shard_map over the node axis) is exercised without TPU hardware, per
the driver contract.  The environment pins the real TPU platform via a
sitecustomize (JAX_PLATFORMS=axon), so env vars alone don't stick — we
override through jax.config before any backend initializes.
"""

import pytest

# the shared bootstrap (also used by tools/collective_census.py and the
# runtime census): sets XLA_FLAGS/JAX_PLATFORMS before the jax import
# AND forces the platform through jax.config
from kubernetes_tpu.component_base.profiling import ensure_virtual_mesh

ensure_virtual_mesh(8)


@pytest.fixture
def no_implicit_transfers():
    """Scope under jax's device->host transfer guard: any implicit pull
    raises; explicit jax.device_get (the `# sync-point:` idiom the
    device-sync lint rule enforces) stays allowed.  On CPU the arrays
    are host-resident so the guard can't trip, but the wiring is what
    TPU CI inherits — see tools/ktpulint/sanitizers.py."""
    from tools.ktpulint.sanitizers import transfer_guard

    with transfer_guard():
        yield


@pytest.fixture
def compile_counter():
    """Factory for CompileCounter contexts (zero-per-wave-recompile
    assertions in device-path suites)."""
    from tools.ktpulint.sanitizers import CompileCounter

    return CompileCounter


def pytest_configure(config):
    # registered here because the repo carries no pytest.ini; without this,
    # -m 'not slow' (the tier-1 selector) relies on unregistered markers
    config.addinivalue_line(
        "markers",
        "slow: long-running scheduling/e2e tests, excluded from tier-1 "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests for the remote TPU seam "
        "(tests/test_chaos_seam.py; deterministic, seeded)")
    config.addinivalue_line(
        "markers",
        "scaleout: multi-instance scheduler tests (tests/test_scaleout.py); "
        "tier-1 runs the shrunk 2-instance chaos case, the full "
        "churn matrix is additionally marked slow")
