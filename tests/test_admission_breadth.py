"""Round-5 admission plugins (VERDICT r4 item #7 + missing #4).

Reference: pkg/kubeapiserver/options/plugins.go:64-101 ordering;
plugin/pkg/admission/noderestriction/admission.go:199 (kubelet writes
pinned to its own node), serviceaccount (token volume injection),
storage/storageclass/setdefault, storageobjectinuseprotection,
nodetaint, PodSecurity, gc (OwnerReferencesPermissionEnforcement).
"""

from __future__ import annotations

import pytest

from kubernetes_tpu.apiserver import admission as adm
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def attrs(verb, resource, obj, old=None, ns="default", name="",
          user="", groups=()):
    return adm.Attributes(verb, resource, obj, old, namespace=ns,
                          name=name, user=user, groups=groups)


KUBELET = dict(user="system:node:n1", groups=("system:nodes",))


class TestNodeRestriction:
    def setup_method(self):
        self.p = adm.NodeRestriction()

    def test_kubelet_creates_pod_bound_to_itself(self):
        pod = make_pod("p").build()
        pod["spec"]["nodeName"] = "n1"
        self.p.admit(attrs(adm.CREATE, "pods", pod, **KUBELET))

    def test_kubelet_cannot_create_pod_for_other_node(self):
        pod = make_pod("p").build()
        pod["spec"]["nodeName"] = "n2"
        with pytest.raises(adm.AdmissionDenied):
            self.p.admit(attrs(adm.CREATE, "pods", pod, **KUBELET))

    def test_kubelet_cannot_update_other_nodes_pod_status(self):
        cur = make_pod("p").build()
        cur["spec"]["nodeName"] = "n2"
        new = dict(cur)
        with pytest.raises(adm.AdmissionDenied):
            self.p.admit(attrs(adm.UPDATE, "pods", new, cur, name="p",
                               **KUBELET))

    def test_kubelet_updates_own_pod_status(self):
        cur = make_pod("p").build()
        cur["spec"]["nodeName"] = "n1"
        self.p.admit(attrs(adm.UPDATE, "pods", dict(cur), cur, name="p",
                           **KUBELET))

    def test_kubelet_delete_scoped_by_current_binding(self):
        cur = make_pod("p").build()
        cur["spec"]["nodeName"] = "n2"
        with pytest.raises(adm.AdmissionDenied):
            self.p.admit(attrs(adm.DELETE, "pods", None, cur, name="p",
                               **KUBELET))

    def test_kubelet_cannot_touch_other_node_object(self):
        node = make_node("n2").build()
        with pytest.raises(adm.AdmissionDenied):
            self.p.admit(attrs(adm.UPDATE, "nodes", node, ns="",
                               name="n2", **KUBELET))
        self.p.admit(attrs(adm.UPDATE, "nodes", make_node("n1").build(),
                           ns="", name="n1", **KUBELET))

    def test_non_kubelet_users_unrestricted(self):
        pod = make_pod("p").build()
        pod["spec"]["nodeName"] = "n2"
        self.p.admit(attrs(adm.CREATE, "pods", pod, user="alice",
                           groups=("system:authenticated",)))


class TestServiceAccount:
    def setup_method(self):
        self.store = kv.MemoryStore()
        self.p = adm.ServiceAccount(self.store)

    def test_defaults_and_injects_token_volume(self):
        pod = make_pod("p").build()
        a = attrs(adm.CREATE, "pods", pod)
        self.p.admit(a)
        self.p.validate(a)
        spec = a.obj["spec"]
        assert spec["serviceAccountName"] == "default"
        vols = [v for v in spec["volumes"]
                if v["name"].startswith("kube-api-access")]
        assert len(vols) == 1
        srcs = vols[0]["projected"]["sources"]
        assert any("serviceAccountToken" in s for s in srcs)
        mounts = spec["containers"][0]["volumeMounts"]
        assert any(m["mountPath"]
                   == adm.ServiceAccount.MOUNT_PATH for m in mounts)

    def test_named_missing_account_rejected(self):
        pod = make_pod("p").build()
        pod["spec"]["serviceAccountName"] = "builder"
        a = attrs(adm.CREATE, "pods", pod)
        self.p.admit(a)
        with pytest.raises(adm.AdmissionDenied):
            self.p.validate(a)

    def test_named_existing_account_accepted(self):
        self.store.create("serviceaccounts", {
            "apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": "builder", "namespace": "default"}})
        pod = make_pod("p").build()
        pod["spec"]["serviceAccountName"] = "builder"
        a = attrs(adm.CREATE, "pods", pod)
        self.p.admit(a)
        self.p.validate(a)

    def test_automount_false_skips_injection(self):
        pod = make_pod("p").build()
        pod["spec"]["automountServiceAccountToken"] = False
        a = attrs(adm.CREATE, "pods", pod)
        self.p.admit(a)
        assert not any(v["name"].startswith("kube-api-access")
                       for v in a.obj["spec"].get("volumes", ()))


class TestDefaultStorageClass:
    def _pvc(self):
        return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": {"name": "c", "namespace": "default"},
                "spec": {"resources": {"requests": {"storage": "1Gi"}}}}

    def test_default_class_applied(self):
        store = kv.MemoryStore()
        store.create("storageclasses", {
            "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": {"name": "fast", "annotations": {
                adm.DefaultStorageClass.DEFAULT_ANN: "true"}}})
        p = adm.DefaultStorageClass(store)
        a = attrs(adm.CREATE, "persistentvolumeclaims", self._pvc())
        p.admit(a)
        assert a.obj["spec"]["storageClassName"] == "fast"

    def test_explicit_class_untouched(self):
        store = kv.MemoryStore()
        store.create("storageclasses", {
            "apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": {"name": "fast", "annotations": {
                adm.DefaultStorageClass.DEFAULT_ANN: "true"}}})
        p = adm.DefaultStorageClass(store)
        pvc = self._pvc()
        pvc["spec"]["storageClassName"] = ""  # explicit no-class
        a = attrs(adm.CREATE, "persistentvolumeclaims", pvc)
        p.admit(a)
        assert a.obj["spec"]["storageClassName"] == ""

    def test_no_default_leaves_unset(self):
        p = adm.DefaultStorageClass(kv.MemoryStore())
        a = attrs(adm.CREATE, "persistentvolumeclaims", self._pvc())
        p.admit(a)
        assert "storageClassName" not in a.obj["spec"]


class TestStorageProtectionAndNodeTaint:
    def test_pvc_pv_finalizers(self):
        p = adm.StorageObjectInUseProtection()
        pvc = {"metadata": {"name": "c", "namespace": "default"},
               "spec": {}}
        p.admit(attrs(adm.CREATE, "persistentvolumeclaims", pvc))
        assert "kubernetes.io/pvc-protection" in \
            pvc["metadata"]["finalizers"]
        pv = {"metadata": {"name": "v"}, "spec": {}}
        p.admit(attrs(adm.CREATE, "persistentvolumes", pv, ns=""))
        assert "kubernetes.io/pv-protection" in pv["metadata"]["finalizers"]

    def test_new_node_gets_not_ready_taint(self):
        p = adm.TaintNodesByCondition()
        node = make_node("n1").build()
        p.admit(attrs(adm.CREATE, "nodes", node, ns=""))
        assert any(t["key"] == "node.kubernetes.io/not-ready"
                   and t["effect"] == "NoSchedule"
                   for t in node["spec"]["taints"])
        # idempotent
        p.admit(attrs(adm.CREATE, "nodes", node, ns=""))
        assert sum(1 for t in node["spec"]["taints"]
                   if t["key"] == "node.kubernetes.io/not-ready") == 1


class TestPodSecurity:
    def _store_with_ns(self, level):
        store = kv.MemoryStore()
        store.create("namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "locked", "namespace": None,
                         "labels": {adm.PodSecurity.ENFORCE_LABEL: level}}})
        return store

    def test_baseline_rejects_host_namespaces_and_privileged(self):
        p = adm.PodSecurity(self._store_with_ns("baseline"))
        pod = make_pod("p", "locked").build()
        pod["spec"]["hostNetwork"] = True
        with pytest.raises(adm.AdmissionDenied):
            p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))
        pod = make_pod("p", "locked").build()
        pod["spec"]["containers"][0]["securityContext"] = {
            "privileged": True}
        with pytest.raises(adm.AdmissionDenied):
            p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))
        pod = make_pod("p", "locked").build()
        pod["spec"]["volumes"] = [{"name": "h", "hostPath": {"path": "/"}}]
        with pytest.raises(adm.AdmissionDenied):
            p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))

    def test_baseline_allows_plain_pod(self):
        p = adm.PodSecurity(self._store_with_ns("baseline"))
        pod = make_pod("p", "locked").build()
        p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))

    def test_restricted_requires_hardening(self):
        p = adm.PodSecurity(self._store_with_ns("restricted"))
        pod = make_pod("p", "locked").build()
        with pytest.raises(adm.AdmissionDenied):
            p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))
        pod["spec"]["containers"][0]["securityContext"] = {
            "runAsNonRoot": True, "allowPrivilegeEscalation": False,
            "capabilities": {"drop": ["ALL"]}}
        p.validate(attrs(adm.CREATE, "pods", pod, ns="locked"))

    def test_unlabeled_namespace_is_privileged(self):
        p = adm.PodSecurity(kv.MemoryStore())
        pod = make_pod("p").build()
        pod["spec"]["hostNetwork"] = True
        p.validate(attrs(adm.CREATE, "pods", pod))


class TestOwnerReferencesPermissionEnforcement:
    def _pod_with_block(self):
        pod = make_pod("p").build()
        pod["metadata"]["ownerReferences"] = [{
            "apiVersion": "apps/v1", "kind": "ReplicaSet", "name": "rs",
            "uid": "u1", "blockOwnerDeletion": True}]
        return pod

    def test_denied_without_finalizer_permission(self):
        p = adm.OwnerReferencesPermissionEnforcement(
            lambda *a: False)
        with pytest.raises(adm.AdmissionDenied):
            p.validate(attrs(adm.CREATE, "pods", self._pod_with_block(),
                             user="alice"))

    def test_allowed_with_permission(self):
        seen = []

        def authorize(user, groups, verb, resource, sub, ns, name):
            seen.append((verb, resource, sub, name))
            return True

        p = adm.OwnerReferencesPermissionEnforcement(authorize)
        p.validate(attrs(adm.CREATE, "pods", self._pod_with_block(),
                         user="alice"))
        assert seen == [("update", "replicasets", "finalizers", "rs")]

    def test_unchanged_block_allowed(self):
        p = adm.OwnerReferencesPermissionEnforcement(lambda *a: False)
        pod = self._pod_with_block()
        p.validate(attrs(adm.UPDATE, "pods", pod, pod, user="alice"))

    def test_no_authorizer_disables(self):
        adm.OwnerReferencesPermissionEnforcement(None).validate(
            attrs(adm.CREATE, "pods", self._pod_with_block()))


class TestChainIntegration:
    def test_default_chain_order_and_disable(self):
        store = kv.MemoryStore()
        chain = adm.default_chain(store)
        names = [p.name for p in chain.plugins]
        assert names[-1] == "ResourceQuota"  # quota last (plugins.go)
        assert "NodeRestriction" in names and "ServiceAccount" in names
        reduced = adm.default_chain(store, disable=frozenset(
            ("ServiceAccount", "TaintNodesByCondition", "Priority")))
        rnames = [p.name for p in reduced.plugins]
        for gone in ("ServiceAccount", "TaintNodesByCondition", "Priority"):
            assert gone not in rnames

    def test_http_noderestriction_end_to_end(self):
        """A kubelet token creating a pod for another node is rejected
        by the real front door; for its own node it lands."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        store = kv.MemoryStore()
        server = APIServer(
            store,
            tokens={"kubelet-tok": ("system:node:n1", ("system:nodes",)),
                    "admin-tok": ("admin", ("system:masters",))},
            enable_default_admission=True,
            disable_admission_plugins=frozenset(
                ("ServiceAccount", "TaintNodesByCondition"))).start()
        try:
            kubelet = HTTPClient.from_url(server.url, token="kubelet-tok")
            bad = make_pod("mirror-bad").build()
            bad["spec"]["nodeName"] = "n2"
            with pytest.raises(Exception) as ei:
                kubelet.create("pods", bad)
            assert "NodeRestriction" in str(ei.value)
            good = make_pod("mirror-good").build()
            good["spec"]["nodeName"] = "n1"
            created = kubelet.create("pods", good)
            assert created["spec"]["nodeName"] == "n1"
        finally:
            server.stop()


class TestNodeRestrictionGapClosures:
    """Round-5 review findings: rebind-via-update and the
    status-subresource bypass."""

    def test_kubelet_cannot_rebind_own_pod_elsewhere(self):
        p = adm.NodeRestriction()
        cur = make_pod("p").build()
        cur["spec"]["nodeName"] = "n1"
        new = {"metadata": dict(cur["metadata"]),
               "spec": {**cur["spec"], "nodeName": "n2"}}
        with pytest.raises(adm.AdmissionDenied):
            p.admit(attrs(adm.UPDATE, "pods", new, cur, name="p",
                          **KUBELET))

    def test_status_put_passes_admission(self):
        """A kubelet token PUTting another node's pod STATUS via the
        real front door is rejected (the path used to bypass the
        chain)."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        store = kv.MemoryStore()
        pod = make_pod("other-pod").build()
        pod["spec"]["nodeName"] = "n2"
        store.create("pods", pod)
        server = APIServer(
            store,
            tokens={"kubelet-tok": ("system:node:n1", ("system:nodes",)),
                    "admin-tok": ("admin", ("system:masters",))},
            enable_default_admission=True,
            disable_admission_plugins=frozenset(
                ("ServiceAccount", "TaintNodesByCondition"))).start()
        try:
            kubelet = HTTPClient.from_url(server.url, token="kubelet-tok")
            body = {"metadata": {"name": "other-pod",
                                 "namespace": "default"},
                    "status": {"phase": "Running"}}
            with pytest.raises(Exception) as ei:
                kubelet._request(
                    "PUT",
                    "/api/v1/namespaces/default/pods/other-pod/status",
                    body)
            assert "NodeRestriction" in str(ei.value)
            stored = store.get("pods", "default", "other-pod")
            assert (stored.get("status") or {}).get("phase") != "Running"
        finally:
            server.stop()
