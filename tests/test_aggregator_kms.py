"""Aggregation layer (APIService proxying) + KMS envelope encryption.

Behavioral contracts from staging/src/k8s.io/kube-aggregator and
staging/src/k8s.io/kms + apiserver/pkg/storage/value/encrypt/envelope.
"""

import importlib.util
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.aggregator import APISERVICES
from kubernetes_tpu.store import kv
from kubernetes_tpu.store.encryption import (
    ENVELOPE_KEY, DecryptError, EnvelopeTransformer, LocalKMS,
)

requires_crypto = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="AES-GCM sealing needs the cryptography package")


def http(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class _EchoBackend:
    """Stand-in aggregated apiserver: echoes method+path as JSON."""

    def __init__(self):
        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _serve(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length).decode() if length else ""
                payload = json.dumps({"backend": True,
                                      "method": self.command,
                                      "path": self.path,
                                      "body": body}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = do_POST = do_PUT = do_DELETE = _serve

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()


class TestAggregator:
    def test_apiservice_routes_to_backend(self):
        store = kv.MemoryStore()
        server = APIServer(store).start()
        backend = _EchoBackend()
        try:
            apisvc = meta.new_object("APIService",
                                     "v1beta1.metrics.example.com", None)
            apisvc["spec"] = {"group": "metrics.example.com",
                              "version": "v1beta1",
                              "service": {"url": backend.url}}
            code, _ = http("POST", f"{server.url}/apis/apiregistration.k8s.io"
                           "/v1/apiservices", apisvc)
            assert code in (200, 201)
            time.sleep(0.6)  # registry watch applies the route

            code, body = http("GET", f"{server.url}/apis/metrics.example.com"
                              "/v1beta1/nodes")
            assert code == 200 and body["backend"] is True
            assert body["path"].endswith("/v1beta1/nodes")
            # unregistered group still served locally
            code, body = http("GET", f"{server.url}/apis/apps/v1/deployments")
            assert code == 200 and "items" in body
        finally:
            backend.stop()
            server.stop()

    def test_builtin_group_cannot_be_shadowed(self):
        """An APIService naming a built-in group (e.g. v1.apps) must NOT
        redirect apps/v1 traffic to an external backend — the reference's
        Local APIServices always win (kube-aggregator apiservice.go)."""
        store = kv.MemoryStore()
        server = APIServer(store).start()
        backend = _EchoBackend()
        try:
            apisvc = meta.new_object("APIService", "v1.apps", None)
            apisvc["spec"] = {"group": "apps", "version": "v1",
                              "service": {"url": backend.url}}
            store.create(APISERVICES, apisvc)
            time.sleep(0.6)
            code, body = http("GET", f"{server.url}/apis/apps/v1/deployments")
            assert code == 200
            assert "backend" not in body and "items" in body
        finally:
            backend.stop()
            server.stop()

    def test_crd_group_cannot_be_shadowed(self):
        """A service-backed APIService must not hijack a group served by an
        established CRD — even if the APIService was created FIRST (the
        reference autoregister controller pins Local APIServices for CRD
        groups)."""
        store = kv.MemoryStore()
        server = APIServer(store).start()
        backend = _EchoBackend()
        try:
            apisvc = meta.new_object("APIService", "v1.widgets.example.com",
                                     None)
            apisvc["spec"] = {"group": "widgets.example.com", "version": "v1",
                              "service": {"url": backend.url}}
            store.create(APISERVICES, apisvc)
            time.sleep(0.6)
            crd = meta.new_object("CustomResourceDefinition",
                                  "widgets.widgets.example.com", None)
            crd["spec"] = {"group": "widgets.example.com",
                           "names": {"plural": "widgets", "kind": "Widget"},
                           "scope": "Namespaced",
                           "versions": [{"name": "v1", "served": True,
                                         "storage": True}]}
            code, _ = http("POST", f"{server.url}/apis/apiextensions.k8s.io"
                           "/v1/customresourcedefinitions", crd)
            assert code in (200, 201)
            code, body = http(
                "GET", f"{server.url}/apis/widgets.example.com/v1/"
                "namespaces/default/widgets")
            assert code == 200
            assert "backend" not in body and "items" in body
        finally:
            backend.stop()
            server.stop()

    def test_unreachable_backend_returns_503(self):
        store = kv.MemoryStore()
        server = APIServer(store).start()
        try:
            apisvc = meta.new_object("APIService", "v1.dead.example.com", None)
            apisvc["spec"] = {"group": "dead.example.com", "version": "v1",
                              "service": {"url": "http://127.0.0.1:1"}}
            store.create(APISERVICES, apisvc)
            time.sleep(0.6)
            code, body = http("GET",
                              f"{server.url}/apis/dead.example.com/v1/things")
            assert code == 503
            assert body["reason"] == "ServiceUnavailable"
        finally:
            server.stop()


@requires_crypto
class TestEnvelopeEncryption:
    def _store(self):
        kms = LocalKMS()
        t = EnvelopeTransformer(kms)
        return kv.MemoryStore(transformers={"secrets": t}), kms, t

    def test_secrets_sealed_at_rest_plain_on_read(self):
        store, kms, t = self._store()
        s = meta.new_object("Secret", "db-pass", "default")
        s["data"] = {"password": "hunter2"}
        store.create("secrets", s)
        # at rest: envelope, no plaintext
        raw = store._data["secrets"]["default/db-pass"]
        assert ENVELOPE_KEY in raw and "data" not in raw
        assert "hunter2" not in json.dumps(raw)
        # reads serve plaintext
        got = store.get("secrets", "default", "db-pass")
        assert got["data"]["password"] == "hunter2"
        items, _ = store.list("secrets", "default")
        assert items[0]["data"]["password"] == "hunter2"
        # other resources untouched
        cm = meta.new_object("ConfigMap", "plain", "default")
        cm["data"] = {"k": "v"}
        store.create("configmaps", cm)
        assert "data" in store._data["configmaps"]["default/plain"]

    def test_update_and_watch_roundtrip(self):
        store, kms, t = self._store()
        s = meta.new_object("Secret", "tok", "default")
        s["data"] = {"t": "one"}
        store.create("secrets", s)
        w = store.watch("secrets", since_rv=0)
        ev = w.next(timeout=1)
        assert ev.type == kv.ADDED and ev.object["data"]["t"] == "one"

        def bump(o):
            o["data"]["t"] = "two"
            return o
        store.guaranteed_update("secrets", "default", "tok", bump)
        ev = w.next(timeout=1)
        assert ev.type == kv.MODIFIED and ev.object["data"]["t"] == "two"
        assert store.get("secrets", "default", "tok")["data"]["t"] == "two"
        w.stop()

    def test_key_rotation_keeps_old_data_readable(self):
        store, kms, t = self._store()
        s = meta.new_object("Secret", "old", "default")
        s["data"] = {"v": "pre-rotation"}
        store.create("secrets", s)
        old_kid = store._data["secrets"]["default/old"][ENVELOPE_KEY]["kid"]
        kms.rotate()
        # old object still decrypts with the retired key
        assert store.get("secrets", "default", "old")["data"]["v"] == \
            "pre-rotation"
        # new writes use the new key
        s2 = meta.new_object("Secret", "new", "default")
        s2["data"] = {"v": "post"}
        store.create("secrets", s2)
        new_kid = store._data["secrets"]["default/new"][ENVELOPE_KEY]["kid"]
        assert new_kid != old_kid

    def test_unknown_key_raises(self):
        kms = LocalKMS()
        with pytest.raises(DecryptError):
            kms.decrypt("nope", b"x" * 32)

    def test_finalizer_delete_flow_stays_plaintext_to_watchers(self):
        store, kms, t = self._store()
        s = meta.new_object("Secret", "fin", "default")
        s["metadata"]["finalizers"] = ["example.com/hold"]
        s["data"] = {"v": "sealed"}
        store.create("secrets", s)
        w = store.watch("secrets", since_rv=store.revision)
        marked = store.delete("secrets", "default", "fin")
        assert marked["metadata"]["deletionTimestamp"]
        assert marked["data"]["v"] == "sealed"  # caller sees plaintext
        ev = w.next(timeout=1)
        assert ev.object["data"]["v"] == "sealed"

        def strip(o):
            o["metadata"]["finalizers"] = []
            return o
        store.guaranteed_update("secrets", "default", "fin", strip)
        ev = w.next(timeout=1)
        assert ev.type == kv.DELETED
        with pytest.raises(kv.NotFoundError):
            store.get("secrets", "default", "fin")
        w.stop()
