"""Tests for api/: quantities, labels, resource accounting.

Mirrors the reference's table-driven unit style
(apimachinery/pkg/api/resource/quantity_test.go, labels/selector_test.go).
"""

import pytest

from kubernetes_tpu.api import labels as lbl
from kubernetes_tpu.api import resources as res
from kubernetes_tpu.api.quantity import (
    format_cpu_milli, format_mem_bytes, parse_cpu_milli, parse_mem_bytes, parse_quantity,
)


class TestQuantity:
    @pytest.mark.parametrize("s,expected", [
        ("100m", 0.1), ("1", 1.0), ("2.5", 2.5), ("1k", 1000.0),
        ("64Mi", 64 * 2**20), ("1Gi", 2**30), ("1G", 1e9),
        ("500n", 5e-7), ("12e3", 12000.0), ("1E2", 100.0),
        ("-5m", -0.005), (250, 250.0), (0.5, 0.5),
    ])
    def test_parse(self, s, expected):
        assert parse_quantity(s) == pytest.approx(expected)

    def test_cpu_milli(self):
        assert parse_cpu_milli("100m") == 100
        assert parse_cpu_milli("2") == 2000
        assert parse_cpu_milli("1.5") == 1500

    def test_mem_bytes(self):
        assert parse_mem_bytes("64Mi") == 64 * 2**20
        assert parse_mem_bytes("1000") == 1000

    def test_invalid(self):
        with pytest.raises(ValueError):
            parse_quantity("abc")
        with pytest.raises(ValueError):
            parse_quantity("1Zi")

    def test_format_roundtrip(self):
        assert format_cpu_milli(1500) == "1500m"
        assert format_cpu_milli(2000) == "2"
        assert format_mem_bytes(64 * 2**20) == "64Mi"
        assert format_mem_bytes(1001) == "1001"


class TestSelectors:
    def test_match_labels(self):
        s = lbl.selector_from_dict({"matchLabels": {"app": "web"}})
        assert s.matches({"app": "web", "tier": "fe"})
        assert not s.matches({"app": "db"})
        assert not s.matches({})

    def test_nil_selector_matches_nothing(self):
        assert not lbl.selector_from_dict(None).matches({"a": "b"})

    def test_empty_selector_matches_everything(self):
        assert lbl.selector_from_dict({}).matches({"a": "b"})
        assert lbl.selector_from_dict({}).matches({})

    @pytest.mark.parametrize("op,values,labels,want", [
        ("In", ["a", "b"], {"k": "a"}, True),
        ("In", ["a", "b"], {"k": "c"}, False),
        ("In", ["a"], {}, False),
        ("NotIn", ["a"], {"k": "b"}, True),
        ("NotIn", ["a"], {}, True),   # absent key matches NotIn
        ("NotIn", ["a"], {"k": "a"}, False),
        ("Exists", [], {"k": "x"}, True),
        ("Exists", [], {}, False),
        ("DoesNotExist", [], {}, True),
        ("DoesNotExist", [], {"k": "x"}, False),
        ("Gt", ["5"], {"k": "7"}, True),
        ("Gt", ["5"], {"k": "3"}, False),
        ("Lt", ["5"], {"k": "3"}, True),
        ("Gt", ["5"], {"k": "abc"}, False),
    ])
    def test_operators(self, op, values, labels, want):
        s = lbl.selector_from_dict(
            {"matchExpressions": [{"key": "k", "operator": op, "values": values}]})
        assert s.matches(labels) is want


def mkpod(containers=None, init=None, overhead=None):
    pod = {"metadata": {"name": "p", "namespace": "d"},
           "spec": {"containers": containers or []}}
    if init:
        pod["spec"]["initContainers"] = init
    if overhead:
        pod["spec"]["overhead"] = overhead
    return pod


def ctr(cpu=None, mem=None, **scalar):
    req = {}
    if cpu is not None:
        req["cpu"] = cpu
    if mem is not None:
        req["memory"] = mem
    req.update(scalar)
    return {"name": "c", "resources": {"requests": req}}


class TestPodRequest:
    def test_sum_containers(self):
        r = res.pod_request(mkpod([ctr("100m", "64Mi"), ctr("200m", "128Mi")]))
        assert r.milli_cpu == 300
        assert r.memory == 192 * 2**20

    def test_init_container_max(self):
        # max(init) vs sum(containers), per fit.go:160
        r = res.pod_request(mkpod([ctr("100m")], init=[ctr("500m")]))
        assert r.milli_cpu == 500
        r = res.pod_request(mkpod([ctr("100m"), ctr("200m")], init=[ctr("250m")]))
        assert r.milli_cpu == 300

    def test_overhead(self):
        r = res.pod_request(mkpod([ctr("100m")], overhead={"cpu": "50m"}))
        assert r.milli_cpu == 150

    def test_scalar_resources(self):
        r = res.pod_request(mkpod([ctr("1", **{"google.com/tpu": "4"})]))
        assert r.scalar["google.com/tpu"] == 4

    def test_nonzero_defaults(self):
        r = res.pod_request_nonzero(mkpod([ctr()]))
        assert r.milli_cpu == res.DEFAULT_MILLI_CPU_REQUEST
        assert r.memory == res.DEFAULT_MEMORY_REQUEST
