"""API server + HTTP client tests, ending in a full scheduler-over-HTTP
integration (informers watching via chunked streams)."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import AdmissionError, APIServer
from kubernetes_tpu.client import SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.client.http_client import HTTPClient
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture
def api():
    store = kv.MemoryStore()
    server = APIServer(store).start()
    client = HTTPClient("127.0.0.1", server.port)
    yield store, server, client
    server.stop()


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


class TestREST:
    def test_crud_roundtrip(self, api):
        store, server, client = api
        created = client.create(PODS, make_pod("p1").build())
        assert meta.uid(created)
        got = client.get(PODS, "default", "p1")
        assert meta.name(got) == "p1"
        got = meta.deep_copy(got)
        got["spec"]["nodeName"] = "nx"
        updated = client.update(PODS, got)
        assert updated["spec"]["nodeName"] == "nx"
        items, rv = client.list(PODS)
        assert len(items) == 1 and rv >= 2
        client.delete(PODS, "default", "p1")
        with pytest.raises(kv.NotFoundError):
            client.get(PODS, "default", "p1")

    def test_cluster_scoped_nodes(self, api):
        store, server, client = api
        client.create(NODES, make_node("n1").build())
        assert meta.name(client.get(NODES, "", "n1")) == "n1"
        items, _ = client.list(NODES)
        assert len(items) == 1

    def test_conflict_on_stale_update(self, api):
        store, server, client = api
        created = client.create(PODS, make_pod("p").build())
        stale = meta.deep_copy(created)
        fresh = meta.deep_copy(created)
        fresh["metadata"]["labels"] = {"v": "2"}
        client.update(PODS, fresh)
        stale["metadata"]["labels"] = {"v": "stale"}
        with pytest.raises(kv.ConflictError):
            client.update(PODS, stale)

    def test_duplicate_create(self, api):
        store, server, client = api
        client.create(PODS, make_pod("p").build())
        with pytest.raises(kv.AlreadyExistsError):
            client.create(PODS, make_pod("p").build())

    def test_watch_stream(self, api):
        store, server, client = api
        w = client.watch(PODS)
        time.sleep(0.1)
        client.create(PODS, make_pod("w1").build())
        deadline = time.time() + 5
        ev = None
        while ev is None and time.time() < deadline:
            ev = w.next(timeout=1.0)
        assert ev is not None and ev.type == kv.ADDED
        assert meta.name(ev.object) == "w1"
        w.stop()

    def test_watch_from_rv_replays(self, api):
        store, server, client = api
        client.create(PODS, make_pod("a").build())
        _, rv = client.list(PODS)
        client.create(PODS, make_pod("b").build())
        w = client.watch(PODS, since_rv=rv)
        ev = None
        deadline = time.time() + 5
        while ev is None and time.time() < deadline:
            ev = w.next(timeout=1.0)
        assert meta.name(ev.object) == "b"
        w.stop()

    def test_admission_hook(self, api):
        store, server, client = api

        def deny_bad(verb, resource, obj):
            if meta.name(obj).startswith("bad"):
                raise AdmissionError("name denied")
            obj.setdefault("metadata", {}).setdefault(
                "labels", {})["admitted"] = "yes"
            return obj

        server.admission_hooks.append(deny_bad)
        ok = client.create(PODS, make_pod("good").build())
        assert meta.labels(ok)["admitted"] == "yes"
        with pytest.raises(kv.StoreError):
            client.create(PODS, make_pod("bad").build())

    def test_healthz_and_version(self, api):
        store, server, client = api
        assert client._request("GET", "/healthz")["status"] == "ok"
        assert client._request("GET", "/version")["platform"] == "tpu"

    def test_auth_token(self):
        store = kv.MemoryStore()
        server = APIServer(store, token="s3cret").start()
        try:
            anon = HTTPClient("127.0.0.1", server.port)
            with pytest.raises(kv.StoreError):
                anon.list(PODS)
            authed = HTTPClient("127.0.0.1", server.port, token="s3cret")
            assert authed.list(PODS)[0] == []
        finally:
            server.stop()


class TestSchedulerOverHTTP:
    def test_full_pipeline(self, api):
        """informers -> reflector -> queue -> bind, all over real HTTP."""
        store, server, client = api
        factory = SharedInformerFactory(client)
        sched = new_scheduler(client, factory)
        factory.start()
        assert factory.wait_for_cache_sync()
        sched.run()
        try:
            client.create(NODES, make_node("n1").build())
            client.create(PODS, make_pod("p1").req(cpu="100m").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p1")) == "n1", timeout=15)
        finally:
            sched.stop()
            factory.stop()
