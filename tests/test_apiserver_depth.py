"""apiserver depth: patch verbs, subresources, admission, APF, audit, CRDs.

Reference contracts: endpoints/handlers/patch.go (3 patch content types),
registry/core/pod/storage (binding/eviction subresources), apiserver
admission chain order, util/flowcontrol APF, audit policy levels,
apiextensions-apiserver CRD serving.
"""

import json
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.apiserver import admission as adm
from kubernetes_tpu.apiserver import audit as auditlib
from kubernetes_tpu.apiserver import crd as crdlib
from kubernetes_tpu.apiserver import flowcontrol
from kubernetes_tpu.apiserver import patch as patchlib
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.client.http_client import HTTPClient, HTTPError
from kubernetes_tpu.store import kv


@pytest.fixture()
def server():
    store = kv.MemoryStore()
    srv = APIServer(store, enable_default_admission=True,
                    audit_logger=auditlib.AuditLogger()).start()
    yield srv, store, HTTPClient.from_url(srv.url)
    srv.stop()


def make_pod(name, ns="default", cpu="100m", labels=None, **spec):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": labels or {}},
            "spec": {"containers": [{"name": "c", "image": "img",
                                     "resources": {"requests": {
                                         "cpu": cpu, "memory": "64Mi"}}}],
                     **spec}}


# -- patch library ---------------------------------------------------------

class TestPatchLibrary:
    def test_json_merge_patch_rfc7386(self):
        target = {"a": 1, "b": {"c": 2, "d": 3}, "e": [1, 2]}
        patch = {"a": 9, "b": {"c": None}, "e": [5]}
        out = patchlib.json_merge_patch(target, patch)
        assert out == {"a": 9, "b": {"d": 3}, "e": [5]}

    def test_json_patch_rfc6902(self):
        doc = {"spec": {"replicas": 1, "list": [1, 2, 3]}}
        ops = [{"op": "replace", "path": "/spec/replicas", "value": 5},
               {"op": "add", "path": "/spec/list/-", "value": 4},
               {"op": "remove", "path": "/spec/list/0"},
               {"op": "test", "path": "/spec/replicas", "value": 5}]
        out = patchlib.json_patch(doc, ops)
        assert out == {"spec": {"replicas": 5, "list": [2, 3, 4]}}
        with pytest.raises(patchlib.PatchError):
            patchlib.json_patch(doc, [{"op": "test", "path": "/spec/replicas",
                                       "value": 99}])

    def test_json_patch_move_copy(self):
        doc = {"a": {"x": 1}, "b": {}}
        out = patchlib.json_patch(doc, [
            {"op": "copy", "from": "/a/x", "path": "/b/y"},
            {"op": "move", "from": "/a/x", "path": "/b/z"}])
        assert out == {"a": {}, "b": {"y": 1, "z": 1}}

    def test_strategic_merge_containers_by_name(self):
        target = {"spec": {"containers": [
            {"name": "app", "image": "v1", "env": [{"name": "A", "value": "1"}]},
            {"name": "sidecar", "image": "s1"}]}}
        patch = {"spec": {"containers": [
            {"name": "app", "image": "v2"}]}}
        out = patchlib.strategic_merge_patch(target, patch)
        containers = out["spec"]["containers"]
        assert len(containers) == 2
        app = next(c for c in containers if c["name"] == "app")
        assert app["image"] == "v2"
        assert app["env"] == [{"name": "A", "value": "1"}]  # merged, not lost

    def test_strategic_merge_delete_directive(self):
        target = {"spec": {"containers": [{"name": "a"}, {"name": "b"}]}}
        patch = {"spec": {"containers": [{"name": "a", "$patch": "delete"}]}}
        out = patchlib.strategic_merge_patch(target, patch)
        assert out["spec"]["containers"] == [{"name": "b"}]

    def test_strategic_merge_atomic_list_replaced(self):
        target = {"spec": {"nodeSelectorTerms": [1, 2]}}
        out = patchlib.strategic_merge_patch(
            target, {"spec": {"nodeSelectorTerms": [3]}})
        assert out["spec"]["nodeSelectorTerms"] == [3]


# -- PATCH over HTTP -------------------------------------------------------

class TestPatchVerb:
    def test_strategic_merge_patch_http(self, server):
        srv, store, client = server
        client.create("pods", make_pod("p1"))
        out = client.patch("pods", "default", "p1",
                           {"metadata": {"labels": {"x": "y"}}})
        assert out["metadata"]["labels"]["x"] == "y"
        assert out["spec"]["containers"]  # untouched

    def test_json_patch_http(self, server):
        srv, store, client = server
        client.create("pods", make_pod("p2"))
        out = client.patch("pods", "default", "p2",
                           [{"op": "add", "path": "/metadata/labels/app",
                             "value": "web"}],
                           patch_type="application/json-patch+json")
        assert out["metadata"]["labels"]["app"] == "web"

    def test_patch_bumps_resource_version(self, server):
        srv, store, client = server
        created = client.create("pods", make_pod("p3"))
        out = client.patch("pods", "default", "p3",
                           {"metadata": {"labels": {"a": "b"}}})
        assert int(out["metadata"]["resourceVersion"]) > int(
            created["metadata"]["resourceVersion"])

    def test_patch_missing_object_404(self, server):
        srv, store, client = server
        with pytest.raises(kv.NotFoundError):
            client.patch("pods", "default", "nope", {"metadata": {}})


# -- subresources ----------------------------------------------------------

class TestSubresources:
    def test_binding_subresource(self, server):
        srv, store, client = server
        client.create("pods", make_pod("bindme"))
        client.bind({"metadata": {"name": "bindme", "namespace": "default"}},
                    "node-1")
        pod = client.get("pods", "default", "bindme")
        assert pod["spec"]["nodeName"] == "node-1"

    def test_binding_conflict_on_double_bind(self, server):
        srv, store, client = server
        client.create("pods", make_pod("once"))
        client.bind({"metadata": {"name": "once", "namespace": "default"}}, "n1")
        with pytest.raises(kv.ConflictError):
            client.bind({"metadata": {"name": "once",
                                      "namespace": "default"}}, "n2")

    def test_status_subresource_only_touches_status(self, server):
        srv, store, client = server
        client.create("pods", make_pod("st"))
        client.update_status("pods", {
            "metadata": {"name": "st", "namespace": "default"},
            "spec": {"nodeName": "SHOULD-NOT-APPLY"},
            "status": {"phase": "Running"}})
        pod = client.get("pods", "default", "st")
        assert pod["status"]["phase"] == "Running"
        assert "nodeName" not in pod["spec"]

    def test_eviction_allowed_without_pdb(self, server):
        srv, store, client = server
        client.create("pods", make_pod("victim"))
        client.evict("default", "victim")
        with pytest.raises(kv.NotFoundError):
            client.get("pods", "default", "victim")

    def test_eviction_blocked_by_pdb_429(self, server):
        srv, store, client = server
        client.create("pods", make_pod("guarded", labels={"app": "db"}))
        client.create("poddisruptionbudgets", {
            "metadata": {"name": "pdb", "namespace": "default"},
            "spec": {"minAvailable": 1,
                     "selector": {"matchLabels": {"app": "db"}}}})
        with pytest.raises(HTTPError) as exc:
            client.evict("default", "guarded")
        assert exc.value.code == 429
        client.get("pods", "default", "guarded")  # still there

    def test_scale_subresource(self, server):
        srv, store, client = server
        client.create("deployments", {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"replicas": 2, "selector": {"matchLabels": {"a": "b"}},
                     "template": {"metadata": {"labels": {"a": "b"}}}}})
        scale = client.scale("deployments", "default", "web")
        assert scale["kind"] == "Scale" and scale["spec"]["replicas"] == 2
        client.scale("deployments", "default", "web", replicas=5)
        assert client.get("deployments", "default", "web")["spec"]["replicas"] == 5


# -- admission chain -------------------------------------------------------

class TestAdmission:
    def test_priority_admission_resolves_class(self, server):
        srv, store, client = server
        client.create("priorityclasses", {
            "metadata": {"name": "high"}, "value": 1000})
        pod = make_pod("prio")
        pod["spec"]["priorityClassName"] = "high"
        created = client.create("pods", pod)
        assert created["spec"]["priority"] == 1000

    def test_priority_admission_unknown_class_rejected(self, server):
        srv, store, client = server
        pod = make_pod("bad")
        pod["spec"]["priorityClassName"] = "missing"
        with pytest.raises(HTTPError) as exc:
            client.create("pods", pod)
        assert exc.value.code == 403

    def test_default_toleration_seconds(self, server):
        srv, store, client = server
        created = client.create("pods", make_pod("tol"))
        keys = {t["key"] for t in created["spec"]["tolerations"]}
        assert "node.kubernetes.io/not-ready" in keys
        assert "node.kubernetes.io/unreachable" in keys

    def test_namespace_lifecycle_rejects_missing_ns(self, server):
        srv, store, client = server
        with pytest.raises(HTTPError) as exc:
            client.create("pods", make_pod("p", ns="ghost"))
        assert exc.value.code == 403
        client.create("namespaces", {"metadata": {"name": "ghost"}})
        client.create("pods", make_pod("p", ns="ghost"))  # now fine

    def test_namespace_lifecycle_blocks_terminating(self, server):
        srv, store, client = server
        client.create("namespaces", {
            "metadata": {"name": "dying"},
            "status": {"phase": "Terminating"}})
        with pytest.raises(HTTPError):
            client.create("pods", make_pod("p", ns="dying"))

    def test_limit_ranger_defaults(self, server):
        srv, store, client = server
        client.create("limitranges", {
            "metadata": {"name": "lr", "namespace": "default"},
            "spec": {"limits": [{"type": "Container",
                                 "defaultRequest": {"cpu": "250m"},
                                 "default": {"memory": "256Mi"}}]}})
        pod = {"metadata": {"name": "lrpod", "namespace": "default"},
               "spec": {"containers": [{"name": "c", "image": "i"}]}}
        created = client.create("pods", pod)
        res = created["spec"]["containers"][0]["resources"]
        assert res["requests"]["cpu"] == "250m"
        assert res["limits"]["memory"] == "256Mi"

    def test_resource_quota_enforced(self, server):
        srv, store, client = server
        client.create("resourcequotas", {
            "metadata": {"name": "rq", "namespace": "default"},
            "spec": {"hard": {"pods": "2", "requests.cpu": "300m"}}})
        client.create("pods", make_pod("q1", cpu="100m"))
        client.create("pods", make_pod("q2", cpu="100m"))
        with pytest.raises(HTTPError) as exc:  # pod count 3 > 2
            client.create("pods", make_pod("q3", cpu="50m"))
        assert exc.value.code == 403

    def test_mutating_webhook_jsonpatch(self, server):
        import base64
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        srv, store, client = server

        class WH(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                json.loads(self.rfile.read(n))
                ops = [{"op": "add", "path": "/metadata/labels/injected",
                        "value": "true"}]
                body = json.dumps({"response": {
                    "allowed": True, "patchType": "JSONPatch",
                    "patch": base64.b64encode(
                        json.dumps(ops).encode()).decode()}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        whd = ThreadingHTTPServer(("127.0.0.1", 0), WH)
        threading.Thread(target=whd.serve_forever, daemon=True).start()
        try:
            wa = adm.WebhookAdmission()
            wa.register(adm.Webhook(
                "inject", "http://127.0.0.1:%d/" % whd.server_address[1],
                mutating=True,
                match=lambda attrs: attrs.resource == "pods"))
            srv.admission_chain.register(wa)
            created = client.create("pods", make_pod("hooked"))
            assert created["metadata"]["labels"]["injected"] == "true"
        finally:
            whd.shutdown()

    def test_validating_webhook_denies(self, server):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        srv, store, client = server

        class WH(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = json.dumps({"response": {
                    "allowed": False,
                    "status": {"message": "nope"}}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        whd = ThreadingHTTPServer(("127.0.0.1", 0), WH)
        threading.Thread(target=whd.serve_forever, daemon=True).start()
        try:
            wa = adm.WebhookAdmission()
            wa.register(adm.Webhook(
                "deny", "http://127.0.0.1:%d/" % whd.server_address[1],
                match=lambda attrs: attrs.resource == "pods"))
            srv.admission_chain.register(wa)
            with pytest.raises(HTTPError) as exc:
                client.create("pods", make_pod("denied"))
            assert "nope" in str(exc.value)
        finally:
            whd.shutdown()


# -- API priority & fairness ----------------------------------------------

class TestFlowControl:
    def test_classification(self):
        d = flowcontrol.Dispatcher()
        assert d.classify("u", "update", "leases").name == "leader-election"
        assert d.classify("system:scheduler", "get", "pods").name == "workload-high"
        assert d.classify("alice", "get", "pods").name == "global-default"

    def test_seats_block_and_release(self):
        lvl = flowcontrol.PriorityLevel("t", seats=1, queues=1, queue_length=4)
        assert lvl.acquire()
        done = threading.Event()

        def second():
            lvl.acquire(timeout=5.0)
            done.set()
            lvl.release()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not done.is_set()  # queued behind the held seat
        lvl.release()
        assert done.wait(2.0)

    def test_queue_timeout_rejects(self):
        lvl = flowcontrol.PriorityLevel("t", seats=1, queues=1, queue_length=4)
        lvl.acquire()
        with pytest.raises(flowcontrol.RejectedError):
            lvl.acquire(timeout=0.05)
        assert lvl.stats()["timed_out"] == 1

    def test_full_queue_rejects_immediately(self):
        lvl = flowcontrol.PriorityLevel("t", seats=1, queues=1, queue_length=0)
        lvl.acquire()
        with pytest.raises(flowcontrol.RejectedError):
            lvl.acquire(timeout=5.0)

    def test_exempt_never_blocks(self):
        lvl = flowcontrol.PriorityLevel("exempt", seats=0, exempt=True)
        for _ in range(100):
            assert lvl.acquire()

    def test_apf_wired_into_server(self):
        store = kv.MemoryStore()
        srv = APIServer(store,
                        flow_dispatcher=flowcontrol.Dispatcher()).start()
        try:
            client = HTTPClient.from_url(srv.url)
            client.create("pods", make_pod("apf"))
            metrics = urllib.request.urlopen(srv.url + "/metrics").read().decode()
            assert "apiserver_flowcontrol_dispatched" in metrics
        finally:
            srv.stop()


# -- audit -----------------------------------------------------------------

class TestAudit:
    def test_policy_levels(self):
        pol = auditlib.Policy(rules=[
            auditlib.PolicyRule(auditlib.LEVEL_NONE, resources=["events"]),
            auditlib.PolicyRule(auditlib.LEVEL_REQUEST, resources=["pods"]),
        ])
        assert pol.level_for("u", "create", "events") == "None"
        assert pol.level_for("u", "create", "pods") == "Request"
        assert pol.level_for("u", "get", "nodes") == "Metadata"

    def test_server_audits_writes(self, server):
        srv, store, client = server
        client.create("pods", make_pod("audited"))
        client.delete("pods", "default", "audited")
        events = srv.audit.snapshot()
        verbs = [(e["verb"], e["objectRef"]["resource"]) for e in events]
        assert ("create", "pods") in verbs
        assert ("delete", "pods") in verbs
        assert all(e["stage"] == "ResponseComplete" for e in events)

    def test_request_level_includes_object(self):
        log = auditlib.AuditLogger(policy=auditlib.Policy(
            default_level=auditlib.LEVEL_REQUEST))
        ev = log.log("ResponseComplete", "u", "create", "pods",
                     obj={"kind": "Pod"})
        assert ev["requestObject"] == {"kind": "Pod"}

    def test_none_level_drops(self):
        log = auditlib.AuditLogger(policy=auditlib.Policy(
            default_level=auditlib.LEVEL_NONE))
        assert log.log("ResponseComplete", "u", "get", "pods") is None
        assert log.snapshot() == []


# -- CRDs ------------------------------------------------------------------

def podgroup_crd():
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "podgroups.scheduling.x-k8s.io"},
        "spec": {
            "group": "scheduling.x-k8s.io",
            "scope": "Namespaced",
            "names": {"plural": "podgroups", "kind": "PodGroup",
                      "shortNames": ["pg"]},
            "versions": [{
                "name": "v1alpha1", "served": True, "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": {"type": "object",
                                 "required": ["minMember"],
                                 "properties": {
                                     "minMember": {"type": "integer",
                                                   "minimum": 1}}}}}}}]}}


class TestCRDs:
    def test_crd_establish_and_serve(self, server):
        srv, store, client = server
        client.create("customresourcedefinitions", podgroup_crd())
        # serve the custom resource under its group path
        body = json.dumps({
            "apiVersion": "scheduling.x-k8s.io/v1alpha1", "kind": "PodGroup",
            "metadata": {"name": "gang-a", "namespace": "default"},
            "spec": {"minMember": 3}}).encode()
        req = urllib.request.Request(
            srv.url + "/apis/scheduling.x-k8s.io/v1alpha1/namespaces/default/podgroups",
            data=body, headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 201
        got = json.loads(urllib.request.urlopen(
            srv.url + "/apis/scheduling.x-k8s.io/v1alpha1/namespaces/default/"
            "podgroups/gang-a").read())
        assert got["spec"]["minMember"] == 3

    def test_crd_schema_validation_422(self, server):
        srv, store, client = server
        client.create("customresourcedefinitions", podgroup_crd())
        bad = json.dumps({
            "apiVersion": "scheduling.x-k8s.io/v1alpha1", "kind": "PodGroup",
            "metadata": {"name": "bad", "namespace": "default"},
            "spec": {"minMember": 0}}).encode()  # below minimum
        req = urllib.request.Request(
            srv.url + "/apis/scheduling.x-k8s.io/v1alpha1/namespaces/default/podgroups",
            data=bad, headers={"Content-Type": "application/json"},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 422

    def test_crd_missing_required_field(self):
        reg = crdlib.CRDRegistry()
        reg.establish(podgroup_crd())
        with pytest.raises(crdlib.ValidationError):
            reg.validate_object("podgroups", "v1alpha1",
                                {"spec": {}})  # minMember required

    def test_crd_survives_restart(self):
        store = kv.MemoryStore()
        srv = APIServer(store).start()
        c = HTTPClient.from_url(srv.url)
        c.create("customresourcedefinitions", podgroup_crd())
        srv.stop()
        # new server over the same store re-establishes from persisted CRDs
        srv2 = APIServer(store).start()
        try:
            assert srv2.crds.lookup("podgroups") is not None
            assert srv2.crds.lookup("pg") is not None  # short name
        finally:
            srv2.stop()

    def test_watch_custom_resource(self, server):
        srv, store, client = server
        client.create("customresourcedefinitions", podgroup_crd())
        w = store.watch("podgroups")
        store.create("podgroups", {
            "metadata": {"name": "g", "namespace": "default"},
            "spec": {"minMember": 2}})
        ev = w.next(timeout=2.0)
        assert ev is not None and ev.type == kv.ADDED
        w.stop()


# -- label selector on list ------------------------------------------------

def test_list_label_selector(server):
    srv, store, client = server
    client.create("pods", make_pod("l1", labels={"app": "a"}))
    client.create("pods", make_pod("l2", labels={"app": "b"}))
    data = json.loads(urllib.request.urlopen(
        srv.url + "/api/v1/namespaces/default/pods?labelSelector=app%3Da"
    ).read())
    names = [i["metadata"]["name"] for i in data["items"]]
    assert names == ["l1"]


# -- review regressions ----------------------------------------------------

class TestReviewRegressions:
    def test_patch_respects_admission(self, server):
        """PATCH runs the same admission gates as PUT."""
        srv, store, client = server
        client.create("pods", make_pod("padm"))

        class Deny(adm.AdmissionPlugin):
            name = "DenyLabel"

            def validate(self, attrs):
                labels = ((attrs.obj or {}).get("metadata") or {}).get(
                    "labels") or {}
                if labels.get("forbidden") == "yes":
                    raise adm.AdmissionDenied(self.name, "forbidden label")

        srv.admission_chain.register(Deny())
        with pytest.raises(HTTPError) as exc:
            client.patch("pods", "default", "padm",
                         {"metadata": {"labels": {"forbidden": "yes"}}})
        assert exc.value.code == 403
        pod = client.get("pods", "default", "padm")
        assert (pod["metadata"].get("labels") or {}).get("forbidden") != "yes"

    def test_patch_validates_crd_schema(self, server):
        srv, store, client = server
        client.create("customresourcedefinitions", podgroup_crd())
        body = json.dumps({
            "apiVersion": "scheduling.x-k8s.io/v1alpha1", "kind": "PodGroup",
            "metadata": {"name": "g1", "namespace": "default"},
            "spec": {"minMember": 3}}).encode()
        base = (srv.url + "/apis/scheduling.x-k8s.io/v1alpha1/namespaces/"
                "default/podgroups")
        urllib.request.urlopen(urllib.request.Request(
            base, data=body, headers={"Content-Type": "application/json"},
            method="POST"))
        bad_patch = json.dumps({"spec": {"minMember": 0}}).encode()
        req = urllib.request.Request(
            base + "/g1", data=bad_patch,
            headers={"Content-Type": "application/merge-patch+json"},
            method="PATCH")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 422

    def test_unknown_subresource_404(self, server):
        srv, store, client = server
        client.create("pods", make_pod("sub"))
        req = urllib.request.Request(
            srv.url + "/api/v1/namespaces/default/pods/sub/bogus")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 404
        # exec IS a subresource now (kubelet tunnel) — an unscheduled
        # pod gets a 400, not a 404 route miss
        req = urllib.request.Request(
            srv.url + "/api/v1/namespaces/default/pods/sub/exec")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 400
        # DELETE on a bogus subresource must NOT delete the parent
        req = urllib.request.Request(
            srv.url + "/api/v1/namespaces/default/pods/sub/anything",
            method="DELETE")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
        client.get("pods", "default", "sub")  # still exists

    def test_watch_exempt_from_apf(self):
        """A held watch stream must not consume APF seats."""
        levels = (("catch-all", 1, 1, 4, False),)
        schemas = [flowcontrol.FlowSchema("all", "catch-all", 1)]
        store = kv.MemoryStore()
        srv = APIServer(store, flow_dispatcher=flowcontrol.Dispatcher(
            levels=levels, schemas=schemas)).start()
        try:
            client = HTTPClient.from_url(srv.url)
            w = client.watch("pods")  # long-running stream held open
            time.sleep(0.1)
            client.create("pods", make_pod("apf-free"))  # must not 429
            ev = w.next(timeout=3.0)
            assert ev is not None
            w.stop()
        finally:
            srv.stop()

    def test_pdb_max_unavailable_blocks(self, server):
        srv, store, client = server
        # RS with 3 desired, only 2 healthy pods -> 1 disruption used
        rs = {"metadata": {"name": "rs1", "namespace": "default"},
              "spec": {"replicas": 3,
                       "selector": {"matchLabels": {"app": "w"}}}}
        created_rs = client.create("replicasets", rs)
        for i in range(2):
            p = make_pod("w%d" % i, labels={"app": "w"})
            p["metadata"]["ownerReferences"] = [{
                "kind": "ReplicaSet", "name": "rs1",
                "uid": created_rs["metadata"].get("uid", ""),
                "controller": True}]
            client.create("pods", p)
        client.create("poddisruptionbudgets", {
            "metadata": {"name": "pdb-mu", "namespace": "default"},
            "spec": {"maxUnavailable": 1,
                     "selector": {"matchLabels": {"app": "w"}}}})
        with pytest.raises(HTTPError) as exc:  # 1 already down, budget spent
            client.evict("default", "w0")
        assert exc.value.code == 429

    def test_pdb_percentage_min_available(self, server):
        srv, store, client = server
        for i in range(4):
            client.create("pods", make_pod("pc%d" % i, labels={"app": "pc"}))
        client.create("poddisruptionbudgets", {
            "metadata": {"name": "pdb-pct", "namespace": "default"},
            "spec": {"minAvailable": "75%",
                     "selector": {"matchLabels": {"app": "pc"}}}})
        client.evict("default", "pc0")  # 3 of 4 left = 75%, allowed
        with pytest.raises(HTTPError) as exc:  # 2 of 4 < 75%
            client.evict("default", "pc1")
        assert exc.value.code == 429

    def test_pdb_status_disruptions_allowed_consumed(self, server):
        srv, store, client = server
        client.create("pods", make_pod("da0", labels={"app": "da"}))
        client.create("pods", make_pod("da1", labels={"app": "da"}))
        client.create("poddisruptionbudgets", {
            "metadata": {"name": "pdb-da", "namespace": "default"},
            "spec": {"selector": {"matchLabels": {"app": "da"}}},
            "status": {"disruptionsAllowed": 1}})
        client.evict("default", "da0")  # consumes the single disruption
        with pytest.raises(HTTPError) as exc:
            client.evict("default", "da1")
        assert exc.value.code == 429

    def test_quota_concurrent_creates_cannot_exceed(self, server):
        srv, store, client = server
        client.create("resourcequotas", {
            "metadata": {"name": "rq1", "namespace": "default"},
            "spec": {"hard": {"pods": "1"}}})
        results = []

        def create(i):
            c = HTTPClient.from_url(srv.url)
            try:
                c.create("pods", make_pod("race%d" % i))
                results.append("ok")
            except Exception:
                results.append("denied")

        threads = [threading.Thread(target=create, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        pods, _ = store.list("pods", "default")
        assert len(pods) <= 1
        assert results.count("ok") <= 1
