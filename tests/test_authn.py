"""X.509 client-cert + ServiceAccount token authentication.

Reference behaviors: staging/src/k8s.io/apiserver/pkg/authentication/
request/x509/x509.go (CN=user, O=groups against --client-ca-file),
pkg/serviceaccount/jwt.go + the TokenRequest subresource
(pkg/registry/core/serviceaccount/storage/token.go).  The apiserver
serves real TLS here; every request in these tests crosses the wire.
"""

import json
import time

import pytest

pytest.importorskip("cryptography",
                    reason="ClusterCA/TLS need the cryptography package")

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver import authn as authnlib
from kubernetes_tpu.client.http_client import HTTPClient, HTTPError
from kubernetes_tpu.controllers.certificates import ClusterCA
from kubernetes_tpu.store import kv


@pytest.fixture(scope="module")
def tls_cluster(tmp_path_factory):
    """TLS apiserver (client-CA authn + RBAC + SA tokens) + cert files."""
    d = tmp_path_factory.mktemp("pki")
    ca = ClusterCA()
    tls = authnlib.write_serving_bundle(ca, str(d))
    store = kv.MemoryStore()
    server = APIServer(store, tls=tls, enable_rbac=True,
                       enable_service_accounts=True).start()

    def client_for(cn, orgs=(), tls_extra=None):
        cert_pem, key_pem = authnlib.issue_cert(ca, cn, tuple(orgs))
        cert_f = d / f"{cn.replace(':', '_').replace('/', '_')}.crt"
        key_f = d / f"{cn.replace(':', '_').replace('/', '_')}.key"
        cert_f.write_text(cert_pem)
        key_f.write_text(key_pem)
        return HTTPClient(server.httpd.server_address[0], server.port,
                          tls={"ca_file": tls["client_ca_file"],
                               "cert_file": str(cert_f),
                               "key_file": str(key_f),
                               **(tls_extra or {})})

    yield server, store, ca, tls, client_for, d
    server.stop()


def anon_client(server, tls):
    return HTTPClient(server.httpd.server_address[0], server.port,
                      tls={"ca_file": tls["client_ca_file"]})


class TestX509:
    def test_admin_cert_is_superuser(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        pod = meta.new_object("Pod", "by-cert", "default")
        pod["spec"] = {"containers": [{"name": "c", "image": "i"}]}
        created = admin.create("pods", pod)
        assert meta.name(created) == "by-cert"
        assert admin.get("pods", "default", "by-cert")

    def test_no_cert_is_anonymous(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        anon = anon_client(server, tls)
        pod = meta.new_object("Pod", "anon-pod", "default")
        with pytest.raises(HTTPError) as exc:
            anon.create("pods", pod)
        assert exc.value.code == 403
        assert "system:anonymous" in str(exc.value)

    def test_wrong_ca_cert_rejected(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        rogue_ca = ClusterCA()
        cert_pem, key_pem = authnlib.issue_cert(
            rogue_ca, "kubernetes-admin", ("system:masters",))
        (d / "rogue.crt").write_text(cert_pem)
        (d / "rogue.key").write_text(key_pem)
        rogue = HTTPClient(server.httpd.server_address[0], server.port,
                           tls={"ca_file": tls["client_ca_file"],
                                "cert_file": str(d / "rogue.crt"),
                                "key_file": str(d / "rogue.key")})
        with pytest.raises(OSError):  # TLS alert: unknown CA
            rogue.list("pods", "default")

    def test_node_cert_is_rbac_scoped(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        node = client_for("system:node:n1", ["system:nodes"])
        node.list("pods", "default")  # read allowed by system:node
        pod = meta.new_object("Pod", "node-made", "default")
        with pytest.raises(HTTPError) as exc:
            node.create("pods", pod)  # pod create is not in the role
        assert exc.value.code == 403
        assert "system:node:n1" in str(exc.value)


class TestServiceAccountTokens:
    def _mint(self, admin, ns, name, seconds=3600):
        sa = meta.new_object("ServiceAccount", name, ns)
        try:
            admin.create("serviceaccounts", sa)
        except kv.AlreadyExistsError:
            pass
        return admin._request(
            "POST", f"/api/v1/namespaces/{ns}/serviceaccounts/{name}/token",
            {"spec": {"expirationSeconds": seconds}})

    def test_token_request_and_authn(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        tr = self._mint(admin, "default", "app-sa")
        token = tr["status"]["token"]
        assert tr["kind"] == "TokenRequest"
        assert token.count(".") == 2
        sa_client = HTTPClient(server.httpd.server_address[0],
                               server.port, token=token,
                               tls={"ca_file": tls["client_ca_file"]})
        # authenticated (basic-user) but unprivileged
        with pytest.raises(HTTPError) as exc:
            sa_client.create("pods", meta.new_object("Pod", "x", "default"))
        assert exc.value.code == 403
        assert "system:serviceaccount:default:app-sa" in str(exc.value)

    def test_deleted_sa_invalidates_token(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        tr = self._mint(admin, "default", "doomed-sa")
        token = tr["status"]["token"]
        sa_client = HTTPClient(server.httpd.server_address[0],
                               server.port, token=token,
                               tls={"ca_file": tls["client_ca_file"]})
        with pytest.raises(HTTPError) as exc:
            sa_client.create("pods", meta.new_object("Pod", "y", "default"))
        assert "doomed-sa" in str(exc.value)  # live token worked
        admin.delete("serviceaccounts", "default", "doomed-sa")
        with pytest.raises(HTTPError) as exc:
            sa_client.list("pods", "default")
        assert exc.value.code == 401  # jwt.go: deleted account -> invalid

    def test_short_expiration_rejected(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        sa = meta.new_object("ServiceAccount", "short-sa", "default")
        admin.create("serviceaccounts", sa)
        with pytest.raises(HTTPError) as exc:
            admin._request(
                "POST",
                "/api/v1/namespaces/default/serviceaccounts/"
                "short-sa/token",
                {"spec": {"expirationSeconds": 60}})
        assert exc.value.code == 400
        assert ">= 600" in str(exc.value)

    def test_external_audience_token_rejected_by_apiserver(
            self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        sa = meta.new_object("ServiceAccount", "aud-sa", "default")
        admin.create("serviceaccounts", sa)
        tr = admin._request(
            "POST",
            "/api/v1/namespaces/default/serviceaccounts/aud-sa/token",
            {"spec": {"audiences": ["vault"]}})
        ext_client = HTTPClient(server.httpd.server_address[0],
                                server.port, token=tr["status"]["token"],
                                tls={"ca_file": tls["client_ca_file"]})
        with pytest.raises(HTTPError) as exc:
            ext_client.list("pods", "default")
        assert exc.value.code == 401

    def test_token_for_missing_sa_404(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        with pytest.raises(kv.NotFoundError):
            admin._request(
                "POST",
                "/api/v1/namespaces/default/serviceaccounts/ghost/token",
                {"spec": {}})

    def test_token_subresource_verbs(self, tls_cluster):
        server, store, ca, tls, client_for, d = tls_cluster
        admin = client_for("kubernetes-admin", ["system:masters"])
        self._mint(admin, "default", "verb-sa")
        for method in ("GET", "PUT", "DELETE"):
            with pytest.raises(HTTPError) as exc:
                admin._request(
                    method,
                    "/api/v1/namespaces/default/serviceaccounts/"
                    "verb-sa/token",
                    {} if method == "PUT" else None)
            assert exc.value.code == 405, method
        # the parent SA survived the rejected verbs
        admin.get("serviceaccounts", "default", "verb-sa")


class TestJWTValidation:
    def test_tamper_and_expiry(self):
        store = kv.MemoryStore()
        sa = meta.new_object("ServiceAccount", "s", "ns1")
        store.create("serviceaccounts", sa)
        issuer = authnlib.ServiceAccountIssuer(store)
        token, _ = issuer.issue("ns1", "s")
        assert issuer.verify(token) is not None
        # tampered payload
        h, p, s_ = token.split(".")
        forged = json.loads(authnlib._unb64url(p))
        forged["sub"] = "system:serviceaccount:kube-system:root"
        assert issuer.verify(
            f"{h}.{authnlib._b64url(json.dumps(forged).encode())}.{s_}"
        ) is None
        # expired (aud valid, so expiry is what rejects it)
        expired_claims = {"iss": authnlib.SA_ISSUER,
                          "sub": "system:serviceaccount:ns1:s",
                          "aud": [authnlib.API_AUDIENCE],
                          "exp": int(time.time()) - 10}
        payload = authnlib._b64url(json.dumps(expired_claims).encode())
        header = h
        sig = issuer._sign(f"{header}.{payload}".encode())
        assert issuer.verify(f"{header}.{payload}.{sig}") is None
        # audience-bound to an external service: not valid here
        ext, _ = issuer.issue("ns1", "s", audiences=("vault",))
        assert issuer.verify(ext) is None
        # restart with the same store: key persists, token still valid
        issuer2 = authnlib.ServiceAccountIssuer(store)
        assert issuer2.verify(token) is not None

    def test_x509_identity_parse(self):
        cert = {"subject": ((("commonName", "jane"),),
                            (("organizationName", "dev"),),
                            (("organizationName", "ops"),))}
        assert authnlib.x509_identity(cert) == ("jane", ("dev", "ops"))
        assert authnlib.x509_identity({}) is None
        assert authnlib.x509_identity(None) is None
        assert authnlib.x509_identity(
            {"subject": ((("organizationName", "dev"),),)}) is None


class TestKubeconfigClient:
    def test_cert_kubeconfig_round_trip(self, tls_cluster, tmp_path):
        server, store, ca, tls, client_for, d = tls_cluster
        from kubernetes_tpu.cmd.kubeadm import (_kubeconfig,
                                                _write_kubeconfig)
        cert_pem, key_pem = authnlib.issue_cert(
            ca, "kubernetes-admin", ("system:masters",))
        path = _write_kubeconfig(
            str(tmp_path), "admin.conf",
            _kubeconfig(server.url, ca.ca_pem(), "kubernetes-admin",
                        cert_pem=cert_pem, key_pem=key_pem))
        client = HTTPClient.from_kubeconfig(path)
        pod = meta.new_object("Pod", "via-kubeconfig", "default")
        pod["spec"] = {"containers": [{"name": "c", "image": "i"}]}
        client.create("pods", pod)
        assert client.get("pods", "default", "via-kubeconfig")

    def test_kubectl_kubeconfig_flag(self, tls_cluster, tmp_path):
        server, store, ca, tls, client_for, d = tls_cluster
        import io

        from kubernetes_tpu.cli.kubectl import run
        from kubernetes_tpu.cmd.kubeadm import (_kubeconfig,
                                                _write_kubeconfig)
        cert_pem, key_pem = authnlib.issue_cert(
            ca, "kubernetes-admin", ("system:masters",))
        path = _write_kubeconfig(
            str(tmp_path), "admin.conf",
            _kubeconfig(server.url, ca.ca_pem(), "kubernetes-admin",
                        cert_pem=cert_pem, key_pem=key_pem))
        out = io.StringIO()
        rc = run(["--kubeconfig", path, "get", "pods"], out=out)
        assert rc == 0
