"""Batched (TPU-path) preemption: device candidate search + host dry-run.

Reference semantics: framework/preemption/preemption.go DryRunPreemption
(:579) / SelectCandidate (:307), run for FitError pods coming out of a
DEVICE batch instead of the per-pod loop (VERDICT r1 item 7).

Runs on CPU with 8 virtual devices (tests/conftest.py).
"""

import time

import pytest

from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.api import meta
from kubernetes_tpu.ops.backend import TPUBatchBackend
from kubernetes_tpu.ops.flatten import Caps
from kubernetes_tpu.scheduler import (
    Profile, Scheduler, new_default_framework,
)
from kubernetes_tpu.scheduler.cache import Cache, Snapshot
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def small_caps():
    return Caps(n_cap=16, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8)


def snapshot_from(nodes, bound_pods=()):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for p in bound_pods:
        cache.add_pod(p)
    return cache.update_snapshot(Snapshot())


def bound(name, node, cpu="800m", prio=1):
    return (make_pod(name).priority(prio).req(cpu=cpu)
            .node(node).build())


class TestPreemptCandidates:
    """Unit: the device masked-refilter candidate search."""

    def make_backend(self, nodes, bound_pods):
        snap = snapshot_from(nodes, bound_pods)
        backend = TPUBatchBackend(small_caps(), batch_size=4)
        backend.assign([], snap)  # sync tensors to the cluster state
        return backend

    def test_candidates_only_where_victims_free_enough(self):
        nodes = [make_node(f"n{i}").capacity(cpu="1", mem="2Gi").build()
                 for i in range(3)]
        # n0: low-prio victim; n1: HIGH-prio occupant (not a victim);
        # n2: low-prio victim
        backend = self.make_backend(nodes, [
            bound("v0", "n0", prio=1), bound("h1", "n1", prio=100),
            bound("v2", "n2", prio=1)])
        preemptor = PodInfo(make_pod("p").priority(50).req(cpu="800m").build())
        (names,) = backend.preempt_candidates([preemptor])
        assert set(names) == {"n0", "n2"}

    def test_no_candidates_without_victims(self):
        nodes = [make_node("n0").capacity(cpu="1", mem="2Gi").build()]
        backend = self.make_backend(nodes, [bound("big", "n0", prio=100)])
        preemptor = PodInfo(make_pod("p").priority(50).req(cpu="800m").build())
        (names,) = backend.preempt_candidates([preemptor])
        assert names == []

    def test_priority_groups_see_different_victim_sets(self):
        nodes = [make_node("n0").capacity(cpu="1", mem="2Gi").build()]
        backend = self.make_backend(nodes, [bound("mid", "n0", prio=50)])
        lo = PodInfo(make_pod("lo").priority(10).req(cpu="800m").build())
        hi = PodInfo(make_pod("hi").priority(90).req(cpu="800m").build())
        lo_names, hi_names = backend.preempt_candidates([lo, hi])
        assert lo_names == []          # prio 10 cannot evict prio 50
        assert hi_names == ["n0"]      # prio 90 can

    def test_fewest_victims_ranked_first(self):
        nodes = [make_node(f"n{i}").capacity(cpu="1", mem="2Gi").build()
                 for i in range(2)]
        backend = self.make_backend(nodes, [
            bound("a0", "n0", cpu="400m"), bound("a1", "n0", cpu="400m"),
            bound("b0", "n1", cpu="800m")])
        preemptor = PodInfo(make_pod("p").priority(50).req(cpu="700m").build())
        (names,) = backend.preempt_candidates([preemptor])
        assert names[0] == "n1"  # one victim beats two


@pytest.fixture
def tpu_cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory)
    backend = TPUBatchBackend(small_caps(), batch_size=8)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(
        fw, batch_backend=backend, batch_size=8)})
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    yield store, client, sched
    sched.stop()
    factory.stop()


def node_of(client, name):
    try:
        return meta.pod_node_name(client.get(PODS, "default", name)) or None
    except kv.NotFoundError:
        return None


class TestBatchPathPreemption:
    """E2E: FitError pods from the device batch preempt victims."""

    def test_high_priority_preempts_through_batch_path(self, tpu_cluster):
        store, client, sched = tpu_cluster
        client.create(NODES,
                      make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS,
                      make_pod("low").priority(1).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "low") == "n1")
        client.create(PODS,
                      make_pod("high").priority(100).req(cpu="800m").build())
        # victim evicted, preemptor eventually lands on the freed node
        assert wait_for(lambda: node_of(client, "low") is None)
        assert wait_for(lambda: node_of(client, "high") == "n1")

    def test_equal_priority_is_not_preempted(self, tpu_cluster):
        store, client, sched = tpu_cluster
        client.create(NODES,
                      make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS,
                      make_pod("first").priority(5).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "first") == "n1")
        client.create(PODS,
                      make_pod("second").priority(5).req(cpu="800m").build())
        time.sleep(1.0)
        assert node_of(client, "first") == "n1"
        assert node_of(client, "second") is None

    def test_minimal_victim_set_through_batch_path(self, tpu_cluster):
        store, client, sched = tpu_cluster
        client.create(NODES,
                      make_node("n1").capacity(cpu="2", mem="4Gi").build())
        client.create(NODES,
                      make_node("n2").capacity(cpu="2", mem="4Gi").build())
        client.create(PODS, make_pod("v1a").priority(1).req(cpu="900m").build())
        client.create(PODS, make_pod("v1b").priority(1).req(cpu="900m").build())
        assert wait_for(lambda: node_of(client, "v1a") and
                        node_of(client, "v1b"))
        # ensure a known layout by filling whichever node got both/neither
        layout = {node_of(client, "v1a"), node_of(client, "v1b")}
        if layout == {"n1", "n2"}:
            # one victim per node: preemptor needs only one victim either
            # way; just verify a single eviction happens
            client.create(PODS,
                          make_pod("hi").priority(9).req(cpu="1500m").build())
            assert wait_for(lambda: node_of(client, "hi") is not None)
            survivors = [n for n in ("v1a", "v1b")
                         if node_of(client, n) is not None]
            assert len(survivors) == 1
        else:
            client.create(PODS,
                          make_pod("hi").priority(9).req(cpu="1500m").build())
            assert wait_for(lambda: node_of(client, "hi") is not None)

    def test_preemption_metrics_recorded(self, tpu_cluster):
        store, client, sched = tpu_cluster
        client.create(NODES,
                      make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS,
                      make_pod("low").priority(1).req(cpu="900m").build())
        assert wait_for(lambda: node_of(client, "low") == "n1")
        client.create(PODS,
                      make_pod("high").priority(50).req(cpu="900m").build())
        assert wait_for(lambda: node_of(client, "high") == "n1")
        assert sched.metrics.preemption_attempts >= 1
