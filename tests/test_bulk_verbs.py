"""Front-door bulk verbs: BindingList POST, bulk create (List POST),
and HTTPWatch burst batching — the server-side halves of the scheduler's
batched write path (store.bind_many / store.create_many were already
transactional; these tests pin the HTTP surfaces over them).

Reference anchors: pkg/registry/core/pod/storage (BindingREST
semantics per entry), scheduler_perf util.go:92 (the reference harness
drives the real REST surface).
"""

import threading

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client.clientset import PODS
from kubernetes_tpu.client.http_client import HTTPClient
from kubernetes_tpu.store import kv


@pytest.fixture()
def server():
    store = kv.MemoryStore(history=10_000)
    srv = APIServer(store).start()
    http = HTTPClient.from_url(srv.url)
    yield http, store
    srv.stop()


def mkpod(name, ns="default"):
    pod = meta.new_object("Pod", name, ns)
    pod["spec"] = {"containers": [{"name": "c", "image": "i"}]}
    return pod


class TestBulkBind:
    def test_bind_many_one_request(self, server):
        http, store = server
        for i in range(5):
            http.create(PODS, mkpod(f"bb-{i}"))
        results = http.bind_many([("default", f"bb-{i}", f"node-{i % 2}")
                                  for i in range(5)])
        assert len(results) == 5
        assert all(err is None for _, err in results)
        for i in range(5):
            pod = store.get(PODS, "default", f"bb-{i}")
            assert pod["spec"]["nodeName"] == f"node-{i % 2}"
            assert any(c["type"] == "PodScheduled"
                       for c in pod["status"]["conditions"])

    def test_per_entry_failures_dont_poison(self, server):
        http, store = server
        http.create(PODS, mkpod("bf-ok"))
        http.create(PODS, mkpod("bf-bound"))
        http.bind_many([("default", "bf-bound", "n0")])
        results = http.bind_many([
            ("default", "bf-ok", "n1"),
            ("default", "bf-bound", "n1"),   # already bound -> Conflict
            ("default", "bf-missing", "n1"),  # -> NotFound
        ])
        assert results[0][1] is None
        assert isinstance(results[1][1], kv.ConflictError)
        assert isinstance(results[2][1], kv.NotFoundError)
        assert store.get(PODS, "default", "bf-ok")["spec"][
            "nodeName"] == "n1"
        assert store.get(PODS, "default", "bf-bound")["spec"][
            "nodeName"] == "n0"

    def test_bind_conflict_fields_survive_the_wire(self, server):
        """The structured BindConflict fields (key/current_node/
        wanted_node) ride the 409 Status `details` block both ways, so
        an HTTP scheduler classifies already_bound_same_node vs
        lost_to_peer exactly like a LocalClient one — no message
        parsing."""
        http, store = server
        http.create(PODS, mkpod("bc-pod"))
        http.bind_many([("default", "bc-pod", "n0")])
        # bulk path
        [(_, err)] = http.bind_many([("default", "bc-pod", "n1")])
        assert isinstance(err, kv.BindConflict)
        assert err.current_node == "n0" and err.wanted_node == "n1"
        # single-binding subresource path
        with pytest.raises(kv.BindConflict) as ei:
            http.bind({"metadata": {"namespace": "default",
                                    "name": "bc-pod"}}, "n2")
        assert ei.value.current_node == "n0"
        assert ei.value.wanted_node == "n2"
        assert ei.value.key  # names the pod
        # a conflict naming OUR node is the already-bound-same-node
        # success tail, distinguishable without parsing
        [(_, err)] = http.bind_many([("default", "bc-pod", "n0")])
        assert isinstance(err, kv.BindConflict)
        assert err.current_node == "n0" == err.wanted_node

    def test_single_binding_collection_post(self, server):
        """Upstream shape: POST one Binding to the collection."""
        http, store = server
        http.create(PODS, mkpod("bs-one"))
        http._request("POST", "/api/v1/namespaces/default/bindings", {
            "kind": "Binding", "apiVersion": "v1",
            "metadata": {"name": "bs-one"},
            "target": {"kind": "Node", "name": "n7"}})
        assert store.get(PODS, "default", "bs-one")["spec"][
            "nodeName"] == "n7"

    def test_cross_namespace_batch(self, server):
        http, store = server
        ns2 = meta.new_object("Namespace", "other", "")
        http.create("namespaces", ns2)
        http.create(PODS, mkpod("cn-a"))
        http.create(PODS, mkpod("cn-b", ns="other"))
        results = http.bind_many([("default", "cn-a", "nA"),
                                  ("other", "cn-b", "nB")])
        assert all(err is None for _, err in results)
        assert store.get(PODS, "other", "cn-b")["spec"]["nodeName"] == "nB"


class TestBulkCreate:
    def test_events_one_request(self, server):
        http, store = server
        events = []
        for i in range(50):
            ev = meta.new_object("Event", f"ev-{i}", "default")
            ev["reason"] = "Scheduled"
            events.append(ev)
        http.create_bulk("events", events)
        items, _ = store.list("events", "default")
        assert len(items) == 50

    def test_malformed_items_get_per_item_statuses(self, server):
        """Items with null/absent metadata.name must produce per-item
        400s, not abort the whole request."""
        http, store = server
        resp = http._request(
            "POST", "/api/v1/namespaces/default/configmaps",
            {"kind": "List", "apiVersion": "v1", "items": [
                {"metadata": None},
                "not-a-dict",
                {"metadata": {"name": "good-one"}},
                {"metadata": {}}]})
        st = resp["items"]
        assert st[0]["code"] == 400
        assert st[1]["code"] == 400
        assert st[2]["status"] == "Success"
        assert st[3]["code"] == 400
        assert store.get("configmaps", "default", "good-one")

    def test_client_raises_on_item_failure(self, server):
        http, store = server
        cm = meta.new_object("ConfigMap", "taken", "default")
        http.create("configmaps", cm)
        with pytest.raises(kv.AlreadyExistsError):
            http.create_bulk("configmaps", [
                meta.new_object("ConfigMap", "taken", "default")])

    def test_bulk_custom_objects_get_crd_pipeline(self, server):
        """Bulk-POSTed custom objects run the same prune/default/
        validate pipeline as singular creates."""
        http, store = server
        schema = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {
                "size": {"type": "integer", "default": 3}}}}}
        crd = {"apiVersion": "apiextensions.k8s.io/v1",
               "kind": "CustomResourceDefinition",
               "metadata": {"name": "widgets.example.com"},
               "spec": {"group": "example.com",
                        "names": {"plural": "widgets", "kind": "Widget"},
                        "scope": "Namespaced",
                        "versions": [{"name": "v1", "served": True,
                                      "storage": True,
                                      "schema": {
                                          "openAPIV3Schema": schema}}]}}
        http.create("customresourcedefinitions", crd)
        resp = http._request(
            "POST", "/apis/example.com/v1/namespaces/default/widgets",
            {"kind": "List", "apiVersion": "v1", "items": [
                {"metadata": {"name": "w1"}, "spec": {}},
                {"metadata": {"name": "w2"},
                 "spec": {"size": "not-an-int"}}]})
        st = resp["items"]
        assert st[0]["status"] == "Success"
        assert st[1]["status"] == "Failure"  # schema rejected
        w1 = http._request(
            "GET", "/apis/example.com/v1/namespaces/default/widgets/w1")
        assert w1["spec"]["size"] == 3  # defaulting applied

    def test_bulk_crds_rejected(self, server):
        http, _ = server
        from kubernetes_tpu.client.http_client import HTTPError
        with pytest.raises(HTTPError):
            http._request(
                "POST", "/api/v1/customresourcedefinitions",
                {"kind": "List", "apiVersion": "v1",
                 "items": [{"metadata": {"name": "x.example.com"}}]})

    def test_per_entry_duplicate_reported_not_fatal(self, server):
        http, store = server
        a = meta.new_object("ConfigMap", "dup", "default")
        http.create("configmaps", a)
        resp = http._request(
            "POST", "/api/v1/namespaces/default/configmaps",
            {"kind": "List", "apiVersion": "v1", "items": [
                {"metadata": {"name": "dup"}},
                {"metadata": {"name": "fresh"}}]})
        st = resp["items"]
        assert st[0]["reason"] == "AlreadyExists"
        assert st[1]["status"] == "Success"
        assert store.get("configmaps", "default", "fresh")


class TestFieldSelector:
    def test_list_filters_by_field(self, server):
        http, store = server
        for i in range(4):
            p = mkpod(f"fs-{i}")
            if i % 2 == 0:
                p["spec"]["nodeName"] = "node-a"
            store.create(PODS, p)
        got = http._request(
            "GET", "/api/v1/namespaces/default/pods"
                   "?fieldSelector=spec.nodeName%3Dnode-a")
        names = {meta.name(p) for p in got["items"]}
        assert names == {"fs-0", "fs-2"}
        got = http._request(
            "GET", "/api/v1/namespaces/default/pods"
                   "?fieldSelector=spec.nodeName!%3Dnode-a")
        names = {meta.name(p) for p in got["items"]}
        assert names == {"fs-1", "fs-3"}
        # metadata.name works too (the other common field)
        got = http._request(
            "GET", "/api/v1/namespaces/default/pods"
                   "?fieldSelector=metadata.name%3Dfs-3")
        assert [meta.name(p) for p in got["items"]] == ["fs-3"]

    def test_watch_translates_enter_and_leave(self, server):
        """The kubelet contract (kubelet/config/apiserver.go:38): a
        spec.nodeName=X watch sees a pod APPEAR (ADDED) when the
        scheduler binds it to X, and DISAPPEAR (DELETED) when it moves
        away — even though the store event is MODIFIED."""
        from kubernetes_tpu.client.http_client import HTTPWatch
        http, store = server
        w = HTTPWatch(http.host, http.port,
                      "/api/v1/namespaces/default/pods?watch=true"
                      "&fieldSelector=spec.nodeName%3Dnode-w",
                      http._headers)
        other = mkpod("fw-other")
        other["spec"]["nodeName"] = "node-z"
        store.create(PODS, other)       # never matches: invisible
        store.create(PODS, mkpod("fw-1"))  # unbound: invisible
        store.bind_many(PODS, [("default", "fw-1", "node-w")])  # enters
        ev = w.next(timeout=5.0)
        assert ev is not None
        assert (ev.type, meta.name(ev.object)) == ("ADDED", "fw-1")
        # a plain update while matching stays MODIFIED
        store.guaranteed_update(
            PODS, "default", "fw-1",
            lambda p: (p["metadata"].setdefault(
                "labels", {}).update(x="y") or p))
        ev = w.next(timeout=5.0)
        assert ev is not None and ev.type == "MODIFIED"
        # leaving the selection serves as DELETED
        store.guaranteed_update(
            PODS, "default", "fw-1",
            lambda p: (p["spec"].__setitem__("nodeName", "node-z") or p))
        ev = w.next(timeout=5.0)
        assert ev is not None
        assert (ev.type, meta.name(ev.object)) == ("DELETED", "fw-1")
        w.stop()


    def test_watch_seeded_for_preexisting_matches(self, server):
        """List-then-watch: an object that matched BEFORE the stream
        opened must produce leave/delete events (the matched set is
        seeded, not built only from observed events)."""
        from kubernetes_tpu.client.http_client import HTTPWatch
        http, store = server
        pre = mkpod("fw-pre")
        pre["spec"]["nodeName"] = "node-s"
        created = store.create(PODS, pre)
        rv = meta.resource_version(created)
        w = HTTPWatch(http.host, http.port,
                      f"/api/v1/namespaces/default/pods?watch=true"
                      f"&resourceVersion={rv}"
                      f"&fieldSelector=spec.nodeName%3Dnode-s",
                      http._headers)
        store.delete(PODS, "default", "fw-pre")
        ev = w.next(timeout=5.0)
        assert ev is not None
        assert (ev.type, meta.name(ev.object)) == ("DELETED", "fw-pre")
        w.stop()

    def test_falsy_present_values_match(self, server):
        http, store = server
        p = mkpod("fz")
        p["spec"]["priority"] = 0
        store.create(PODS, p)
        got = http._request(
            "GET", "/api/v1/namespaces/default/pods"
                   "?fieldSelector=spec.priority%3D0")
        assert [meta.name(o) for o in got["items"]] == ["fz"]

    def test_malformed_selector_is_400(self, server):
        http, _ = server
        from kubernetes_tpu.client.http_client import HTTPError
        with pytest.raises(HTTPError):
            http._request(
                "GET", "/api/v1/namespaces/default/pods"
                       "?fieldSelector=nosuchoperator")


class TestWatchBatching:
    def test_burst_arrives_as_one_batch(self, server):
        http, store = server
        w = http.watch(PODS)
        # server-side burst: one transactional create_many
        store.create_many(PODS, [mkpod(f"wb-{i}") for i in range(64)])
        batch = w.next_batch(timeout=5.0)
        # the drain must deliver substantially more than one event per
        # call (exact count can split across TCP segments)
        total = len(batch)
        while total < 64:
            more = w.next_batch(timeout=2.0)
            assert more, f"stream dried up at {total}/64"
            total += len(more)
        assert total == 64
        assert not w.stopped

    def test_partial_line_survives_timeout(self, server):
        """A poll timeout must not corrupt framing: events arriving
        after quiet polls still parse."""
        http, store = server
        w = http.watch(PODS)
        assert w.next(timeout=0.05) is None  # quiet poll
        store.create(PODS, mkpod("pl-1"))
        ev = w.next(timeout=5.0)
        assert ev is not None and meta.name(ev.object) == "pl-1"
        assert w.next(timeout=0.05) is None
        store.create(PODS, mkpod("pl-2"))
        ev = w.next(timeout=5.0)
        assert ev is not None and meta.name(ev.object) == "pl-2"
        assert not w.stopped
