"""Crash-only recovery: every component can die mid-flight and a fresh
instance rebuilds from the store (SURVEY.md §5 failure detection /
elastic recovery; the reference's chaosmonkey exercises the same
contract during upgrades).
"""

import time

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def wait_for(pred, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def new_scheduler(client):
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(fw)})
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    return sched, factory


def scheduled(client):
    return [p for p in client.list(PODS, "default")[0]
            if meta.pod_node_name(p)]


class TestSchedulerCrashRecovery:
    def test_scheduler_restart_resumes_pending_pods(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        for i in range(4):
            client.create(NODES, make_node(f"cr-{i}").build())
        sched, factory = new_scheduler(client)
        for i in range(10):
            client.create(PODS,
                          make_pod(f"a{i}").req(cpu="100m").build())
        assert wait_for(lambda: len(scheduled(client)) == 10)

        # crash: scheduler + informers die with in-memory state
        sched.stop()
        factory.stop()

        # pods created while nobody is scheduling pile up pending
        for i in range(10):
            client.create(PODS,
                          make_pod(f"b{i}").req(cpu="100m").build())
        assert len(scheduled(client)) == 10

        # fresh scheduler = re-list + re-watch; cache rebuilt, backlog drains
        sched2, factory2 = new_scheduler(client)
        try:
            assert wait_for(lambda: len(scheduled(client)) == 20)
            # the rebuilt cache agrees with the apiserver
            from kubernetes_tpu.scheduler.debugger import CacheDebugger
            diff = CacheDebugger(sched2, client).compare()
            assert not diff["nodes"]["missing"] and not diff["pods"]["missing"]
        finally:
            sched2.stop()
            factory2.stop()


class TestControllerCrashRecovery:
    def test_controller_manager_restart_reconverges(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        mgr = ControllerManager(client, factory,
                                controllers=("replicaset",))
        factory.start()
        factory.wait_for_cache_sync()
        mgr.run()

        rs = meta.new_object("ReplicaSet", "cr-rs", "default")
        rs["spec"] = {"replicas": 3,
                      "selector": {"matchLabels": {"app": "cr"}},
                      "template": {"metadata": {"labels": {"app": "cr"}},
                                   "spec": {"containers": [
                                       {"name": "c0", "image": "img"}]}}}
        client.create("replicasets", rs)
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 3)
        mgr.stop()
        factory.stop()

        # scale up while the controller is down; delete a pod too
        def scale(o):
            o["spec"]["replicas"] = 5
            return o
        client.guaranteed_update("replicasets", "default", "cr-rs", scale)
        victim = client.list(PODS, "default")[0][0]
        client.delete(PODS, "default", meta.name(victim))
        assert len(client.list(PODS, "default")[0]) == 2

        factory2 = SharedInformerFactory(client)
        mgr2 = ControllerManager(client, factory2,
                                 controllers=("replicaset",))
        factory2.start()
        factory2.wait_for_cache_sync()
        mgr2.run()
        try:
            assert wait_for(lambda: len([
                p for p in client.list(PODS, "default")[0]
                if meta.deletion_timestamp(p) is None]) == 5)
        finally:
            mgr2.stop()
            factory2.stop()


class TestApiserverRestart:
    def test_http_clients_relist_after_apiserver_restart(self):
        """Store survives (etcd role); the HTTP serving layer restarts and
        watch clients recover via relist (reflector TooOld semantics)."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient

        store = kv.MemoryStore()
        server = APIServer(store).start()
        url = server.url
        client = HTTPClient.from_url(url)
        factory = SharedInformerFactory(client)
        factory.start()
        factory.wait_for_cache_sync()
        store.create(NODES, make_node("ar-1").build())
        inf = factory.informer(NODES)
        assert wait_for(lambda: inf.get("", "ar-1") is not None)

        server.stop()
        # object written while the API is down (by a co-located writer)
        store.create(NODES, make_node("ar-2").build())
        server2 = APIServer(store, port=server.port).start()
        try:
            assert wait_for(lambda: inf.get("", "ar-2") is not None,
                            timeout=30.0)
        finally:
            factory.stop()
            server2.stop()
