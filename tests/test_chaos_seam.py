"""Chaos suite for the fault-tolerant remote TPU seam (ops/remote.py,
ops/faults.py, ops/failover.py; test/e2e chaosmonkey precedent).

Every fault is injected on a seeded, deterministic schedule
(FaultSchedule), so each test is reproducible: dropped requests are
retried transparently, corrupted response frames are detected by the CRC
framing and deduped by the worker's seq cache, a killed+restarted worker
is resynced mid-stream bit-identically, malformed requests surface as
clean client exceptions, and the failover ladder opens/re-closes its
breakers — with the scheduler requeueing failed batches instead of
dropping pods.
"""

import threading
import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.ops.backend import TPUBatchBackend
from kubernetes_tpu.ops.failover import FailoverBatchBackend
from kubernetes_tpu.ops.faults import (
    CORRUPT, DELAY, DROP, KILL, NONE, FaultSchedule, FaultyTransport)
from kubernetes_tpu.ops.flatten import Caps
from kubernetes_tpu.ops.remote import (
    DeviceWorker, RemoteTPUBatchBackend, WorkerProtocolError, transport_for)
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.scheduler.cache import Cache, Snapshot
from kubernetes_tpu.scheduler.config import (
    ConfigError, RemoteSeamPolicy, load_config)
from kubernetes_tpu.scheduler.scheduler import (
    BackendUnavailableError, BatchBackend)
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod

pytestmark = pytest.mark.chaos


def small_caps():
    return Caps(n_cap=32, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8)


def snapshot_from(nodes):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    return cache.update_snapshot(Snapshot())


def wait_for(pred, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def fast_policy(**kw):
    kw.setdefault("retry_base", 0.005)
    kw.setdefault("retry_max", 0.05)
    return RemoteSeamPolicy(**kw)


@pytest.fixture(params=["http", "grpc"])
def worker(request):
    """Chaos runs need a private worker per test (kills mint new epochs),
    over BOTH transports."""
    if request.param == "grpc":
        from kubernetes_tpu.ops.remote import GrpcDeviceWorker
        w = GrpcDeviceWorker().start()
    else:
        w = DeviceWorker().start()
    yield w
    w.stop()


def faulty_backend(worker, schedule, *, caps=None, policy=None, **kw):
    transport = FaultyTransport(transport_for(worker.url), schedule,
                                on_kill=worker.simulate_restart)
    backend = RemoteTPUBatchBackend(
        worker.url, caps or small_caps(), transport=transport,
        policy=policy or fast_policy(), **kw)
    return backend, transport


def spread_pods(n=12):
    return [PodInfo(make_pod(f"s{i}").labels(app="web").req(cpu="100m")
                    .topology_spread("topology.kubernetes.io/zone",
                                     max_skew=2,
                                     match_labels={"app": "web"}).build())
            for i in range(n)]


def zone_nodes(n=9):
    return [make_node(f"z{i}").zone("abc"[i % 3])
            .capacity(cpu="8", mem="32Gi").build() for i in range(n)]


class KillOnNthStep(FaultSchedule):
    """Restart the worker immediately before its Nth /step — robust to
    the exact call count of init/static/refresh traffic around it."""

    def __init__(self, n: int):
        super().__init__()
        self.n = n
        self.steps = 0
        self.fired = False

    def action(self, call_index, verb):
        if verb.startswith("/step"):
            self.steps += 1
            if self.steps == self.n and not self.fired:
                self.fired = True
                self.rng.random()  # keep the one-draw-per-call invariant
                return KILL
        # rate-driven weather (if any) still applies to every other call
        return super().action(call_index, verb)


class TestTransportFaults:
    def test_drops_are_retried_transparently(self, worker):
        """Scripted request drops on the static/refresh/step path: the
        bounded-backoff retry absorbs them and the assignments match an
        in-process run exactly."""
        # call 0 is /init; 1..2 drop /static twice (two retries), 4 drops
        # another verb's first attempt
        schedule = FaultSchedule(script={1: DROP, 2: DROP, 4: DROP})
        backend, transport = faulty_backend(worker, schedule)
        nodes = [make_node(f"n{i}").capacity(cpu="4", mem="16Gi").build()
                 for i in range(8)]
        snap = snapshot_from(nodes)
        pods = [PodInfo(make_pod(f"p{i}").req(cpu="500m",
                                              mem="512Mi").build())
                for i in range(16)]
        got = backend.assign(list(pods), snap)
        want = TPUBatchBackend(small_caps(), batch_size=256).assign(
            list(pods), snap)
        assert [n for n, _ in got] == [n for n, _ in want]
        assert transport.injected[DROP] == 3
        assert backend.seam_stats["retries"] >= 3
        assert backend.seam_stats["giveups"] == 0

    def test_delays_within_deadline_are_harmless(self, worker):
        schedule = FaultSchedule(seed=7, delay_rate=0.5, delay_s=0.005)
        backend, transport = faulty_backend(worker, schedule)
        nodes = [make_node(f"n{i}").capacity(cpu="4", mem="16Gi").build()
                 for i in range(4)]
        out = backend.assign(
            [PodInfo(make_pod(f"d{i}").req(cpu="100m").build())
             for i in range(8)], snapshot_from(nodes))
        assert all(n is not None for n, _ in out)
        assert transport.injected[DELAY] > 0

    def test_corrupt_frame_detected_and_retry_dedups(self, worker):
        """A corrupted /step response triggers the CRC check; the retry
        carries the same seq, so the worker serves its cached response
        WITHOUT re-applying — results identical to a clean run."""
        # fresh backend call sequence: 0=/init 1=/static 2=/refresh 3=/step
        schedule = FaultSchedule(script={3: CORRUPT})
        backend, transport = faulty_backend(worker, schedule)
        nodes = [make_node("solo").capacity(cpu="2", mem="8Gi").build()]
        snap = snapshot_from(nodes)
        out = backend.assign(
            [PodInfo(make_pod("c0").req(cpu="1500m").build())], snap)
        assert out[0][0] == "solo"
        assert transport.injected[CORRUPT] == 1
        assert backend.seam_stats["corrupt_frames"] == 1
        # the step was applied exactly once: a second pod of the same size
        # must NOT fit (a double-applied step would have left used=3000m
        # and a single-applied 1500m — either way it rejects; check via a
        # small pod that fits only if exactly one step committed)
        out2 = backend.assign(
            [PodInfo(make_pod("c1").req(cpu="400m").build())], snap)
        assert out2[0][0] == "solo"

    def test_malformed_step_is_a_clean_client_error(self, worker):
        """Satellite regression: a malformed /step body must surface as a
        structured, non-retryable client exception (not a stall, not a
        dead worker)."""
        backend = RemoteTPUBatchBackend(worker.url, small_caps(),
                                        policy=fast_policy())
        nodes = [make_node("m0").capacity(cpu="4", mem="16Gi").build()]
        snap = snapshot_from(nodes)
        with pytest.raises(WorkerProtocolError):
            backend._post("/step?variant=full", b"\x01\x02\x03")
        assert backend.seam_stats["retries"] == 0  # fatal, not retried
        # the worker survived the bad request and keeps serving
        out = backend.assign(
            [PodInfo(make_pod("ok").req(cpu="100m").build())], snap)
        assert out[0][0] == "m0"

    def test_unreachable_worker_exhausts_into_unavailable(self):
        """Retries against a dead address give up with the scheduler-
        visible BackendUnavailableError subclass, promptly."""
        policy = fast_policy(max_retries=2, init_timeout=0.5)
        with pytest.raises(BackendUnavailableError):
            RemoteTPUBatchBackend("http://127.0.0.1:9", small_caps(),
                                  policy=policy)


class TestRestartResync:
    def test_kill_mid_stream_resyncs_bit_identical(self, worker):
        """The tentpole acceptance: kill+restart the worker between steps
        of a chunked batch; the client detects the lost state via the
        epoch token, replays init/static/refresh + the step journal, and
        the final assignments are bit-identical to an uninterrupted
        in-process run."""
        schedule = KillOnNthStep(2)
        backend, transport = faulty_backend(
            worker, schedule, batch_size=16, full_batch_cap=4)
        nodes = zone_nodes()
        snap = snapshot_from(nodes)
        pods = spread_pods(12)  # 3 chunks through the full variant
        got = backend.assign(list(pods), snap)
        want = TPUBatchBackend(small_caps(), batch_size=16,
                               full_batch_cap=4).assign(list(pods), snap)
        assert transport.injected[KILL] == 1
        assert backend.seam_stats["resyncs"] >= 1
        assert backend.seam_stats["state_lost"] >= 1
        assert [n for n, _ in got] == [n for n, _ in want]

    def test_ns_selector_tensors_round_trip_with_kill(self, worker):
        """Acceptance: the namespace tensors (per-pod namespace ids via
        the packed step buffer, per-group namespace masks via /static)
        round-trip the seam on BOTH transports and survive a mid-stream
        kill+resync — assignments bit-identical to the in-process
        backend fed the identical namespace events, with zero escapes."""
        schedule = KillOnNthStep(2)
        backend, transport = faulty_backend(worker, schedule, batch_size=8)
        reference = TPUBatchBackend(small_caps(), batch_size=8)
        namespaces = [
            {"metadata": {"name": "ns-dev-a", "labels": {"team": "dev"}}},
            {"metadata": {"name": "ns-dev-b", "labels": {"team": "dev"}}},
            {"metadata": {"name": "ns-ops", "labels": {"team": "ops"}}},
        ]
        for b in (backend, reference):
            for ns in namespaces:
                b.note_namespace_event("ADDED", ns)
        nodes = [make_node(f"h{i}")
                 .labels(**{"kubernetes.io/hostname": f"h{i}"})
                 .capacity(cpu="8", mem="32Gi").build() for i in range(6)]
        snap = snapshot_from(nodes)

        def anti_pod(name, ns):
            p = make_pod(name, ns).labels(color="green").req(
                cpu="100m").build()
            p["spec"]["affinity"] = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {"topologyKey": "kubernetes.io/hostname",
                     "labelSelector": {"matchLabels": {"color": "green"}},
                     "namespaceSelector": {"matchLabels": {"team": "dev"}}}]}}
            return PodInfo(p)

        ns_cycle = ["ns-dev-a", "ns-dev-b", "ns-ops", "default"]
        first = [anti_pod(f"a{i}", ns_cycle[i % 4]) for i in range(4)]
        # the second batch's /step is the 2nd overall -> lands on a
        # restarted worker: the resync must replay the namespace masks
        # (static) AND the first batch's committed claims
        second = [anti_pod(f"b{i}", ns_cycle[i % 4]) for i in range(4)]
        got = [backend.assign(list(batch), snap)
               for batch in (first, second)]
        want = [reference.assign(list(batch), snap)
                for batch in (first, second)]
        assert transport.injected[KILL] == 1
        assert backend.seam_stats["resyncs"] >= 1
        for g, w in zip(got, want):
            assert [n for n, _ in g] == [n for n, _ in w]
        assert backend.drain_escape_reasons() == {}
        assert reference.drain_escape_reasons() == {}

    def test_kill_mid_preemption_wave_resyncs_bit_identical(self, worker):
        """Batched-preemption chaos: the worker dies right before a
        /preempt post.  The client detects the lost state, replays the
        victim-carrying /static checkpoint + /refresh, and the re-posted
        dry run returns decisions bit-identical to an in-process run —
        no escape, no divergence."""

        class KillOnFirstPreempt(FaultSchedule):
            def action(self, call_index, verb):
                self.rng.random()  # keep the one-draw-per-call invariant
                if verb == "/preempt" and self.injectable:
                    self.injectable = False
                    return KILL
                return NONE
            injectable = True

        schedule = KillOnFirstPreempt()
        backend, transport = faulty_backend(worker, schedule)
        nodes = [make_node(f"pn{i}").capacity(cpu="2", mem="8Gi").build()
                 for i in range(4)]
        cache = Cache()
        for n in nodes:
            cache.add_node(n)
        for i in range(8):
            cache.add_pod(make_pod(f"pv{i}").priority(1)
                          .req(cpu="700m").node(f"pn{i % 4}").build())
        snap = cache.update_snapshot(Snapshot())
        backend.assign([], snap)
        reference = TPUBatchBackend(small_caps(), batch_size=8)
        reference.assign([], snap)
        preemptors = [PodInfo(make_pod(f"pp{j}").priority(10)
                              .req(cpu="1600m").build()) for j in range(3)]
        node_ord_of = {ni.name: i for i, ni in enumerate(snap.list())}
        got, esc = backend.preempt_batch(preemptors, node_ord_of)
        want, esc_w = reference.preempt_batch(preemptors, node_ord_of)
        assert transport.injected[KILL] == 1
        assert backend.seam_stats["resyncs"] >= 1
        assert esc == esc_w == {}
        assert got == want

    def test_kill_then_more_batches_keep_chaining(self, worker):
        """Resident-state chaining survives a restart: claims committed
        before AND replayed after the kill constrain later batches."""
        schedule = KillOnNthStep(2)
        backend, _ = faulty_backend(worker, schedule, batch_size=4)
        nodes = [make_node("small").capacity(cpu="1", mem="2Gi").build()]
        snap = snapshot_from(nodes)
        first = backend.assign([PodInfo(make_pod("a").req(
            cpu="800m").build())], snap)
        assert first[0][0] == "small"
        # this batch's step is the 2nd overall -> lands on a restarted
        # worker, forcing a resync that must replay pod a's claim
        second = backend.assign([PodInfo(make_pod("b").req(
            cpu="800m").build())], snap)
        assert second[0][0] is None
        assert backend.seam_stats["resyncs"] >= 1


class _StubRung(BatchBackend):
    """Scriptable rung for ladder tests: fails the next N dispatches,
    then assigns every pod to a fixed node."""

    def __init__(self, node: str = "fb-0"):
        self.node = node
        self.fail_next = 0
        self.healthy = True
        self.dispatches = 0
        self.stats = {"batches": 0}

    def health(self):
        if not self.healthy:
            raise RuntimeError("stub rung down")
        return {"ok": True}

    def dispatch(self, pod_infos, snapshot):
        self.dispatches += 1
        if self.fail_next > 0:
            self.fail_next -= 1
            raise BackendUnavailableError("injected rung failure")
        results = [(self.node, None) for _ in pod_infos]
        self.stats["batches"] += 1
        return lambda: results


class TestFailoverLadder:
    def test_breaker_opens_after_threshold_and_fails_over(self):
        a, b = _StubRung("a0"), _StubRung("b0")
        ladder = FailoverBatchBackend([("remote", a), ("inproc", b)],
                                      failure_threshold=2,
                                      probe_interval=100.0)
        a.fail_next = 2
        for _ in range(2):
            with pytest.raises(BackendUnavailableError):
                ladder.dispatch([1], None)
        assert ladder.breaker_state() == {"remote": 1.0, "inproc": 0.0}
        assert ladder.seam_stats["failovers"] == 1
        out = ladder.dispatch([1, 2], None)()
        assert [n for n, _ in out] == ["b0", "b0"]
        assert a.dispatches == 2  # open rung never sees the batch

    def test_breaker_probes_and_recloses(self):
        a, b = _StubRung("a0"), _StubRung("b0")
        ladder = FailoverBatchBackend([("remote", a), ("inproc", b)],
                                      failure_threshold=1,
                                      probe_interval=0.03)
        a.fail_next = 1
        a.healthy = False
        with pytest.raises(BackendUnavailableError):
            ladder.dispatch([1], None)
        assert ladder.breaker_state()["remote"] == 1.0
        time.sleep(0.05)
        # probe due but the rung is still down: failed probe re-arms and
        # the batch serves from the next rung
        assert ladder.dispatch([1], None)()[0][0] == "b0"
        assert ladder.seam_stats["failed_probes"] >= 1
        a.healthy = True
        time.sleep(0.05)
        assert ladder.dispatch([1], None)()[0][0] == "a0"  # failed back
        assert ladder.seam_stats["recloses"] >= 1
        assert ladder.breaker_state()["remote"] == 0.0

    def test_all_rungs_open_degrades_to_oracle_skips(self):
        a, b = _StubRung("a0"), _StubRung("b0")
        ladder = FailoverBatchBackend([("remote", a), ("inproc", b)],
                                      failure_threshold=1,
                                      probe_interval=100.0)
        a.fail_next, b.fail_next = 1, 1
        for _ in range(2):
            with pytest.raises(BackendUnavailableError):
                ladder.dispatch([1], None)
        out = ladder.dispatch([1, 2, 3], None)()
        assert all(n is None and s.is_skip() for n, s in out)
        assert ladder.seam_stats["oracle_batches"] == 1
        snap = ladder.seam_snapshot()
        assert snap["failovers"] == 2

    def test_resolve_failure_also_counts(self):
        class FailsOnResolve(_StubRung):
            def dispatch(self, pod_infos, snapshot):
                def boom():
                    raise BackendUnavailableError("resolve-side failure")
                return boom

        a, b = FailsOnResolve(), _StubRung("b0")
        ladder = FailoverBatchBackend([("remote", a), ("inproc", b)],
                                      failure_threshold=1,
                                      probe_interval=100.0)
        with pytest.raises(BackendUnavailableError):
            ladder.dispatch([1], None)()
        assert ladder.breaker_state()["remote"] == 1.0


class TestSchedulerRequeue:
    def test_failed_batches_reenter_backoff_and_still_bind(self):
        """Satellite 3 + tentpole (3): a backend that fails twice must not
        drop or unschedulable-mark the batch — the pods re-enter the
        backoff tier and bind once the backend recovers."""
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        fw = new_default_framework(client, factory)
        flaky = _StubRung("fb-0")
        flaky.fail_next = 2
        sched = Scheduler(client, factory, {"default-scheduler": Profile(
            fw, batch_backend=flaky, batch_size=8)})
        sched.queue._initial_backoff = 0.05
        sched.queue._max_backoff = 0.2
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        try:
            client.create(NODES, make_node("fb-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(5):
                client.create(PODS,
                              make_pod(f"fb{i}").req(cpu="100m").build())
            assert wait_for(lambda: all(
                meta.pod_node_name(p)
                for p in client.list(PODS, "default")[0]), timeout=30)
            assert sched.metrics.prom.tpu_seam_events.value(
                "batch_failures") == 2.0
            assert sched.metrics.prom.tpu_seam_events.value(
                "requeued_pods") > 0
        finally:
            sched.stop()
            factory.stop()


class TestSeamPolicyConfig:
    def test_remote_seam_stanza_parses(self):
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "remoteSeam": {
                "stepTimeoutSeconds": 7.5,
                "maxRetries": 5,
                "retryBaseSeconds": 0.01,
                "failureThreshold": 4,
                "probeIntervalSeconds": 1.0,
                "journalCap": 64,
            },
        })
        p = cfg.remote_seam
        assert p.step_timeout == 7.5
        assert p.init_timeout == 120.0  # untouched fields keep defaults
        assert p.max_retries == 5
        assert p.failure_threshold == 4
        assert p.journal_cap == 64

    def test_unknown_seam_key_rejected(self):
        with pytest.raises(ConfigError):
            load_config({
                "apiVersion": "kubescheduler.config.k8s.io/v1",
                "kind": "KubeSchedulerConfiguration",
                "remoteSeam": {"stepDeadline": 7.5},
            })

    def test_policy_backoff_bounded(self):
        import random
        p = RemoteSeamPolicy(retry_base=0.1, retry_max=1.0,
                             retry_jitter=0.5)
        rng = random.Random(0)
        delays = [p.backoff(a, rng) for a in range(1, 12)]
        assert all(0.0 <= d <= 1.5 for d in delays)
        assert delays[0] < 1.0  # starts near the base, grows

    def test_legacy_timeout_arg_still_respected(self, worker):
        backend = RemoteTPUBatchBackend(worker.url, small_caps(),
                                        timeout=33.0)
        assert backend.timeout == 33.0
        assert backend.policy.step_timeout == 33.0
        assert backend.policy.init_timeout == 33.0


@pytest.mark.slow
class TestChaoticWeatherEndToEnd:
    def test_full_scheduler_through_seeded_chaos(self, worker):
        """The acceptance storm: seeded drops + delays + corrupt frames +
        one worker kill under a live scheduler.  Every pod must bind,
        and no node may end up over-committed (a duplicate/incorrect
        binding would overflow a node's capacity)."""
        schedule = KillOnNthStep(3)
        schedule.drop_rate = 0.10
        schedule.delay_rate = 0.25
        schedule.corrupt_rate = 0.08
        schedule.delay_s = 0.003
        transport = FaultyTransport(transport_for(worker.url), schedule,
                                    on_kill=worker.simulate_restart)
        backend = RemoteTPUBatchBackend(
            worker.url, small_caps(), batch_size=8,
            transport=transport, policy=fast_policy(max_retries=6))
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        fw = new_default_framework(client, factory)
        sched = Scheduler(client, factory, {"default-scheduler": Profile(
            fw, batch_backend=backend, batch_size=8)})
        sched.queue._initial_backoff = 0.05
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        try:
            for i in range(4):
                client.create(NODES, make_node(f"cw-{i}")
                              .capacity(cpu="10", mem="40Gi").build())
            for i in range(40):
                client.create(PODS,
                              make_pod(f"cp{i}").req(cpu="1").build())
            assert wait_for(lambda: all(
                meta.pod_node_name(p)
                for p in client.list(PODS, "default")[0]), timeout=120)
            pods, _ = client.list(PODS, "default")
            per_node: dict = {}
            for p in pods:
                per_node[meta.pod_node_name(p)] = per_node.get(
                    meta.pod_node_name(p), 0) + 1
            # cpu=10 per node, cpu=1 per pod: any double-counted binding
            # would overflow a node
            assert all(v <= 10 for v in per_node.values()), per_node
            assert sum(per_node.values()) == 40
            # the kill is deterministic (3rd step); weather is seeded on
            # top of it
            assert transport.injected[KILL] == 1
            assert backend.seam_stats["resyncs"] >= 1
        finally:
            sched.stop()
            factory.stop()
