"""Randomized churn parity: event-driven tensor maintenance vs
from-scratch re-flatten.

Seeded informer event streams (adds, relabels, deletes, mid-wave event
drains, forced compaction, forced generation-stale fallback) drive the
incremental patch path, and every scenario is pinned against an
authoritative oracle:

  * tensor parity — after churn, a forced full re-encode of every live
    row (the from-scratch flatten) must reproduce the patched tensors
    bit for bit;
  * wave parity — the identical event+wave stream replayed on another
    backend lineage (single-chip vs sharded vs grpc-seam, healthy vs
    gen-fence-tripped) must yield identical assignments.

Identical event order means identical row-slot allocation across
lineages, so assignment equality here is exact (no tie-break slack).
"""

import copy
import random

import numpy as np
import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.ops.backend import FLUSH_FIRST, TPUBatchBackend
from kubernetes_tpu.ops.flatten import Caps
from kubernetes_tpu.scheduler.cache import Cache, Snapshot
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.testing import make_node, make_pod

pytestmark = pytest.mark.churn


def small_caps():
    return Caps(n_cap=32, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8)


# -- seeded scenario builder -----------------------------------------------

def build_ops(seed: int, rounds: int, base_nodes: int, *,
              allow_deletes: bool = True, constraint_pods: bool = False,
              forced_compact: bool = True) -> list:
    """Deterministic op list from one seed.  Ops are pure data (node/pod
    dicts built here, deep-copied per run) so the same stream replays
    bit-identically on every backend lineage:

      ("event", type, node_obj)         informer delta -> patch path
      ("wave", [pod_objs], [mid_events]) dispatch; mid_events land
                                         between dispatch and resolve
      ("compact",)                       forced tombstone reclamation
    """
    rng = random.Random(seed)
    ops: list = []
    live: list[str] = []
    zone_of: dict[str, str] = {}
    cpu_of: dict[str, str] = {}
    serial = 0

    def new_node(relabel_round: int | None = None, name: str | None = None):
        nonlocal serial
        if name is None:
            name = f"churn{seed}-n{serial}"
            serial += 1
            zone_of[name] = "abc"[rng.randrange(3)]
            cpu_of[name] = str(4 + 2 * rng.randrange(3))
        w = make_node(name).zone(zone_of[name]).capacity(
            cpu=cpu_of[name], mem="32Gi")
        if relabel_round is not None:
            w = w.labels(tier=f"t{relabel_round}")
            if rng.random() < 0.3:  # taint relabels churn the static side
                w = w.taint("churn-tier", f"t{relabel_round % 2}",
                            "PreferNoSchedule")
        return w.build()

    def event(kind: str, node) -> tuple:
        return ("event", kind, node)

    for _ in range(base_nodes):
        node = new_node()
        live.append(meta.name(node))
        ops.append(event("ADDED", node))

    pod_serial = 0
    for r in range(rounds):
        # a few informer deltas between waves
        for _ in range(rng.randint(1, 3)):
            roll = rng.random()
            if roll < 0.40 or not live:
                node = new_node()
                live.append(meta.name(node))
                ops.append(event("ADDED", node))
            elif roll < 0.75 or not allow_deletes or len(live) < 4:
                name = live[rng.randrange(len(live))]
                ops.append(event("MODIFIED", new_node(r, name)))
            else:
                name = live.pop(rng.randrange(len(live)))
                ops.append(event("DELETED", new_node(None, name)))
        if forced_compact and r == rounds // 2:
            ops.append(("compact",))
        pods = []
        for _ in range(rng.randint(3, 8)):
            w = make_pod(f"churn{seed}-p{pod_serial}").req(
                cpu=rng.choice(("100m", "250m", "500m")),
                mem=rng.choice(("256Mi", "512Mi", "1Gi")))
            pod_serial += 1
            if constraint_pods and rng.random() < 0.2:
                w = w.labels(app="web").topology_spread(
                    "topology.kubernetes.io/zone", max_skew=2,
                    match_labels={"app": "web"})
            pods.append(w.build())
        mid = []
        if rng.random() < 0.5:
            # mid-wave drain: deltas landing while the wave is in flight
            for _ in range(rng.randint(1, 2)):
                if allow_deletes and live and rng.random() < 0.3:
                    name = live.pop(rng.randrange(len(live)))
                    mid.append(event("DELETED", new_node(None, name)))
                else:
                    node = new_node()
                    live.append(meta.name(node))
                    mid.append(event("ADDED", node))
        ops.append(("wave", pods, mid))
    return ops


def inject_before_wave(ops: list, wave_idx: int, op: tuple) -> list:
    """Copy of `ops` with `op` inserted right before the wave_idx'th
    wave (0-based) — the gen-skew chaos hook."""
    out, seen = [], 0
    for o in ops:
        if o[0] == "wave":
            if seen == wave_idx:
                out.append(op)
            seen += 1
        out.append(o)
    assert seen > wave_idx, "scenario has too few waves"
    return out


def build_fence_ops(seed: int, rounds: int = 6, base_nodes: int = 12) -> list:
    """Stream whose between-wave churn is EXTERNAL POD BINDS — another
    scheduler committing pods onto shared nodes.  That is a dynamic row
    mutation with no static change: the one churn class the depth-2
    pipeline absorbs as a FENCED dispatch (mirror-borne patch + gen bump
    + replay at resolve) instead of a flush.  Two rounds mix in node
    adds so fenced and flushed waves interleave in one stream.

      ("xpod", pod_obj)   externally-bound pod lands in the cache
    """
    rng = random.Random(seed)
    ops: list = []
    live: list[str] = []

    def new_node(i: int):
        node = make_node(f"fence{seed}-n{i}").zone(
            "abc"[rng.randrange(3)]).capacity(cpu="8", mem="32Gi").build()
        live.append(meta.name(node))
        return ("event", "ADDED", node)

    for i in range(base_nodes):
        ops.append(new_node(i))
    pod_serial = 0
    xpod_serial = 0
    for r in range(rounds):
        if r in (2, 4):
            # static churn round: the pipelined arm must flush here,
            # fence on the xpod rounds, and still match bit for bit
            ops.append(new_node(base_nodes + r))
        else:
            for _ in range(rng.randint(1, 2)):
                xp = make_pod(f"fence{seed}-x{xpod_serial}").req(
                    cpu=rng.choice(("250m", "500m", "1")),
                    mem="512Mi").node(
                        live[rng.randrange(len(live))]).build()
                xpod_serial += 1
                ops.append(("xpod", xp))
        pods = []
        for _ in range(rng.randint(3, 6)):
            pods.append(make_pod(f"fence{seed}-p{pod_serial}").req(
                cpu=rng.choice(("100m", "250m", "500m")),
                mem="256Mi").build())
            pod_serial += 1
        ops.append(("wave", pods, []))
    return ops


# -- scenario driver -------------------------------------------------------

def _apply_event(cache: Cache, backend, kind: str, node) -> None:
    node = copy.deepcopy(node)
    if kind == "DELETED":
        cache.remove_node(node)
    elif kind == "ADDED":
        cache.add_node(node)
    else:
        cache.update_node(node)
    # the scheduler's informer fan-out: cache first, then the patch
    backend.note_node_event(kind, meta.name(node), cache.flatten_view())


def _run_wave(backend, cache: Cache, pod_objs, mid_events):
    pod_objs = [copy.deepcopy(p) for p in pod_objs]
    infos = [PodInfo(p) for p in pod_objs]
    resolve = backend.dispatch(infos, cache.flatten_view())
    assert resolve is not FLUSH_FIRST
    for kind, _t, node in mid_events:
        _apply_event(cache, backend, kind, node)
    results = resolve()
    out = []
    for pod, (name, status) in zip(pod_objs, results):
        out.append((name, None if status is None else status.code))
        if name:
            bound = copy.deepcopy(pod)
            bound.setdefault("spec", {})["nodeName"] = name
            cache.add_pod(bound)
    return out


def run_scenario(backend, ops):
    """Replay one op stream; returns (cache, per-wave result lists)."""
    cache = Cache()
    waves = []
    for op in ops:
        if op[0] == "event":
            _apply_event(cache, backend, op[1], op[2])
        elif op[0] == "compact":
            with backend._lock:
                backend.tensors.compact()
        elif op[0] == "xpod":
            # externally-bound pod (another scheduler's commit): dynamic
            # row churn only — the next dispatch diffs it as a patch
            cache.add_pod(copy.deepcopy(op[1]))
        elif op[0] == "gen_skew":
            # desynchronize the host generation expectation: the next
            # wave's resolve must trip the fence and take the
            # restore-from-mirror + re-run recovery path
            backend._gen += 3
        else:
            waves.append(_run_wave(backend, cache, op[1], op[2]))
    return cache, waves


# -- oracle: forced full re-encode must reproduce the patched tensors -----

_PARITY_FIELDS = ("used", "used_nz", "npods", "port_mask",
                  "alloc", "maxpods", "valid",
                  "taint_mask", "label_mask", "key_mask",
                  "cnt_sg", "dom_sg", "cnt_asg", "dom_asg")


def assert_full_reencode_parity(backend, cache: Cache) -> None:
    """Bit-parity pin: force every live row of a deep copy of the
    resident tensors through the from-scratch _encode_node path (all
    incremental short-circuits defeated) and assert nothing moves."""
    with backend._lock:
        # catch the authoritative tensors up with the cache (the final
        # wave's binds were committed after its drain)
        backend.tensors.update_from_snapshot_tracked(cache.flatten_view())
        t = copy.deepcopy(backend.tensors)
    before = {k: np.array(getattr(t, k), copy=True) for k in _PARITY_FIELDS}
    rows_before = dict(t.row_of)
    t.gen[:] = -1       # every row generation-stale -> full re-encode
    t.node_gen[:] = -1  # defeat the static short-circuit too
    snap = cache.update_snapshot(Snapshot())
    t.update_from_snapshot_tracked(snap)
    assert dict(t.row_of) == rows_before, "re-flatten moved row slots"
    for k in _PARITY_FIELDS:
        np.testing.assert_array_equal(
            before[k], np.asarray(getattr(t, k)),
            err_msg=f"patched tensors diverge from full re-encode: {k}")


# -- tests -----------------------------------------------------------------

@pytest.mark.parametrize("seed", [7, 23])
def test_incremental_patches_match_full_reencode(seed):
    """Seeded churn (adds/relabels/deletes/mid-wave drains/forced
    compaction) through the patch path, then the from-scratch oracle."""
    ops = build_ops(seed, rounds=4, base_nodes=10, constraint_pods=True)
    backend = TPUBatchBackend(small_caps(), batch_size=16)
    cache, waves = run_scenario(backend, ops)
    assert waves and any(n for w in waves for n, _ in w)
    assert backend.stats["event_patches"] > 0
    assert backend.stats.get("compactions", 0) + 1 >= 1  # forced op ran
    assert backend.stats["waves_patched"] >= 1
    assert backend.stats.get("gen_stale_waves", 0) == 0
    snap = backend.maintenance_snapshot()
    assert 0.0 < snap["row_occupancy"] <= 1.0
    assert snap["event_patches"] == backend.stats["event_patches"]
    assert_full_reencode_parity(backend, cache)


def test_forced_reflatten_matches_incremental(monkeypatch):
    """The same stream through (a) the event-patch path and (b) the
    KTPU_FORCE_REFLATTEN world with no event fan-out — every wave pays
    the full re-flatten — must place identically.  No deletes: both
    worlds then allocate row slots in the same order, so equality is
    exact."""
    ops = build_ops(11, rounds=3, base_nodes=8, allow_deletes=False,
                    forced_compact=False)
    inc = TPUBatchBackend(small_caps(), batch_size=16)
    _, inc_waves = run_scenario(inc, ops)
    assert inc.stats["event_patches"] > 0

    monkeypatch.setenv("KTPU_FORCE_REFLATTEN", "1")
    full = TPUBatchBackend(small_caps(), batch_size=16)
    assert full.FORCE_REFLATTEN
    # strip the event fan-out: the forced world only sees wave drains
    full.note_node_event = lambda *a, **k: None
    _, full_waves = run_scenario(full, ops)
    assert full.stats["event_patches"] == 0
    assert inc_waves == full_waves


def test_gen_stale_fallback_parity_single_and_sharded():
    """Forced generation-stale fallback: skew the host gen expectation
    before a mid-stream wave on one lineage; the fence must trip, the
    wave must re-run from the restored mirror, and every assignment must
    still match the healthy lineage bit for bit — on both the
    single-chip and the sharded backend."""
    from kubernetes_tpu.parallel.backend import ShardedTPUBatchBackend

    ops = build_ops(5, rounds=3, base_nodes=10)
    skewed_ops = inject_before_wave(ops, 1, ("gen_skew",))

    healthy = TPUBatchBackend(small_caps(), batch_size=16)
    _, healthy_waves = run_scenario(healthy, ops)
    assert healthy.stats.get("gen_stale_waves", 0) == 0

    skewed = TPUBatchBackend(small_caps(), batch_size=16)
    _, skewed_waves = run_scenario(skewed, skewed_ops)
    assert skewed.stats["gen_stale_waves"] >= 1
    assert skewed.stats["gen_recoveries"] >= 1
    assert skewed_waves == healthy_waves

    sh_healthy = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, sh_healthy_waves = run_scenario(sh_healthy, ops)
    assert sh_healthy.stats.get("gen_stale_waves", 0) == 0
    assert sh_healthy.stats["event_patches"] > 0

    sh_skewed = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, sh_skewed_waves = run_scenario(sh_skewed, skewed_ops)
    assert sh_skewed.stats["gen_stale_waves"] >= 1
    assert sh_skewed.stats["gen_recoveries"] >= 1
    # NOT asserted: sharded == single-chip placements.  Equal-score ties
    # break by row order in the single-chip argmax but by shard-local
    # argmax + cross-shard reduce on the mesh — both answers are correct;
    # the parity pin here is per-lineage (healthy vs recovered).
    assert sh_skewed_waves == sh_healthy_waves


def test_seam_backend_churn_parity():
    """The grpc-seam backend (client-side patches, payloads over the
    wire, worker-held device state) through the same churn stream must
    match the in-process backend — including a forced gen-stale wave
    recovered via a mirror /refresh resync."""
    from kubernetes_tpu.ops.remote import DeviceWorker, RemoteTPUBatchBackend

    ops = build_ops(13, rounds=3, base_nodes=10)
    local = TPUBatchBackend(small_caps(), batch_size=16)
    _, local_waves = run_scenario(local, ops)

    worker = DeviceWorker().start()
    try:
        remote = RemoteTPUBatchBackend(worker.url, small_caps(),
                                       batch_size=16)
        skewed_ops = inject_before_wave(ops, 2, ("gen_skew",))
        cache, remote_waves = run_scenario(remote, skewed_ops)
        assert remote.stats["event_patches"] > 0
        assert remote.stats["gen_stale_waves"] >= 1
        assert remote.stats["gen_recoveries"] >= 1
        assert remote_waves == local_waves
        assert_full_reencode_parity(remote, cache)
    finally:
        worker.stop()


# -- checkpointed warm-start: restart mid-stream, resume bit-identical ----

def _split_at_wave(ops: list, wave_idx: int) -> tuple[list, list]:
    """Split the op stream at the wave_idx'th wave boundary: everything
    before it runs pre-restart, everything after resumes post-restart."""
    first, rest, seen = [], [], 0
    for op in ops:
        (first if seen < wave_idx else rest).append(op)
        if op[0] == "wave":
            seen += 1
    assert seen > wave_idx, "scenario has too few waves"
    return first, rest


def _run_tracked(backend, cache: Cache, ops, store: dict) -> list:
    """run_scenario's body, additionally maintaining `store` — the
    objects a restarted informer would be primed with: the latest node
    object per live node plus every bound pod object."""
    waves = []
    for op in ops:
        if op[0] == "event":
            kind, node = op[1], op[2]
            name = meta.name(node)
            if kind == "DELETED":
                store["nodes"].pop(name, None)
            else:
                store["nodes"][name] = node
            _apply_event(cache, backend, kind, node)
        elif op[0] == "compact":
            with backend._lock:
                backend.tensors.compact()
        else:
            pod_objs = [copy.deepcopy(p) for p in op[1]]
            infos = [PodInfo(p) for p in pod_objs]
            resolve = backend.dispatch(infos, cache.flatten_view())
            assert resolve is not FLUSH_FIRST
            for kind, _t, node in op[2]:
                name = meta.name(node)
                if kind == "DELETED":
                    store["nodes"].pop(name, None)
                else:
                    store["nodes"][name] = node
                _apply_event(cache, backend, kind, node)
            results = resolve()
            w = []
            for pod, (name, status) in zip(pod_objs, results):
                w.append((name, None if status is None else status.code))
                if name:
                    bound = copy.deepcopy(pod)
                    bound.setdefault("spec", {})["nodeName"] = name
                    cache.add_pod(bound)
                    store["pods"].append(bound)
            waves.append(w)
    return waves


def _restart_through_checkpoint(make_backend, ops, split_wave: int,
                                path: str):
    """Run `ops` up to split_wave on one backend, checkpoint_mirror,
    then resume the remainder on a FRESH backend + FRESH cache primed
    from the checkpoint's objects — the restarted process's informer
    replay.  Returns (warm backend, its cache, all waves)."""
    first, rest = _split_at_wave(ops, split_wave)
    a = make_backend()
    cache_a = Cache()
    store: dict = {"nodes": {}, "pods": []}
    waves = _run_tracked(a, cache_a, first, store)
    info = a.checkpoint_mirror(
        path, snapshot=cache_a.flatten_view(),
        resource_versions={"nodes": 1, "pods": 1},
        objects={"nodes": [copy.deepcopy(n)
                           for n in store["nodes"].values()],
                 "pods": [copy.deepcopy(p) for p in store["pods"]]})
    b = make_backend()
    warm = b.warm_start(path)
    cache_b = Cache()
    for n in warm["objects"]["nodes"]:
        cache_b.add_node(n)
    for p in warm["objects"]["pods"]:
        cache_b.add_pod(p)
    b.warm_align(cache_b.flatten_view())
    # every checkpointed row's content digest matches the primed replay,
    # so every row is adopted verbatim — zero re-encodes on restart
    assert b.stats.get("warm_adopted", 0) == info["nodes"]
    assert b.stats.get("warm_starts", 0) == 1
    waves += _run_tracked(b, cache_b, rest, store)
    return b, cache_b, waves


@pytest.mark.upgrade
def test_warm_start_parity_single_chip(tmp_path):
    """checkpoint_mirror -> warm_start mid-stream: the restarted
    single-chip backend must place every remaining wave bit-identically
    to the never-restarted control, and the from-scratch re-encode
    oracle must agree with its adopted tensors."""
    ops = build_ops(42, rounds=3, base_nodes=9, constraint_pods=True)
    control = TPUBatchBackend(small_caps(), batch_size=16)
    _, control_waves = run_scenario(control, ops)

    make = lambda: TPUBatchBackend(small_caps(), batch_size=16)  # noqa: E731
    b, cache_b, waves = _restart_through_checkpoint(
        make, ops, 2, str(tmp_path / "single.ckpt"))
    assert waves == control_waves
    assert_full_reencode_parity(b, cache_b)


@pytest.mark.upgrade
@pytest.mark.slow
def test_warm_start_parity_sharded(tmp_path):
    """The same restart contract on the sharded lineage (per-lineage
    control: equal-score ties break differently across lineages)."""
    from kubernetes_tpu.parallel.backend import ShardedTPUBatchBackend

    ops = build_ops(17, rounds=5, base_nodes=10)
    control = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, control_waves = run_scenario(control, ops)

    make = lambda: ShardedTPUBatchBackend(small_caps(), batch_size=16)  # noqa: E731
    b, cache_b, waves = _restart_through_checkpoint(
        make, ops, 2, str(tmp_path / "sharded.ckpt"))
    assert waves == control_waves
    assert_full_reencode_parity(b, cache_b)


@pytest.mark.upgrade
@pytest.mark.slow
def test_warm_start_parity_seam(tmp_path):
    """The grpc-seam lineage: the restarted client warm-starts its host
    mirror from the checkpoint and rebuilds the (fresh) worker's device
    state from it — against a control that never restarted and a
    DIFFERENT worker process, so nothing can leak through the seam."""
    from kubernetes_tpu.ops.remote import DeviceWorker, RemoteTPUBatchBackend

    ops = build_ops(29, rounds=5, base_nodes=10)
    control = TPUBatchBackend(small_caps(), batch_size=16)
    _, control_waves = run_scenario(control, ops)

    workers = []

    def make():
        w = DeviceWorker().start()
        workers.append(w)
        return RemoteTPUBatchBackend(w.url, small_caps(), batch_size=16)

    try:
        b, cache_b, waves = _restart_through_checkpoint(
            make, ops, 2, str(tmp_path / "seam.ckpt"))
        assert waves == control_waves
        assert_full_reencode_parity(b, cache_b)
    finally:
        for w in workers:
            w.stop()


@pytest.mark.upgrade
@pytest.mark.slow
def test_warm_start_portable_across_lineages(tmp_path):
    """The checkpoint payload is host-only (device state rebuilds
    per-lineage), so a single-chip checkpoint warm-starts a sharded
    backend: every row adopts by content digest and the from-scratch
    oracle agrees with the adopted tensors."""
    from kubernetes_tpu.parallel.backend import ShardedTPUBatchBackend

    ops = build_ops(8, rounds=4, base_nodes=10)
    first, rest = _split_at_wave(ops, 2)
    a = TPUBatchBackend(small_caps(), batch_size=16)
    cache_a = Cache()
    store: dict = {"nodes": {}, "pods": []}
    _run_tracked(a, cache_a, first, store)
    path = str(tmp_path / "cross.ckpt")
    info = a.checkpoint_mirror(
        path, snapshot=cache_a.flatten_view(),
        objects={"nodes": [copy.deepcopy(n)
                           for n in store["nodes"].values()],
                 "pods": [copy.deepcopy(p) for p in store["pods"]]})
    b = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    warm = b.warm_start(path)
    assert warm["lineage"] == "tpu"  # informational, not a gate
    cache_b = Cache()
    for n in warm["objects"]["nodes"]:
        cache_b.add_node(n)
    for p in warm["objects"]["pods"]:
        cache_b.add_pod(p)
    b.warm_align(cache_b.flatten_view())
    assert b.stats.get("warm_adopted", 0) == info["nodes"]
    _run_tracked(b, cache_b, rest, store)
    assert_full_reencode_parity(b, cache_b)


@pytest.mark.upgrade
def test_checkpoint_rejects_never_corrupts(tmp_path):
    """Stale, corrupt or mismatched checkpoints raise CheckpointError
    BEFORE any backend state moves: the cold start that follows places
    bit-identically to a backend that never saw a checkpoint."""
    from kubernetes_tpu.ops.backend import (
        CHECKPOINT_MAGIC, CHECKPOINT_SCHEMA_VERSION, CheckpointError)
    from kubernetes_tpu.ops.flatten import Caps

    ops = build_ops(3, rounds=3, base_nodes=8)
    control = TPUBatchBackend(small_caps(), batch_size=16)
    _, control_waves = run_scenario(control, ops)

    donor = TPUBatchBackend(small_caps(), batch_size=16)
    cache, _ = run_scenario(donor, ops)[0], None
    path = str(tmp_path / "donor.ckpt")
    donor.checkpoint_mirror(path, snapshot=cache.flatten_view())
    raw = open(path, "rb").read()
    hlen = len(CHECKPOINT_MAGIC) + 8

    cases = {
        "bad magic": b"NOTACKPT" + raw[len(CHECKPOINT_MAGIC):],
        "schema bump": (CHECKPOINT_MAGIC
                        + (CHECKPOINT_SCHEMA_VERSION + 1).to_bytes(4, "big")
                        + raw[len(CHECKPOINT_MAGIC) + 4:]),
        "crc corrupt": raw[:-8] + bytes(8),
        "truncated": raw[:hlen - 2],
    }
    for label, blob in cases.items():
        bad = str(tmp_path / "bad.ckpt")
        with open(bad, "wb") as f:
            f.write(blob)
        victim = TPUBatchBackend(small_caps(), batch_size=16)
        with pytest.raises(CheckpointError):
            victim.warm_start(bad)
        assert not victim._warm_pending, label
        _, waves = run_scenario(victim, ops)
        assert waves == control_waves, f"{label}: cold fallback diverged"

    # caps mismatch: same container shape class, different capacity
    other = TPUBatchBackend(
        Caps(n_cap=64, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
             s_cap=2, sg_cap=8, asg_cap=8), batch_size=16)
    with pytest.raises(CheckpointError):
        other.warm_start(path)
    # missing file: plain cold-start error, no state touched
    with pytest.raises(CheckpointError):
        TPUBatchBackend(small_caps(), batch_size=16).warm_start(
            str(tmp_path / "nope.ckpt"))


# -- pipelined churn: depth-2 dispatch with churn landing mid-pipeline ----

def run_scenario_pipelined(backend, ops, depth=2):
    """Depth-`depth` scheduler-style driver: up to `depth` waves ride
    the device queue at once, retired oldest-first (the exact protocol
    of scheduler.schedule_step: dispatch, append, trim to depth).  A
    wave op's mid events land BETWEEN this wave's dispatch and the next
    dispatch, so churn hits with a wave in flight — the fenced-dispatch
    path.  FLUSH_FIRST drains the pipeline then re-dispatches, exactly
    like scheduler._dispatch_batch."""
    cache = Cache()
    waves: list = []
    pending: list = []  # (resolve, pod_objs), oldest first

    def finish():
        resolve, pod_objs = pending.pop(0)
        results = resolve()
        w = []
        for pod, (name, status) in zip(pod_objs, results):
            w.append((name, None if status is None else status.code))
            if name:
                bound = copy.deepcopy(pod)
                bound.setdefault("spec", {})["nodeName"] = name
                cache.add_pod(bound)
        waves.append(w)

    for op in ops:
        if op[0] == "event":
            _apply_event(cache, backend, op[1], op[2])
        elif op[0] == "compact":
            while pending:   # compaction needs a quiescent device chain
                finish()
            with backend._lock:
                backend.tensors.compact()
        elif op[0] == "xpod":
            # lands while wave N is in flight: wave N+1's dispatch sees
            # a dynamic-only diff and must ride the pipeline FENCED
            cache.add_pod(copy.deepcopy(op[1]))
        elif op[0] == "gen_skew":
            backend._gen += 3
        else:
            pod_objs = [copy.deepcopy(p) for p in op[1]]
            infos = [PodInfo(p) for p in pod_objs]
            resolve = backend.dispatch(infos, cache.flatten_view())
            if resolve is FLUSH_FIRST:
                while pending:
                    finish()
                resolve = backend.dispatch(infos, cache.flatten_view())
                assert resolve is not FLUSH_FIRST, \
                    "backend demanded flush with empty pipeline"
            pending.append((resolve, pod_objs))
            for kind, _t, node in op[2]:
                _apply_event(cache, backend, kind, node)
            while len(pending) > depth:
                finish()
    while pending:
        finish()
    return cache, waves


@pytest.mark.pipeline
@pytest.mark.parametrize("seed", [7, 23, 5])
def test_pipelined_churn_parity_single_chip(seed):
    """Node deletes/relabels landing between wave N's dispatch and wave
    N+1's dispatch must produce assignments bit-identical to the serial
    depth-1 run: the fenced dispatch holds the patches back in the
    mirror and the fenced wave replays from restored state at resolve.
    The fenced path must actually run (fence_replays > 0) and the
    from-scratch re-encode oracle must agree with the patched tensors."""
    ops = build_ops(seed, rounds=5, base_nodes=10, constraint_pods=True)
    serial = TPUBatchBackend(small_caps(), batch_size=16)
    _, serial_waves = run_scenario(serial, ops)

    piped = TPUBatchBackend(small_caps(), batch_size=16)
    cache, piped_waves = run_scenario_pipelined(piped, ops, depth=2)
    assert piped_waves == serial_waves
    # node churn is STATIC change, which never rides the pipeline — the
    # depth-2 arm must drain (flush) at those boundaries, not resolve a
    # retained wave against swapped static arrays
    assert piped.stats.get("flush_first", 0) >= 1
    assert piped.stats.get("fenced_waves", 0) == piped.stats.get(
        "fence_replays", 0)
    assert piped.stats.get("gen_stale_waves", 0) == 0
    assert piped._fence_pending == 0
    assert not piped._stage_pins
    assert_full_reencode_parity(piped, cache)


@pytest.mark.pipeline
@pytest.mark.parametrize("seed", [11, 42])
def test_pipelined_fence_external_binds(seed):
    """External pod binds (dynamic row churn, no static change) landing
    between wave N's dispatch and wave N+1's dispatch: wave N+1 must
    ride the pipeline FENCED — mirror-borne patch, gen bump, replay at
    resolve — and still match the serial arm bit for bit."""
    ops = build_fence_ops(seed)
    serial = TPUBatchBackend(small_caps(), batch_size=16)
    _, serial_waves = run_scenario(serial, ops)

    piped = TPUBatchBackend(small_caps(), batch_size=16)
    cache, piped_waves = run_scenario_pipelined(piped, ops, depth=2)
    assert piped_waves == serial_waves
    assert piped.stats.get("fence_replays", 0) >= 1
    assert piped.stats.get("fenced_waves", 0) == piped.stats.get(
        "fence_replays", 0)
    assert piped.stats.get("gen_stale_waves", 0) == 0
    assert piped._fence_pending == 0
    assert not piped._stage_pins
    assert_full_reencode_parity(piped, cache)


@pytest.mark.pipeline
def test_pipelined_churn_parity_with_gen_skew():
    """Forced gen-skew recovery inside the pipelined run: the fence
    machinery must recover mid-pipeline and still match the serial
    depth-1 arm bit for bit."""
    ops = build_ops(31, rounds=5, base_nodes=10)
    skewed_ops = inject_before_wave(ops, 2, ("gen_skew",))

    serial = TPUBatchBackend(small_caps(), batch_size=16)
    _, serial_waves = run_scenario(serial, ops)

    piped = TPUBatchBackend(small_caps(), batch_size=16)
    cache, piped_waves = run_scenario_pipelined(piped, ops, depth=2)
    assert piped_waves == serial_waves

    skewed = TPUBatchBackend(small_caps(), batch_size=16)
    _, skewed_waves = run_scenario_pipelined(skewed, skewed_ops, depth=2)
    assert skewed.stats.get("gen_stale_waves", 0) >= 1
    assert skewed.stats["gen_recoveries"] >= 1
    assert skewed_waves == serial_waves
    assert_full_reencode_parity(piped, cache)


@pytest.mark.pipeline
def test_pipelined_churn_parity_sharded():
    """The sharded lineage under the same depth-2 driver (per-lineage
    control: equal-score ties break differently across lineages)."""
    from kubernetes_tpu.parallel.backend import ShardedTPUBatchBackend

    ops = build_ops(9, rounds=4, base_nodes=10)
    serial = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, serial_waves = run_scenario(serial, ops)

    piped = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    cache, piped_waves = run_scenario_pipelined(piped, ops, depth=2)
    assert piped_waves == serial_waves
    assert piped._fence_pending == 0

    skewed_ops = inject_before_wave(ops, 1, ("gen_skew",))
    skewed = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, skewed_waves = run_scenario_pipelined(skewed, skewed_ops, depth=2)
    assert skewed.stats.get("gen_stale_waves", 0) >= 1
    assert skewed_waves == serial_waves
    assert_full_reencode_parity(piped, cache)

    # fenced path on the sharded lineage: external binds between waves
    fops = build_fence_ops(9, rounds=4)
    fserial = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    _, fserial_waves = run_scenario(fserial, fops)
    fpiped = ShardedTPUBatchBackend(small_caps(), batch_size=16)
    fcache, fpiped_waves = run_scenario_pipelined(fpiped, fops, depth=2)
    assert fpiped_waves == fserial_waves
    assert fpiped.stats.get("fence_replays", 0) >= 1
    assert fpiped._fence_pending == 0
    assert_full_reencode_parity(fpiped, fcache)


@pytest.mark.pipeline
def test_pipelined_churn_parity_seam():
    """The grpc-seam lineage: fenced dispatches ride the wire (the
    fenced replay goes through a mirror /refresh resync on the worker)
    and must still match the in-process serial arm, including a forced
    gen-skew wave."""
    from kubernetes_tpu.ops.remote import DeviceWorker, RemoteTPUBatchBackend

    ops = build_ops(13, rounds=4, base_nodes=10)
    local = TPUBatchBackend(small_caps(), batch_size=16)
    _, local_waves = run_scenario(local, ops)
    fops = build_fence_ops(13, rounds=4)
    flocal = TPUBatchBackend(small_caps(), batch_size=16)
    _, flocal_waves = run_scenario(flocal, fops)

    worker = DeviceWorker().start()
    try:
        remote = RemoteTPUBatchBackend(worker.url, small_caps(),
                                       batch_size=16)
        skewed_ops = inject_before_wave(ops, 2, ("gen_skew",))
        cache, remote_waves = run_scenario_pipelined(remote, skewed_ops,
                                                     depth=2)
        assert remote.stats.get("gen_stale_waves", 0) >= 1
        assert remote_waves == local_waves
        assert_full_reencode_parity(remote, cache)

        # fenced dispatches over the wire: external binds between waves
        fremote = RemoteTPUBatchBackend(worker.url, small_caps(),
                                        batch_size=16)
        fcache, fremote_waves = run_scenario_pipelined(fremote, fops,
                                                       depth=2)
        assert fremote_waves == flocal_waves
        assert fremote.stats.get("fence_replays", 0) >= 1
        assert_full_reencode_parity(fremote, fcache)
    finally:
        worker.stop()


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202, 303])
def test_churn_parity_large_tier(seed):
    """Large tier: hundreds of nodes, long seeded streams, natural
    compaction pressure.  Patched waves must dominate (the tentpole's
    steady state) and the from-scratch oracle must still agree."""
    caps = Caps(n_cap=256, l_cap=128, kl_cap=48, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8)
    ops = build_ops(seed, rounds=10, base_nodes=120, constraint_pods=True)
    backend = TPUBatchBackend(caps, batch_size=16)
    cache, waves = run_scenario(backend, ops)
    assert any(n for w in waves for n, _ in w)
    s = backend.stats
    assert s["event_patches"] > 0
    # steady state keeps the resident tensors: only the first wave may
    # rebuild device state from scratch
    assert s["waves_patched"] >= s["waves_reflattened"]
    assert s["waves_reflattened"] <= 2
    assert_full_reencode_parity(backend, cache)
