"""Churn-storm chaos tier: topology churn flooding the informer mid-wave.

The engagement PR's proving ground — a seeded node add/drain/relabel
storm (ops/faults.py ChurnStormSchedule + NodeStormDriver) runs
CONCURRENTLY with a pod flood on the depth-2 pipelined path, stressing
the backend's row patches, between-wave compaction and pipelined
gen-fence recovery while the on-by-default engagement controller decides
when the overload machinery earns its keep.  A store-watch bind ledger
sits on top asserting the invariants chaos must not break:

  - exactly-once binds: a pod's nodeName, once set, never moves
  - zero lost pods: the closing barrier sees every flood pod bound
  - zero system/high-band sheds, storm or not
  - bounded engagement transitions (hysteresis holds under churn)

Schedule/driver unit tests pin seeded determinism and the one-draw
stream-stability rule so bench.py and this tier replay IDENTICAL storms.
Tier-1 runs the shrunk storm; the full-size workload is also slow.
"""

from __future__ import annotations

import copy
import threading
import time

import pytest

from kubernetes_tpu.client.clientset import LocalClient, NODES, PODS
from kubernetes_tpu.ops.faults import (
    ChurnStormSchedule, NodeStormDriver, NODE_ADD, NODE_DRAIN, NODE_RELABEL,
)
from kubernetes_tpu.perf import caps_for_nodes, load_workloads
from kubernetes_tpu.perf.scheduler_perf import (
    ThroughputCollector, run_workload, setup_cluster,
)
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node

pytestmark = pytest.mark.storm


def _schedule(**kw) -> ChurnStormSchedule:
    base = dict(seed=7, add_rate=0.3, drain_rate=0.2, relabel_rate=0.3)
    base.update(kw)
    return ChurnStormSchedule(**base)


class TestChurnStormSchedule:
    def test_seeded_determinism(self):
        sa, sb = _schedule(), _schedule()
        a = [sa.action(i) for i in range(50)]
        b = [sb.action(i) for i in range(50)]
        assert a == b

    def test_one_draw_stream_stability(self):
        """Scripting a step must not shift the seeded stream around it:
        every step consumes exactly one draw, scripted or not."""
        sp = _schedule()
        plain = [sp.action(i) for i in range(30)]
        scripted = _schedule(script={11: (NODE_DRAIN, 0.5)})
        got = [scripted.action(i) for i in range(30)]
        assert got[11] == (NODE_DRAIN, 0.5)
        assert got[:11] == plain[:11]
        assert got[12:] == plain[12:]

    def test_zero_rates_are_quiet(self):
        s = ChurnStormSchedule(seed=3)
        assert all(s.action(i)[0] == "none" for i in range(20))

    def test_bands_partition_and_fractions_cover(self):
        """Rates partition the unit interval; the victim fraction is the
        draw re-scaled within its band, so it spans [0, 1)."""
        s = _schedule(seed=1, add_rate=0.4, drain_rate=0.3,
                      relabel_rate=0.3)
        seen = {NODE_ADD: [], NODE_DRAIN: [], NODE_RELABEL: []}
        for i in range(3000):
            act, frac = s.action(i)
            assert act in seen  # rates sum to 1.0: never "none"
            assert 0.0 <= frac < 1.0
            seen[act].append(frac)
        for act, fracs in seen.items():
            assert fracs, f"band {act} never drawn"
            assert min(fracs) < 0.1 and max(fracs) > 0.9, \
                f"band {act} fractions don't cover the unit interval"


class TestNodeStormDriver:
    def _cluster(self, n=4):
        store = kv.MemoryStore()
        client = LocalClient(store)
        names = []
        for i in range(n):
            name = f"base-{i}"
            client.create(NODES, make_node(name)
                          .capacity(cpu="8", mem="32Gi").build())
            names.append(name)
        return store, client, names

    def test_adds_create_schedulable_nodes(self):
        store, client, names = self._cluster()
        drv = NodeStormDriver(client, _schedule(
            add_rate=1.0, drain_rate=0.0, relabel_rate=0.0),
            names, rack_labels=3)
        for _ in range(5):
            assert drv.step()[0] == NODE_ADD
        items, _ = client.list(NODES, "")
        assert len(items) == 4 + 5
        added = {o["metadata"]["name"]: o for o in items
                 if o["metadata"]["name"].startswith("storm-")}
        assert set(added) == {f"storm-{i}" for i in range(5)}
        for o in added.values():
            assert o["metadata"]["labels"]["ktpu.io/rack"] in "012"
        assert drv.injected[NODE_ADD] == 5

    def test_drains_respect_min_nodes_floor(self):
        store, client, names = self._cluster(n=4)
        drv = NodeStormDriver(client, _schedule(
            add_rate=0.0, drain_rate=1.0, relabel_rate=0.0),
            names, min_nodes=2)
        results = [drv.step() for _ in range(10)]
        applied = [r for r in results if r is not None]
        assert len(applied) == 2  # 4 nodes, floor 2: only 2 drains land
        items, _ = client.list(NODES, "")
        assert len(items) == 2
        assert drv.injected[NODE_DRAIN] == 2
        # refused steps still consumed a draw (stream stability)
        assert drv.steps == 10

    def test_adds_respect_max_nodes_ceiling(self):
        """Unbounded adds would grow the cluster past the backend's
        tensor caps and stall every wave; the ceiling refuses them."""
        store, client, names = self._cluster(n=4)
        drv = NodeStormDriver(client, _schedule(
            add_rate=1.0, drain_rate=0.0, relabel_rate=0.0),
            names, max_nodes=6)
        results = [drv.step() for _ in range(10)]
        assert sum(1 for r in results if r is not None) == 2
        items, _ = client.list(NODES, "")
        assert len(items) == 6
        assert drv.steps == 10  # refusals still consume draws

    def test_relabels_bump_epoch_via_guaranteed_update(self):
        store, client, names = self._cluster(n=3)
        drv = NodeStormDriver(client, _schedule(
            add_rate=0.0, drain_rate=0.0, relabel_rate=1.0), names)
        applied = [drv.step() for _ in range(6)]
        assert all(r is not None and r[0] == NODE_RELABEL
                   for r in applied)
        items, _ = client.list(NODES, "")
        bumped = [o for o in items if "ktpu.io/storm-epoch"
                  in o["metadata"].get("labels", {})]
        assert bumped, "no node carries the storm epoch label"
        assert drv.injected[NODE_RELABEL] == 6
        # log records (step, action, victim) for deterministic replay
        assert [e[0] for e in drv.log] == list(range(6))

    def test_drain_victims_tracked_not_redrained(self):
        """The driver's live-name view shrinks with each drain; a later
        drain never targets an already-deleted node (which would be a
        silent no-op masquerading as churn)."""
        store, client, names = self._cluster(n=6)
        drv = NodeStormDriver(client, _schedule(
            add_rate=0.4, drain_rate=0.6, relabel_rate=0.0),
            names, min_nodes=1)
        for _ in range(40):
            drv.step()
        drained = [n for (_, a, n) in drv.log if a == NODE_DRAIN]
        assert len(drained) == len(set(drained))


class TestGhostNodeRace:
    """The storm-tier bug this PR's chaos runs caught: the zero-copy
    cache view shares LIVE NodeInfos with the tensors, and
    Cache.remove_node nulls .node IN PLACE when a drained node still
    holds pods — a wave resolving across that mutation used to read
    NodeInfo.name == "" and bind its pods to an empty nodeName, which
    every reader treats as "unbound".  The pods were silently lost
    (condition PodScheduled=True, no nodeName, absent from every queue
    tier).  Dispatch now snapshots the tensors' row_names (strings) and
    the store refuses empty-node binds outright."""

    def _tensors_with_node(self):
        from kubernetes_tpu.ops.flatten import Caps, ClusterTensors
        from kubernetes_tpu.scheduler.cache import Cache

        cache = Cache()
        node = make_node("churn-0").capacity(cpu="32", mem="128Gi").build()
        cache.add_node(node)
        pod = {"metadata": {"name": "rider", "namespace": "default"},
               "spec": {"nodeName": "churn-0",
                        "containers": [{"name": "c", "resources": {
                            "requests": {"cpu": "1"}}}]}}
        cache.add_pod(pod)  # a resident pod keeps the NodeInfo on drain
        caps = Caps(n_cap=8, l_cap=16, kl_cap=8, t_cap=4, pt_cap=4,
                    s_cap=2, sg_cap=4, asg_cap=4, c_cap=2)
        t = ClusterTensors(caps)
        t.update_from_snapshot_tracked(cache.flatten_view())
        return cache, node, t

    def test_row_names_survive_inplace_node_removal(self):
        """The dispatch-time row_names snapshot must keep resolving the
        registration-time name after the cache nulls the shared
        NodeInfo's .node mid-wave."""
        import numpy as np

        from kubernetes_tpu.ops.backend import decode_results

        cache, node, t = self._tensors_with_node()
        row = t.row_of["churn-0"]
        assert t.row_names[row] == "churn-0"
        row_names = list(t.row_names)  # what dispatch captures
        live_ni = t.node_infos[row]
        cache.remove_node(node)  # resident pod -> in-place .node = None
        assert live_ni.node is None and live_ni.name == "", \
            "hazard precondition changed: cache no longer nulls in place"
        out = decode_results(np.asarray([row]), 1, 8, set(),
                             row_names, "no fit")
        assert out == [("churn-0", None)]

    def test_decode_refuses_unnamed_rows(self):
        """A free/tombstoned row in the captured view decodes to a loud
        ERROR (requeue), never a falsy node name."""
        import numpy as np

        from kubernetes_tpu.ops.backend import decode_results

        for ghost in (None, ""):
            out = decode_results(np.asarray([3]), 1, 8, set(),
                                 [None, None, None, ghost], "no fit")
            (name, status), = out
            assert name is None
            assert status is not None and not status.is_success()
            assert "no node name" in status.message()

    def test_store_refuses_empty_node_bind(self):
        """Belt-and-suspenders: a bind carrying an empty nodeName is
        refused at the store, leaving the pod untouched (no phantom
        PodScheduled condition)."""
        store = kv.MemoryStore()
        client = LocalClient(store)
        client.create(PODS, {"metadata": {"name": "p0",
                                          "namespace": "default"},
                             "spec": {}})
        (obj, err), = client.bind_many([("default", "p0", "")])
        assert obj is None and isinstance(err, kv.StoreError)
        with pytest.raises(kv.StoreError):
            client.bind({"metadata": {"name": "p0",
                                      "namespace": "default"}}, "")
        cur = store.get(PODS, "default", "p0")
        assert "nodeName" not in cur["spec"]
        assert not (cur.get("status") or {}).get("conditions")


class BindLedger:
    """Store-watch exactly-once ledger: replays the pods watch stream and
    flags any pod whose nodeName, once set, changes to a different node —
    the double-bind a gen-fence failure or a stale-row patch would
    produce under topology churn.  Drained once after the run (the store
    watch buffers unboundedly)."""

    def __init__(self, store: kv.MemoryStore):
        self._watch = store.watch(PODS)
        self.bound: dict[str, str] = {}
        self.rebinds: list[tuple[str, str, str]] = []

    def drain(self) -> None:
        while True:
            evs = self._watch.next_batch(timeout=0.0)
            if not evs:
                break
            for ev in evs:
                o = ev.object
                md = o["metadata"]
                k = f"{md.get('namespace', '')}/{md['name']}"
                if ev.type == kv.DELETED:
                    self.bound.pop(k, None)
                    continue
                node = (o.get("spec") or {}).get("nodeName")
                if not node:
                    continue
                prev = self.bound.get(k)
                if prev is None:
                    self.bound[k] = node
                elif prev != node:
                    self.rebinds.append((k, prev, node))

    def stop(self) -> None:
        self._watch.stop()


def _shrunk_storm(nodes: int, pods: int, timeout: float = 180.0) -> dict:
    cfg = copy.deepcopy(load_workloads()["SchedulingChurnStorm"])
    for op in cfg["workloadTemplate"]:
        if op["opcode"] == "createNodes":
            op["count"] = nodes
            op["rackLabels"] = min(op.get("rackLabels", 0), nodes)
        elif op["opcode"] == "createPods":
            if op.get("collectMetrics"):
                op["count"] = max(8, pods)
                # pace the flood over a couple of seconds so the storm
                # genuinely overlaps in-flight waves (a full-backlog
                # dump binds before the first drain lands)
                op["ratePerSecond"] = max(100, pods // 3)
            else:
                op["count"] = max(8, pods // 20)
        elif op["opcode"] == "barrier":
            op["timeout"] = timeout
        elif op["opcode"] == "nodeStorm":
            op["minNodes"] = max(2, nodes // 2)
            op["intervalMilliseconds"] = 10
    return cfg


def _run_storm(nodes: int, pods: int, timeout: float = 180.0):
    """Shared e2e body: shrunk SchedulingChurnStorm on the depth-2
    pipelined TPU path with the DEFAULT (auto-engagement) overload
    policy, a bind ledger on the store, and the storm stats returned for
    assertions."""
    from kubernetes_tpu.scheduler.config import OverloadPolicy

    cfg = _shrunk_storm(nodes, pods, timeout)
    # the REAL tensor backend (not null_device): the storm's value is
    # driving row patches / compaction / gen fences, which only the
    # resident-mirror backend carries; jax runs them on CPU here
    cluster = setup_cluster(tpu=True, caps=caps_for_nodes(nodes + 64),
                            batch_size=64, pipeline_depth=2,
                            overload=OverloadPolicy())
    ledger = BindLedger(cluster.store)
    collector = ThroughputCollector(cluster.store)
    try:
        stats = run_workload(cluster, cfg["workloadTemplate"], collector)
        collector.stop()
        ledger.drain()
        sched = cluster.scheduler
        sched.expose_metrics()
        prom = sched.metrics.prom
        stats["sheds"] = dict(prom.queue_shed_total.values())
        stats["transitions"] = dict(
            prom.overload_transition_total.values())
        stats["engagement"] = sched.overload_engagement
        stats["max_active"] = sched.queue.stats()["active"]
        for p in sched.profiles.values():
            if p.batch_backend is not None:
                stats["backend_stats"] = dict(p.batch_backend.stats)
                maint = getattr(p.batch_backend, "maintenance_snapshot",
                                None)
                if maint is not None:
                    stats["tensor_maintenance"] = maint()
                break
        return stats, ledger
    finally:
        ledger.stop()
        cluster.shutdown()


def _assert_invariants(stats, ledger, pods: int):
    assert stats.get("barrier_ok"), \
        f"lost pods: flood never fully bound ({stats})"
    assert not ledger.rebinds, \
        f"exactly-once violated: {ledger.rebinds[:5]}"
    # the barrier proved every flood pod bound; the ledger saw them all
    assert len(ledger.bound) >= pods
    for (reason, band), n in stats["sheds"].items():
        assert band not in ("system", "high"), \
            f"shed {n} {band} pods (reason={reason})"
    # hysteresis holds under oscillating churn: the controller may
    # engage and disengage, but it must not flap per-wave
    assert sum(stats["transitions"].values()) <= 12, stats["transitions"]
    # topology churn reached the backend: the storm applied real
    # adds/drains/relabels and the maintenance path saw node events
    storm = stats["storm"]
    assert storm["injected"][NODE_ADD] > 0
    assert storm["injected"][NODE_DRAIN] > 0
    assert storm["injected"][NODE_RELABEL] > 0
    maint = stats.get("tensor_maintenance")
    if maint is not None:
        # gen-fence recovery observables exist and never went negative;
        # patched/reflattened wave counts account for the churn
        assert maint["gen_stale_waves"] >= 0
        assert maint["waves_patched"] + maint["waves_reflattened"] > 0


class TestChurnStormE2E:
    def test_shrunk_storm_depth2(self):
        """Tier-1: the shrunk storm over the depth-2 pipelined path with
        the DEFAULT config (engagement auto, on by default)."""
        stats, ledger = _run_storm(nodes=24, pods=600)
        _assert_invariants(stats, ledger, 600)

    @pytest.mark.slow
    def test_full_storm_depth2(self):
        """The full-tier storm: closer to the YAML's published shape."""
        stats, ledger = _run_storm(nodes=120, pods=6000, timeout=420.0)
        _assert_invariants(stats, ledger, 6000)
        # at this scale the drain/relabel pressure must actually exercise
        # the gen-fence / patch machinery, not just coexist with it
        maint = stats.get("tensor_maintenance")
        assert maint is not None
        assert maint["event_patches"] > 0
