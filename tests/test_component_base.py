"""component-base: metrics registry, featuregate, tracing, logs, configz.

Reference contracts: staging/src/k8s.io/component-base/{metrics,featuregate,
logs,tracing}, pkg/scheduler/metrics/metrics.go.
"""

import logging

import pytest

from kubernetes_tpu.component_base import configz, featuregate, logs, metrics, tracing


# -- metrics ---------------------------------------------------------------

def test_counter_inc_and_expose():
    r = metrics.Registry()
    c = metrics.new_counter("sched_attempts_total", "attempts",
                            labels=("result",), registry=r)
    c.inc(1.0, "scheduled")
    c.inc(2.0, "error")
    c.labels("scheduled").inc()
    assert c.value("scheduled") == 2.0
    text = r.expose()
    assert 'sched_attempts_total{result="scheduled"} 2' in text
    assert 'sched_attempts_total{result="error"} 2' in text
    assert "# TYPE sched_attempts_total counter" in text


def test_counter_cannot_decrease():
    c = metrics.Counter("c")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    r = metrics.Registry()
    g = metrics.new_gauge("pending_pods", "", labels=("queue",), registry=r)
    g.set(5, "active")
    g.inc(2, "active")
    g.dec(1, "active")
    assert g.value("active") == 6
    assert 'pending_pods{queue="active"} 6' in r.expose()


def test_histogram_buckets_sum_count_quantile():
    h = metrics.Histogram("lat", buckets=[0.001, 0.01, 0.1, 1.0])
    for v in (0.0005, 0.005, 0.05, 0.5):
        h.observe(v)
    assert h.count() == 4
    assert abs(h.sum() - 0.5555) < 1e-9
    # median falls in the 0.01 bucket (2 of 4 observations <= 0.01)
    assert h.quantile(0.5) == 0.01
    text = "\n".join(h.collect())
    assert 'lat_bucket{le="0.001"} 1' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text


def test_histogram_labeled_series_independent():
    h = metrics.Histogram("d", labels=("point",), buckets=[1, 10])
    h.observe(0.5, "PreFilter")
    h.labels("Score").observe(5)
    assert h.count("PreFilter") == 1
    assert h.count("Score") == 1
    assert h.count("Bind") == 0


def test_exponential_buckets_match_reference():
    # metrics.go:58 ExponentialBuckets(0.001, 2, 15)
    b = metrics.exponential_buckets(0.001, 2, 15)
    assert len(b) == 15
    assert b[0] == 0.001
    assert abs(b[-1] - 16.384) < 1e-9


def test_registry_duplicate_registration_fails():
    r = metrics.Registry()
    r.register(metrics.Counter("x"))
    with pytest.raises(ValueError):
        r.register(metrics.Counter("x"))


def test_hidden_metric_skipped_in_exposition_but_writable():
    r = metrics.Registry()
    c = metrics.new_counter("old_metric", registry=r,
                            deprecated_version="1.24")
    c.hidden = True
    c.inc()
    assert "old_metric" not in r.expose()
    assert c.value() == 1.0


def test_stability_level_in_help():
    r = metrics.Registry()
    metrics.new_counter("s", "help text", registry=r,
                        stability=metrics.STABLE)
    assert "# HELP s [STABLE] help text" in r.expose()


# -- featuregate -----------------------------------------------------------

def test_featuregate_defaults_and_set():
    fg = featuregate.FeatureGate().add({
        "Alpha1": featuregate.FeatureSpec(False, featuregate.ALPHA),
        "Beta1": featuregate.FeatureSpec(True, featuregate.BETA),
    })
    assert not fg.enabled("Alpha1")
    assert fg.enabled("Beta1")
    fg.set("Alpha1=true,Beta1=false")
    assert fg.enabled("Alpha1")
    assert not fg.enabled("Beta1")


def test_featuregate_unknown_gate_errors():
    fg = featuregate.FeatureGate()
    with pytest.raises(ValueError):
        fg.set_from_map({"Nope": True})
    with pytest.raises(ValueError):
        fg.enabled("Nope")


def test_featuregate_locked_ga_gate():
    fg = featuregate.FeatureGate().add({
        "GA1": featuregate.FeatureSpec(True, featuregate.GA,
                                       lock_to_default=True)})
    with pytest.raises(ValueError):
        fg.set_from_map({"GA1": False})
    fg.set_from_map({"GA1": True})  # default value is fine


def test_default_feature_catalogue():
    fg = featuregate.default_feature_gate.deep_copy()
    assert fg.enabled("TPUBatchAssign")
    assert fg.enabled("ServerSideApply")
    fg.set("TPUBatchAssign=false")
    assert not fg.enabled("TPUBatchAssign")
    # the shared default gate is unaffected by the copy
    assert featuregate.default_feature_gate.enabled("TPUBatchAssign")


# -- tracing ---------------------------------------------------------------

def test_utiltrace_logs_only_over_threshold(caplog):
    tr = tracing.Trace("scheduleOne", pod="default/p")
    tr.step("snapshot")
    tr.step("filter")
    with caplog.at_level(logging.INFO, logger="kubernetes_tpu.component_base.tracing"):
        assert not tr.log_if_long(60.0)      # fast op: silent
        assert tr.log_if_long(0.0)           # threshold 0: logged
    assert "scheduleOne" in caplog.text
    assert "filter" in caplog.text


def test_span_provider_records():
    tp = tracing.TracerProvider()
    tracer = tp.tracer("apiserver")
    with tracer.start_span("HTTP POST /api/v1/pods") as span:
        span.set_attribute("code", 201)
        span.add_event("admission done")
    spans = tp.snapshot()
    assert len(spans) == 1
    assert spans[0].attributes["code"] == 201
    assert spans[0].duration >= 0


def test_span_provider_sampling_off():
    tp = tracing.TracerProvider(sampling_rate_per_million=0)
    with tp.tracer("t").start_span("s"):
        pass
    assert tp.snapshot() == []


# -- logs ------------------------------------------------------------------

def test_structured_text_and_verbosity(caplog):
    logger = logging.getLogger("test.logs")
    logs.set_format("text")
    logs.set_verbosity(4)
    try:
        with caplog.at_level(logging.INFO):
            logs.info_s(logger, "Scheduled pod", pod="ns/p", node="n1")
            logs.v(10).info_s(logger, "super verbose dump")
        assert 'Scheduled pod pod="ns/p" node="n1"' in caplog.text
        assert "super verbose dump" not in caplog.text
        assert logs.enabled(4) and not logs.enabled(5)
    finally:
        logs.set_verbosity(0)


def test_json_log_format(caplog):
    logger = logging.getLogger("test.logs.json")
    logs.set_format("json")
    try:
        with caplog.at_level(logging.ERROR):
            logs.error_s(logger, RuntimeError("boom"), "bind failed", pod="a/b")
        assert '"msg": "bind failed"' in caplog.text
        assert '"err": "boom"' in caplog.text
    finally:
        logs.set_format("text")


# -- configz ---------------------------------------------------------------

def test_configz_registry():
    r = configz.Registry()
    r.install("kubescheduler.config.k8s.io", {"parallelism": 16})
    assert r.snapshot() == {"kubescheduler.config.k8s.io": {"parallelism": 16}}
    r.delete("kubescheduler.config.k8s.io")
    assert r.snapshot() == {}


# -- scheduler metrics bundle ---------------------------------------------

def test_scheduler_metrics_bundle_exposition():
    from kubernetes_tpu.scheduler.metrics import Metrics
    m = Metrics()
    m.schedule_attempts.inc(1.0, "scheduled", "default-scheduler")
    m.framework_extension_point_duration.observe(
        0.002, "PreFilter", "Success", "default-scheduler")
    m.pending_pods.set(3, "active")
    text = m.expose()
    assert ('scheduler_schedule_attempts_total{result="scheduled",'
            'profile="default-scheduler"} 1') in text
    assert "scheduler_framework_extension_point_duration_seconds_bucket" in text
    assert 'scheduler_pending_pods{queue="active"} 3' in text
