"""Controller tests: ReplicaSet/Deployment/Job reconcile loops, garbage
collection, leader election. Mirrors pkg/controller/*/..._test.go reduced
to the behavioral contracts."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import (
    DEPLOYMENTS, JOBS, LEASES, PODS, REPLICASETS,
)
from kubernetes_tpu.client.leaderelection import LeaderElector
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    mgr = ControllerManager(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    mgr.run()
    yield store, client
    mgr.stop()
    factory.stop()


def make_rs(name, replicas, labels=None, ns="default"):
    labels = labels or {"app": name}
    rs = meta.new_object("ReplicaSet", name, ns)
    rs["spec"] = {
        "replicas": replicas,
        "selector": {"matchLabels": labels},
        "template": {"metadata": {"labels": dict(labels)},
                     "spec": {"containers": [{"name": "c0", "image": "img"}]}},
    }
    return rs


def make_deployment(name, replicas, image="img:v1", ns="default"):
    dep = meta.new_object("Deployment", name, ns)
    dep["spec"] = {
        "replicas": replicas,
        "selector": {"matchLabels": {"app": name}},
        "template": {"metadata": {"labels": {"app": name}},
                     "spec": {"containers": [{"name": "c0", "image": image}]}},
    }
    return dep


def pods_of(client, ns="default"):
    return client.list(PODS, ns)[0]


class TestReplicaSet:
    def test_scales_up(self, cluster):
        store, client = cluster
        client.create(REPLICASETS, make_rs("web", 3))
        assert wait_for(lambda: len(pods_of(client)) == 3)
        for p in pods_of(client):
            ref = meta.controller_ref(p)
            assert ref["kind"] == "ReplicaSet" and ref["name"] == "web"

    def test_scales_down(self, cluster):
        store, client = cluster
        client.create(REPLICASETS, make_rs("web", 3))
        assert wait_for(lambda: len(pods_of(client)) == 3)
        client.guaranteed_update(REPLICASETS, "default", "web",
                                 lambda o: {**o, "spec": {**o["spec"], "replicas": 1}})
        assert wait_for(lambda: len(pods_of(client)) == 1)

    def test_replaces_deleted_pod(self, cluster):
        store, client = cluster
        client.create(REPLICASETS, make_rs("web", 2))
        assert wait_for(lambda: len(pods_of(client)) == 2)
        victim = pods_of(client)[0]
        client.delete(PODS, "default", meta.name(victim))
        assert wait_for(lambda: len(pods_of(client)) == 2)

    def test_status_updated(self, cluster):
        store, client = cluster
        client.create(REPLICASETS, make_rs("web", 2))
        assert wait_for(lambda: (client.get(REPLICASETS, "default", "web")
                                 .get("status") or {}).get("replicas") == 2)


class TestDeployment:
    def test_creates_rs_and_pods(self, cluster):
        store, client = cluster
        client.create(DEPLOYMENTS, make_deployment("api", 2))
        assert wait_for(lambda: len(client.list(REPLICASETS, "default")[0]) == 1)
        assert wait_for(lambda: len(pods_of(client)) == 2)

    def test_rolling_update_creates_new_rs(self, cluster):
        store, client = cluster
        client.create(DEPLOYMENTS, make_deployment("api", 2, image="img:v1"))
        assert wait_for(lambda: len(pods_of(client)) == 2)

        def set_image(o):
            o["spec"]["template"]["spec"]["containers"][0]["image"] = "img:v2"
            return o
        client.guaranteed_update(DEPLOYMENTS, "default", "api", set_image)
        assert wait_for(lambda: len(client.list(REPLICASETS, "default")[0]) == 2)
        # v2 pods get created (old ones drain once new are Ready; without a
        # kubelet nothing reports Ready, so we just assert the surge)
        def v2_pods():
            return [p for p in pods_of(client)
                    if p["spec"]["containers"][0]["image"] == "img:v2"]
        assert wait_for(lambda: len(v2_pods()) == 2)

    def test_cascading_delete_via_gc(self, cluster):
        store, client = cluster
        client.create(DEPLOYMENTS, make_deployment("api", 2))
        assert wait_for(lambda: len(pods_of(client)) == 2)
        client.delete(DEPLOYMENTS, "default", "api")
        assert wait_for(lambda: len(client.list(REPLICASETS, "default")[0]) == 0,
                        timeout=15)
        assert wait_for(lambda: len(pods_of(client)) == 0, timeout=15)


class TestJob:
    def test_runs_to_completion(self, cluster):
        store, client = cluster
        job = meta.new_object("Job", "batch1", "default")
        job["spec"] = {"completions": 2, "parallelism": 2,
                       "template": {"spec": {"containers": [
                           {"name": "c0", "image": "worker"}]}}}
        client.create(JOBS, job)
        assert wait_for(lambda: len(pods_of(client)) == 2)
        # simulate kubelet finishing the pods
        for p in pods_of(client):
            client.update_status(PODS, {**p, "status": {"phase": "Succeeded"}})
        assert wait_for(lambda: any(
            c.get("type") == "Complete"
            for c in (client.get(JOBS, "default", "batch1")
                      .get("status") or {}).get("conditions", [])), timeout=15)

    def test_failed_pods_retried_and_backoff_limit(self, cluster):
        store, client = cluster
        job = meta.new_object("Job", "flaky", "default")
        job["spec"] = {"completions": 1, "parallelism": 1, "backoffLimit": 1,
                       "template": {"spec": {"containers": [
                           {"name": "c0", "image": "worker"}]}}}
        client.create(JOBS, job)

        def fail_active():
            for p in pods_of(client):
                if (meta.controller_ref(p) or {}).get("name") == "flaky" \
                        and (p.get("status") or {}).get("phase") not in (
                            "Succeeded", "Failed"):
                    client.update_status(PODS, {**p, "status": {"phase": "Failed"}})
                    return True
            return False

        assert wait_for(fail_active)           # first failure
        assert wait_for(fail_active, timeout=15)  # retry also fails
        assert wait_for(lambda: any(
            c.get("type") == "Failed"
            for c in (client.get(JOBS, "default", "flaky")
                      .get("status") or {}).get("conditions", [])), timeout=15)


class TestLeaderElection:
    def test_single_leader_and_failover(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        a = LeaderElector(client, "test-lock", identity="a",
                          lease_duration=0.6, retry_period=0.1)
        b = LeaderElector(client, "test-lock", identity="b",
                          lease_duration=0.6, retry_period=0.1)
        a.run()
        assert wait_for(lambda: a.is_leader)
        b.run()
        time.sleep(0.5)
        assert not b.is_leader
        a.stop()  # releases the lease
        assert wait_for(lambda: b.is_leader, timeout=5)
        b.stop()
