"""Controller breadth: StatefulSet, DaemonSet, CronJob, Disruption,
Namespace, ResourceQuota, ServiceAccount, PodGC, TTLAfterFinished, HPA.

Behavioral contracts from pkg/controller/{statefulset,daemon,cronjob,
disruption,namespace,resourcequota,serviceaccount,podgc,ttlafterfinished,
podautoscaler}.
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import (
    CRONJOBS, DAEMONSETS, HPAS, JOBS, NAMESPACES, NODES, PDBS, PODS, PVCS,
    REPLICASETS, RESOURCEQUOTAS, SECRETS, SERVICEACCOUNTS, STATEFULSETS,
)
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.cronjob import CronSchedule
from kubernetes_tpu.controllers.hpa import USAGE_ANNOTATION
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    mgr = ControllerManager(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    mgr.run()
    yield store, client, mgr
    mgr.stop()
    factory.stop()


def pods_of(client, ns="default"):
    return client.list(PODS, ns)[0]


def set_phase(client, pod, phase):
    client.update_status(PODS, {**pod, "status": {"phase": phase}})


def mark_ready(client, pod):
    client.update_status(PODS, {**pod, "status": {
        "phase": "Running",
        "conditions": [{"type": "Ready", "status": "True"}]}})


# -- StatefulSet -----------------------------------------------------------

def make_sts(name, replicas, policy=None, vcts=None):
    sts = meta.new_object("StatefulSet", name, "default")
    sts["spec"] = {
        "replicas": replicas, "serviceName": name,
        "selector": {"matchLabels": {"app": name}},
        "template": {"metadata": {"labels": {"app": name}},
                     "spec": {"containers": [{"name": "c0", "image": "i"}]}},
    }
    if policy:
        sts["spec"]["podManagementPolicy"] = policy
    if vcts:
        sts["spec"]["volumeClaimTemplates"] = vcts
    return sts


class TestStatefulSet:
    def test_ordered_creation_with_stable_names(self, cluster):
        store, client, _ = cluster
        client.create(STATEFULSETS, make_sts("db", 3))
        # ordinal 0 first; 1 only after 0 is ready
        assert wait_for(lambda: any(meta.name(p) == "db-0"
                                    for p in pods_of(client)))
        time.sleep(0.3)
        assert not any(meta.name(p) == "db-1" for p in pods_of(client))
        mark_ready(client, client.get(PODS, "default", "db-0"))
        assert wait_for(lambda: any(meta.name(p) == "db-1"
                                    for p in pods_of(client)))
        mark_ready(client, client.get(PODS, "default", "db-1"))
        assert wait_for(lambda: any(meta.name(p) == "db-2"
                                    for p in pods_of(client)))

    def test_parallel_policy_creates_all(self, cluster):
        store, client, _ = cluster
        client.create(STATEFULSETS, make_sts("par", 3, policy="Parallel"))
        assert wait_for(lambda: {meta.name(p) for p in pods_of(client)}
                        >= {"par-0", "par-1", "par-2"})

    def test_scale_down_highest_ordinal_first(self, cluster):
        store, client, _ = cluster
        client.create(STATEFULSETS, make_sts("sd", 2, policy="Parallel"))
        assert wait_for(lambda: len(pods_of(client)) == 2)
        for p in pods_of(client):
            mark_ready(client, p)

        def scale(o):
            o["spec"]["replicas"] = 1
            return o
        client.guaranteed_update(STATEFULSETS, "default", "sd", scale)
        assert wait_for(lambda: [meta.name(p) for p in pods_of(client)
                                 if meta.deletion_timestamp(p) is None]
                        == ["sd-0"])

    def test_pvc_per_volume_claim_template(self, cluster):
        store, client, _ = cluster
        vct = [{"metadata": {"name": "data"},
                "spec": {"resources": {"requests": {"storage": "1Gi"}}}}]
        client.create(STATEFULSETS, make_sts("pv", 1, vcts=vct))
        assert wait_for(lambda: any(
            meta.name(c) == "data-pv-0" for c in client.list(PVCS,
                                                             "default")[0]))
        pod = client.get(PODS, "default", "pv-0")
        assert pod["spec"]["volumes"][0]["persistentVolumeClaim"][
            "claimName"] == "data-pv-0"


# -- DaemonSet -------------------------------------------------------------

def make_node(name, labels=None, taints=None):
    node = meta.new_object("Node", name, "")
    node["metadata"]["labels"] = labels or {}
    node["spec"] = {"taints": taints or []}
    node["status"] = {"allocatable": {"cpu": "4", "memory": "8Gi",
                                      "pods": "110"}}
    return node


class TestDaemonSet:
    def test_pod_per_node(self, cluster):
        store, client, _ = cluster
        for i in range(3):
            client.create(NODES, make_node(f"n{i}"))
        ds = meta.new_object("DaemonSet", "agent", "default")
        ds["spec"] = {"template": {
            "metadata": {"labels": {"app": "agent"}},
            "spec": {"containers": [{"name": "c0", "image": "i"}]}}}
        client.create(DAEMONSETS, ds)
        assert wait_for(lambda: len(pods_of(client)) == 3)
        # a new node gets a pod too
        client.create(NODES, make_node("n3"))
        assert wait_for(lambda: len(pods_of(client)) == 4)

    def test_node_selector_respected(self, cluster):
        store, client, _ = cluster
        client.create(NODES, make_node("gpu-1", labels={"accel": "tpu"}))
        client.create(NODES, make_node("cpu-1"))
        ds = meta.new_object("DaemonSet", "tpud", "default")
        ds["spec"] = {"template": {
            "metadata": {"labels": {"app": "tpud"}},
            "spec": {"nodeSelector": {"accel": "tpu"},
                     "containers": [{"name": "c0", "image": "i"}]}}}
        client.create(DAEMONSETS, ds)
        assert wait_for(lambda: len(pods_of(client)) == 1)
        time.sleep(0.2)
        assert len(pods_of(client)) == 1

    def test_untolerated_taint_excludes_node(self, cluster):
        store, client, _ = cluster
        client.create(NODES, make_node("ok"))
        client.create(NODES, make_node(
            "tainted", taints=[{"key": "dedicated", "value": "x",
                                "effect": "NoSchedule"}]))
        ds = meta.new_object("DaemonSet", "d", "default")
        ds["spec"] = {"template": {
            "metadata": {"labels": {"app": "d"}},
            "spec": {"containers": [{"name": "c0", "image": "i"}]}}}
        client.create(DAEMONSETS, ds)
        assert wait_for(lambda: len(pods_of(client)) == 1)
        status = client.get(DAEMONSETS, "default", "d").get("status") or {}
        assert status.get("desiredNumberScheduled") == 1


# -- CronJob ---------------------------------------------------------------

class TestCronSchedule:
    def test_every_minute(self):
        s = CronSchedule("* * * * *")
        assert s.matches(time.localtime())

    def test_specific_minute(self):
        s = CronSchedule("30 14 * * *")
        t = time.struct_time((2026, 7, 29, 14, 30, 0, 2, 210, -1))
        assert s.matches(t)
        t2 = time.struct_time((2026, 7, 29, 14, 31, 0, 2, 210, -1))
        assert not s.matches(t2)

    def test_step_and_range(self):
        s = CronSchedule("*/15 9-17 * * 1-5")
        assert 0 in s.minutes and 45 in s.minutes and 20 not in s.minutes
        assert 9 in s.hours and 17 in s.hours and 8 not in s.hours
        assert 1 in s.dow and 5 in s.dow and 0 not in s.dow

    def test_next_after(self):
        s = CronSchedule("0 * * * *")  # top of every hour
        nxt = s.next_after(0.0)
        assert nxt is not None and nxt % 3600 == 0

    def test_range_step_anchors_at_range_start(self):
        # vixie cron: 1-23/2 selects the odd hours, not the even ones
        s = CronSchedule("0 1-23/2 * * *")
        assert 1 in s.hours and 23 in s.hours
        assert 2 not in s.hours and 0 not in s.hours

    def test_invalid_rejected(self):
        from kubernetes_tpu.controllers.cronjob import CronParseError
        with pytest.raises(CronParseError):
            CronSchedule("99 * * * *")
        with pytest.raises(CronParseError):
            CronSchedule("* * *")


class TestCronJob:
    def test_creates_job_when_due(self, cluster):
        store, client, mgr = cluster
        cj = meta.new_object("CronJob", "tick", "default")
        cj["spec"] = {"schedule": "* * * * *",
                      "jobTemplate": {"spec": {
                          "completions": 1,
                          "template": {"spec": {"containers": [
                              {"name": "c0", "image": "i"}]}}}}}
        client.create(CRONJOBS, cj)
        ctrl = mgr.controllers["cronjob"]
        # drive deterministically instead of waiting a wall minute
        wait_for(lambda: ctrl.cj_informer.get("default", "tick") is not None)
        ctrl.reconcile_once(time.time() + 60)
        jobs, _ = client.list(JOBS, "default")
        assert len(jobs) == 1
        assert meta.name(jobs[0]).startswith("tick-")
        # same tick is idempotent
        ctrl.reconcile_once(time.time() + 61)
        assert len(client.list(JOBS, "default")[0]) == 1

    def test_forbid_concurrency(self, cluster):
        store, client, mgr = cluster
        cj = meta.new_object("CronJob", "fb", "default")
        cj["spec"] = {"schedule": "* * * * *",
                      "concurrencyPolicy": "Forbid",
                      "jobTemplate": {"spec": {
                          "template": {"spec": {"containers": [
                              {"name": "c0", "image": "i"}]}}}}}
        client.create(CRONJOBS, cj)
        ctrl = mgr.controllers["cronjob"]
        wait_for(lambda: ctrl.cj_informer.get("default", "fb") is not None)
        now = time.time()
        ctrl.reconcile_once(now + 60)
        assert wait_for(
            lambda: len([j for j in ctrl.job_informer.list("default")]) == 1)
        ctrl.reconcile_once(now + 120)  # previous job still active
        assert len(client.list(JOBS, "default")[0]) == 1

    def test_suspend(self, cluster):
        store, client, mgr = cluster
        cj = meta.new_object("CronJob", "sus", "default")
        cj["spec"] = {"schedule": "* * * * *", "suspend": True,
                      "jobTemplate": {"spec": {}}}
        client.create(CRONJOBS, cj)
        ctrl = mgr.controllers["cronjob"]
        wait_for(lambda: ctrl.cj_informer.get("default", "sus") is not None)
        ctrl.reconcile_once(time.time() + 60)
        assert client.list(JOBS, "default")[0] == []


# -- Disruption ------------------------------------------------------------

class TestDisruption:
    def test_pdb_status_maintained(self, cluster):
        store, client, _ = cluster
        for i in range(3):
            p = meta.new_object("Pod", f"w{i}", "default")
            p["metadata"]["labels"] = {"app": "web"}
            p["spec"] = {"containers": [{"name": "c", "image": "i"}]}
            mark_ready(client, client.create(PODS, p))
        pdb = meta.new_object("PodDisruptionBudget", "pdb", "default")
        pdb["spec"] = {"minAvailable": 2,
                       "selector": {"matchLabels": {"app": "web"}}}
        client.create(PDBS, pdb)
        assert wait_for(lambda: (client.get(PDBS, "default", "pdb")
                                 .get("status") or {})
                        .get("disruptionsAllowed") == 1)
        st = client.get(PDBS, "default", "pdb")["status"]
        assert st["currentHealthy"] == 3 and st["desiredHealthy"] == 2

    def test_allowed_drops_after_pod_failure(self, cluster):
        store, client, _ = cluster
        for i in range(2):
            p = meta.new_object("Pod", f"x{i}", "default")
            p["metadata"]["labels"] = {"app": "x"}
            p["spec"] = {"containers": [{"name": "c", "image": "i"}]}
            mark_ready(client, client.create(PODS, p))
        pdb = meta.new_object("PodDisruptionBudget", "px", "default")
        pdb["spec"] = {"minAvailable": 2,
                       "selector": {"matchLabels": {"app": "x"}}}
        client.create(PDBS, pdb)
        assert wait_for(lambda: (client.get(PDBS, "default", "px")
                                 .get("status") or {})
                        .get("disruptionsAllowed") == 0)
        set_phase(client, client.get(PODS, "default", "x0"), "Failed")
        assert wait_for(lambda: (client.get(PDBS, "default", "px")
                                 .get("status") or {})
                        .get("currentHealthy") == 1)


# -- Namespace -------------------------------------------------------------

class TestNamespace:
    def test_delete_sweeps_content(self, cluster):
        store, client, _ = cluster
        client.create(NAMESPACES, meta.new_object("Namespace", "doomed", ""))
        p = meta.new_object("Pod", "inside", "doomed")
        p["spec"] = {"containers": [{"name": "c", "image": "i"}]}
        client.create(PODS, p)
        cm = meta.new_object("ConfigMap", "cfg", "doomed")
        client.create("configmaps", cm)
        client.delete(NAMESPACES, "", "doomed")
        assert wait_for(lambda: client.list(PODS, "doomed")[0] == [])
        assert wait_for(lambda: client.list("configmaps", "doomed")[0] == [])

    def test_active_phase_set(self, cluster):
        store, client, _ = cluster
        client.create(NAMESPACES, meta.new_object("Namespace", "living", ""))
        assert wait_for(lambda: (client.get(NAMESPACES, "", "living")
                                 .get("status") or {}).get("phase") == "Active")


# -- ResourceQuota status --------------------------------------------------

class TestResourceQuotaController:
    def test_status_used_tracked(self, cluster):
        store, client, _ = cluster
        rq = meta.new_object("ResourceQuota", "rq", "default")
        rq["spec"] = {"hard": {"pods": "10", "requests.cpu": "2"}}
        client.create(RESOURCEQUOTAS, rq)
        p = meta.new_object("Pod", "billed", "default")
        p["spec"] = {"containers": [{"name": "c", "image": "i",
                                     "resources": {"requests": {
                                         "cpu": "500m"}}}]}
        client.create(PODS, p)
        assert wait_for(lambda: ((client.get(RESOURCEQUOTAS, "default", "rq")
                                  .get("status") or {}).get("used") or {})
                        .get("pods") == "1")
        used = client.get(RESOURCEQUOTAS, "default", "rq")["status"]["used"]
        assert used["requests.cpu"] == "500m"


# -- ServiceAccount --------------------------------------------------------

class TestServiceAccount:
    def test_default_sa_and_token_created(self, cluster):
        store, client, _ = cluster
        client.create(NAMESPACES, meta.new_object("Namespace", "team-a", ""))
        assert wait_for(lambda: client.list(SERVICEACCOUNTS, "team-a")[0])
        assert wait_for(lambda: (client.get(SERVICEACCOUNTS, "team-a",
                                            "default").get("secrets")))
        secret_name = client.get(SERVICEACCOUNTS, "team-a",
                                 "default")["secrets"][0]["name"]
        secret = client.get(SECRETS, "team-a", secret_name)
        assert secret["type"] == "kubernetes.io/service-account-token"
        assert secret["data"]["token"]


# -- PodGC -----------------------------------------------------------------

class TestPodGC:
    def test_orphaned_pods_on_deleted_node(self, cluster):
        store, client, mgr = cluster
        client.create(NODES, make_node("gone"))
        p = meta.new_object("Pod", "orphan", "default")
        p["spec"] = {"containers": [{"name": "c", "image": "i"}],
                     "nodeName": "gone"}
        client.create(PODS, p)
        client.delete(NODES, "", "gone")
        ctrl = mgr.controllers["podgc"]
        wait_for(lambda: ctrl.node_informer.get("", "gone") is None)
        ctrl.gc_once()
        assert wait_for(lambda: not any(meta.name(p) == "orphan"
                                        for p in pods_of(client)))

    def test_terminated_pods_over_threshold(self, cluster):
        store, client, mgr = cluster
        ctrl = mgr.controllers["podgc"]
        ctrl.threshold = 2
        for i in range(4):
            p = meta.new_object("Pod", f"done{i}", "default")
            p["spec"] = {"containers": [{"name": "c", "image": "i"}]}
            created = client.create(PODS, p)
            set_phase(client, created, "Succeeded")
        wait_for(lambda: sum(
            1 for p in ctrl.pod_informer.list("default")
            if (p.get("status") or {}).get("phase") == "Succeeded") == 4)
        ctrl.gc_once()
        assert wait_for(lambda: len(pods_of(client)) == 2)


# -- TTL after finished ----------------------------------------------------

class TestTTLAfterFinished:
    def test_finished_job_deleted_after_ttl(self, cluster):
        store, client, mgr = cluster
        job = meta.new_object("Job", "brief", "default")
        job["spec"] = {"ttlSecondsAfterFinished": 5, "completions": 1,
                       "template": {"spec": {"containers": [
                           {"name": "c0", "image": "i"}]}}}
        client.create(JOBS, job)
        assert wait_for(lambda: len(pods_of(client)) == 1)
        set_phase(client, pods_of(client)[0], "Succeeded")
        assert wait_for(lambda: (client.get(JOBS, "default", "brief")
                                 .get("status") or {}).get("completionTime"))
        ctrl = mgr.controllers["ttlafterfinished"]
        done_at = client.get(JOBS, "default", "brief")["status"]["completionTime"]
        # the stamp is stable: status rewrites by the job controller must
        # not wipe it (would otherwise defer TTL forever)
        time.sleep(0.3)
        assert client.get(JOBS, "default",
                          "brief")["status"]["completionTime"] == done_at
        ctrl.sweep_once(done_at + 2)  # before TTL: stays
        assert client.get(JOBS, "default", "brief")
        ctrl.sweep_once(done_at + 6)  # after TTL: gone
        with pytest.raises(kv.NotFoundError):
            client.get(JOBS, "default", "brief")


# -- HPA -------------------------------------------------------------------

class TestHPA:
    def _setup_target(self, client, usage="800m"):
        rs = meta.new_object("ReplicaSet", "web", "default")
        rs["spec"] = {"replicas": 2,
                      "selector": {"matchLabels": {"app": "web"}},
                      "template": {"metadata": {"labels": {"app": "web"}},
                                   "spec": {"containers": [
                                       {"name": "c0", "image": "i",
                                        "resources": {"requests": {
                                            "cpu": "500m"}}}]}}}
        client.create(REPLICASETS, rs)
        assert wait_for(
            lambda: len(client.list(PODS, "default")[0]) == 2)
        for p in client.list(PODS, "default")[0]:
            def ann(o, u=usage):
                o["metadata"].setdefault("annotations", {})[
                    USAGE_ANNOTATION] = u
                return o
            client.guaranteed_update(PODS, "default", meta.name(p), ann)

    def test_scales_up_on_high_utilization(self, cluster):
        store, client, mgr = cluster
        self._setup_target(client, usage="800m")  # 160% of request
        hpa = meta.new_object("HorizontalPodAutoscaler", "hpa", "default")
        hpa["spec"] = {"scaleTargetRef": {"kind": "ReplicaSet", "name": "web"},
                       "minReplicas": 1, "maxReplicas": 10,
                       "targetCPUUtilizationPercentage": 80}
        client.create(HPAS, hpa)
        ctrl = mgr.controllers["horizontalpodautoscaler"]
        wait_for(lambda: ctrl.hpa_informer.get("default", "hpa") is not None)
        wait_for(lambda: len(ctrl.pod_informer.list("default")) == 2)
        ctrl.reconcile_once(time.time())
        # desired = ceil(2 * 160 / 80) = 4
        assert wait_for(lambda: client.get(REPLICASETS, "default",
                                           "web")["spec"]["replicas"] == 4)

    def test_respects_max_replicas(self, cluster):
        store, client, mgr = cluster
        self._setup_target(client, usage="5000m")  # 1000% of request
        hpa = meta.new_object("HorizontalPodAutoscaler", "hpa2", "default")
        hpa["spec"] = {"scaleTargetRef": {"kind": "ReplicaSet", "name": "web"},
                       "minReplicas": 1, "maxReplicas": 5,
                       "targetCPUUtilizationPercentage": 80}
        client.create(HPAS, hpa)
        ctrl = mgr.controllers["horizontalpodautoscaler"]
        wait_for(lambda: ctrl.hpa_informer.get("default", "hpa2") is not None)
        wait_for(lambda: len(ctrl.pod_informer.list("default")) == 2)
        ctrl.reconcile_once(time.time())
        assert wait_for(lambda: client.get(REPLICASETS, "default",
                                           "web")["spec"]["replicas"] == 5)

    def test_no_metrics_holds(self, cluster):
        store, client, mgr = cluster
        rs = meta.new_object("ReplicaSet", "quiet", "default")
        rs["spec"] = {"replicas": 2,
                      "selector": {"matchLabels": {"app": "quiet"}},
                      "template": {"metadata": {"labels": {"app": "quiet"}},
                                   "spec": {"containers": [
                                       {"name": "c0", "image": "i"}]}}}
        client.create(REPLICASETS, rs)
        hpa = meta.new_object("HorizontalPodAutoscaler", "hq", "default")
        hpa["spec"] = {"scaleTargetRef": {"kind": "ReplicaSet",
                                          "name": "quiet"},
                       "minReplicas": 1, "maxReplicas": 10,
                       "targetCPUUtilizationPercentage": 80}
        client.create(HPAS, hpa)
        ctrl = mgr.controllers["horizontalpodautoscaler"]
        wait_for(lambda: ctrl.hpa_informer.get("default", "hq") is not None)
        ctrl.reconcile_once(time.time())
        assert client.get(REPLICASETS, "default", "quiet")["spec"][
            "replicas"] == 2


# -- review regressions ----------------------------------------------------

class TestControllerReviewRegressions:
    def test_bad_cron_does_not_starve_others(self, cluster):
        store, client, mgr = cluster
        bad = meta.new_object("CronJob", "aaa-bad", "default")
        bad["spec"] = {"schedule": "1-x * * * *", "jobTemplate": {"spec": {}}}
        good = meta.new_object("CronJob", "zzz-good", "default")
        good["spec"] = {"schedule": "* * * * *",
                        "jobTemplate": {"spec": {"template": {"spec": {
                            "containers": [{"name": "c0", "image": "i"}]}}}}}
        client.create(CRONJOBS, bad)
        client.create(CRONJOBS, good)
        ctrl = mgr.controllers["cronjob"]
        wait_for(lambda: len(ctrl.cj_informer.list("default")) == 2)
        ctrl.reconcile_once(time.time() + 60)  # must not raise
        jobs = [meta.name(j) for j in client.list(JOBS, "default")[0]]
        assert any(n.startswith("zzz-good-") for n in jobs)

    def test_impossible_dom_schedule_rejected(self):
        from kubernetes_tpu.controllers.cronjob import CronParseError
        with pytest.raises(CronParseError):
            CronSchedule("0 0 31 2 *")  # Feb 31 never exists
        with pytest.raises(CronParseError):
            CronSchedule("*/0 * * * *")  # zero step
        CronSchedule("0 0 31 2 0")  # dow restricted: fires on Sundays

    def test_daemonset_respects_template_affinity(self, cluster):
        store, client, _ = cluster
        client.create(NODES, make_node("tpu-node", labels={"accel": "tpu"}))
        client.create(NODES, make_node("plain-node"))
        ds = meta.new_object("DaemonSet", "affin", "default")
        ds["spec"] = {"template": {
            "metadata": {"labels": {"app": "affin"}},
            "spec": {
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [
                            {"key": "accel", "operator": "In",
                             "values": ["tpu"]}]}]}}},
                "containers": [{"name": "c0", "image": "i"}]}}}
        client.create(DAEMONSETS, ds)
        assert wait_for(lambda: len(pods_of(client)) == 1)
        time.sleep(0.3)
        assert len(pods_of(client)) == 1  # plain-node excluded

    def test_pdb_expected_sums_multiple_owners(self, cluster):
        store, client, mgr = cluster
        for rs_name in ("rs-a", "rs-b"):
            rs = meta.new_object("ReplicaSet", rs_name, "default")
            rs["spec"] = {"replicas": 3,
                          "selector": {"matchLabels": {"tier": rs_name}},
                          "template": {"metadata": {"labels": {
                              "tier": rs_name, "shared": "yes"}},
                              "spec": {"containers": [
                                  {"name": "c0", "image": "i"}]}}}
            client.create(REPLICASETS, rs)
        assert wait_for(lambda: len(pods_of(client)) == 6)
        for p in pods_of(client):
            mark_ready(client, p)
        pdb = meta.new_object("PodDisruptionBudget", "span", "default")
        pdb["spec"] = {"minAvailable": "50%",
                       "selector": {"matchLabels": {"shared": "yes"}}}
        client.create(PDBS, pdb)
        assert wait_for(lambda: (client.get(PDBS, "default", "span")
                                 .get("status") or {})
                        .get("expectedPods") == 6)
        st = client.get(PDBS, "default", "span")["status"]
        assert st["desiredHealthy"] == 3 and st["disruptionsAllowed"] == 3
