"""Controller fleet round 2: endpointslice, replication controller,
certificates (approve/sign), ttl, nodeipam, root-ca publisher, bootstrap
tokens, PV binder, pvc/pv protection, attach/detach, ephemeral volumes.

Behavioral contracts from pkg/controller/{endpointslice,replication,
certificates,ttl,nodeipam,bootstrap,volume}.
"""

import base64
import importlib.util
import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import (
    CONFIGMAPS, CSRS, ENDPOINTSLICES, NAMESPACES, NODES, PODS, PVCS, PVS,
    REPLICATIONCONTROLLERS, SECRETS, SERVICES, STORAGECLASSES,
    VOLUMEATTACHMENTS,
)
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod, wait_for

requires_crypto = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="CSR signing/root CA need the cryptography package")


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    mgr = ControllerManager(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    mgr.run()
    yield store, client, mgr
    mgr.stop()
    factory.stop()


def bound_running_pod(name, node="n1", labels=None, ns="default"):
    p = make_pod(name, ns).labels(**(labels or {})).node(node).build()
    p["status"] = {"phase": "Running",
                   "podIP": "10.0.0.9",
                   "conditions": [{"type": "Ready", "status": "True"}]}
    return p


class TestEndpointSlice:
    def test_slices_track_service_pods(self, cluster):
        _, client, _ = cluster
        svc = meta.new_object("Service", "web", "default")
        svc["spec"] = {"selector": {"app": "web"},
                       "ports": [{"port": 80, "protocol": "TCP"}]}
        client.create(SERVICES, svc)
        client.create(PODS, bound_running_pod("w1", labels={"app": "web"}))
        client.create(PODS, bound_running_pod("w2", labels={"app": "web"}))
        client.create(PODS, bound_running_pod("other", labels={"app": "db"}))

        def slice_has_two():
            sls = [s for s in client.list(ENDPOINTSLICES, "default")[0]
                   if meta.labels(s).get("kubernetes.io/service-name") == "web"]
            return sls and sum(len(s.get("endpoints") or ()) for s in sls) == 2
        assert wait_for(slice_has_two)
        # pod deletion shrinks the slice
        client.delete(PODS, "default", "w2")
        assert wait_for(lambda: sum(
            len(s.get("endpoints") or ())
            for s in client.list(ENDPOINTSLICES, "default")[0]) == 1)
        # service deletion removes the slices
        client.delete(SERVICES, "default", "web")
        assert wait_for(
            lambda: not client.list(ENDPOINTSLICES, "default")[0])


class TestEndpointSliceNamedPorts:
    def test_named_target_port_resolves_per_pod(self, cluster):
        """String targetPorts resolve against each pod's container ports;
        pods with different mappings land in separate slices (reference
        endpointslice/reconciler.go resolves named ports per endpoint)."""
        _, client, _ = cluster
        svc = meta.new_object("Service", "api", "default")
        svc["spec"] = {"selector": {"app": "api"},
                       "ports": [{"port": 80, "targetPort": "http",
                                  "protocol": "TCP"}]}
        client.create(SERVICES, svc)
        p1 = bound_running_pod("a1", labels={"app": "api"})
        p1["spec"]["containers"] = [{"name": "c0", "image": "img",
                                     "ports": [{"name": "http",
                                                "containerPort": 8080}]}]
        p2 = bound_running_pod("a2", labels={"app": "api"})
        p2["spec"]["containers"] = [{"name": "c0", "image": "img",
                                     "ports": [{"name": "http",
                                                "containerPort": 9090}]}]
        client.create(PODS, p1)
        client.create(PODS, p2)

        def resolved():
            sls = [s for s in client.list(ENDPOINTSLICES, "default")[0]
                   if meta.labels(s).get("kubernetes.io/service-name")
                   == "api"]
            got = {}
            for s in sls:
                for ep in s.get("endpoints") or ():
                    got[ep["targetRef"]["name"]] = [
                        pt["port"] for pt in s.get("ports") or ()]
            return got == {"a1": [8080], "a2": [9090]}
        assert wait_for(resolved)
        # no slice may carry a non-numeric port (the proxier consumes these)
        for s in client.list(ENDPOINTSLICES, "default")[0]:
            for pt in s.get("ports") or ():
                assert isinstance(pt["port"], int)


class TestReplicationController:
    def test_scales_up_and_down(self, cluster):
        _, client, _ = cluster
        rc = meta.new_object("ReplicationController", "rc1", "default")
        rc["spec"] = {"replicas": 3, "selector": {"app": "rc1"},
                      "template": {"metadata": {"labels": {"app": "rc1"}},
                                   "spec": {"containers": [
                                       {"name": "c0", "image": "img"}]}}}
        client.create(REPLICATIONCONTROLLERS, rc)
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 3)

        def scale(o):
            o["spec"]["replicas"] = 1
            return o
        client.guaranteed_update(REPLICATIONCONTROLLERS, "default", "rc1",
                                 scale)
        assert wait_for(lambda: len([
            p for p in client.list(PODS, "default")[0]
            if meta.deletion_timestamp(p) is None]) == 1)


@requires_crypto
class TestCertificates:
    def _make_csr_pem(self):
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID
        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        csr = (x509.CertificateSigningRequestBuilder()
               .subject_name(x509.Name([x509.NameAttribute(
                   NameOID.COMMON_NAME, "system:node:n1")]))
               .sign(key, hashes.SHA256()))
        return csr.public_bytes(serialization.Encoding.PEM)

    def test_approve_and_sign_kubelet_csr(self, cluster):
        _, client, _ = cluster
        csr = meta.new_object("CertificateSigningRequest", "csr-n1", None)
        csr["spec"] = {
            "signerName": "kubernetes.io/kube-apiserver-client-kubelet",
            "usages": ["key encipherment", "digital signature", "client auth"],
            "request": base64.b64encode(self._make_csr_pem()).decode(),
        }
        client.create(CSRS, csr)

        def signed():
            c = client.get(CSRS, "", "csr-n1")
            st = c.get("status") or {}
            approved = any(x.get("type") == "Approved"
                           for x in st.get("conditions") or ())
            return approved and st.get("certificate")
        assert wait_for(signed)
        # the issued cert chains to the cluster CA
        from cryptography import x509
        from kubernetes_tpu.controllers.certificates import ClusterCA
        pem = base64.b64decode(client.get(CSRS, "", "csr-n1")
                               ["status"]["certificate"])
        cert = x509.load_pem_x509_certificate(pem)
        assert cert.issuer == ClusterCA.shared().cert.subject

    def test_unknown_signer_not_approved(self, cluster):
        _, client, _ = cluster
        csr = meta.new_object("CertificateSigningRequest", "csr-x", None)
        csr["spec"] = {"signerName": "example.com/custom",
                       "usages": ["client auth"], "request": ""}
        client.create(CSRS, csr)
        time.sleep(0.3)
        st = client.get(CSRS, "", "csr-x").get("status") or {}
        assert not any(x.get("type") == "Approved"
                       for x in st.get("conditions") or ())


class TestTTLAndRootCA:
    def test_nodes_annotated_with_ttl(self, cluster):
        _, client, _ = cluster
        client.create(NODES, make_node("n1").build())
        assert wait_for(lambda: (client.get(NODES, "", "n1")["metadata"]
                                 .get("annotations") or {})
                        .get("node.alpha.kubernetes.io/ttl") == "0")

    @requires_crypto
    def test_root_ca_configmap_published(self, cluster):
        _, client, _ = cluster
        client.create(NAMESPACES, meta.new_object("Namespace", "team-a", None))
        assert wait_for(lambda: client.list(CONFIGMAPS, "team-a")[0])
        cm = client.get(CONFIGMAPS, "team-a", "kube-root-ca.crt")
        assert "BEGIN CERTIFICATE" in cm["data"]["ca.crt"]


class TestNodeIpam:
    def test_pod_cidr_allocation_and_reuse(self, cluster):
        store, client, mgr = cluster
        from kubernetes_tpu.client import SharedInformerFactory
        from kubernetes_tpu.controllers.nodeipam import NodeIpamController
        factory = SharedInformerFactory(client)
        ipam = NodeIpamController(client, factory,
                                  cluster_cidr="10.244.0.0/22", node_mask=24)
        factory.start()
        factory.wait_for_cache_sync()
        ipam.run()
        try:
            client.create(NODES, make_node("ip-1").build())
            client.create(NODES, make_node("ip-2").build())
            assert wait_for(lambda: all(
                (client.get(NODES, "", n).get("spec") or {}).get("podCIDR")
                for n in ("ip-1", "ip-2")))
            c1 = client.get(NODES, "", "ip-1")["spec"]["podCIDR"]
            c2 = client.get(NODES, "", "ip-2")["spec"]["podCIDR"]
            assert c1 != c2
            # release on delete, reallocate to a new node
            client.delete(NODES, "", "ip-1")
            assert wait_for(lambda: ipam.cidrs._used.get(c1) is None)
            client.create(NODES, make_node("ip-3").build())
            assert wait_for(lambda: (client.get(NODES, "", "ip-3").get("spec")
                                     or {}).get("podCIDR") == c1)
        finally:
            ipam.stop()
            factory.stop()


class TestBootstrapTokens:
    def test_expired_token_cleaned_and_cluster_info_signed(self, cluster):
        store, client, mgr = cluster
        from kubernetes_tpu.controllers.bootstrap import (
            BootstrapSigner, TokenCleaner)
        factory = SharedInformerFactory(client)
        cleaner = TokenCleaner(client, factory)
        cleaner.resync_seconds = 0.1
        signer = BootstrapSigner(client, factory)
        factory.start()
        factory.wait_for_cache_sync()
        cleaner.run()
        signer.run()
        try:
            live = meta.new_object("Secret", "bootstrap-token-abc123",
                                   "kube-system")
            live["type"] = "bootstrap.kubernetes.io/token"
            live["data"] = {"token-id": "abc123", "token-secret": "s3cret",
                            "expiration": str(time.time() + 3600)}
            client.create(SECRETS, live)
            dead = meta.new_object("Secret", "bootstrap-token-dead00",
                                   "kube-system")
            dead["type"] = "bootstrap.kubernetes.io/token"
            dead["data"] = {"token-id": "dead00", "token-secret": "x",
                            "expiration": str(time.time() - 1)}
            client.create(SECRETS, dead)
            assert wait_for(lambda: not any(
                meta.name(s) == "bootstrap-token-dead00"
                for s in client.list(SECRETS, "kube-system")[0]))
            assert wait_for(lambda: "jws-kubeconfig-abc123" in (
                (client.get(CONFIGMAPS, "kube-public", "cluster-info")
                 .get("data") or {})
                if client.list(CONFIGMAPS, "kube-public")[0] else {}))
        finally:
            cleaner.stop()
            signer.stop()
            factory.stop()


class TestVolumeControllers:
    def _pvc(self, name, ns="default", storage="1Gi", cls=None):
        pvc = meta.new_object("PersistentVolumeClaim", name, ns)
        pvc["spec"] = {"accessModes": ["ReadWriteOnce"],
                       "resources": {"requests": {"storage": storage}}}
        if cls:
            pvc["spec"]["storageClassName"] = cls
        return pvc

    def _pv(self, name, storage="2Gi", cls=None, policy="Retain"):
        pv = meta.new_object("PersistentVolume", name, None)
        pv["spec"] = {"capacity": {"storage": storage},
                      "accessModes": ["ReadWriteOnce"],
                      "persistentVolumeReclaimPolicy": policy}
        if cls:
            pv["spec"]["storageClassName"] = cls
        return pv

    def test_static_binding(self, cluster):
        _, client, _ = cluster
        client.create(PVS, self._pv("pv-a"))
        client.create(PVCS, self._pvc("claim-a"))
        assert wait_for(lambda: (client.get(PVCS, "default", "claim-a")
                                 .get("spec") or {}).get("volumeName") == "pv-a")
        pv = client.get(PVS, "", "pv-a")
        assert (pv.get("spec") or {}).get("claimRef", {}).get("name") == "claim-a"
        assert (pv.get("status") or {}).get("phase") == "Bound"

    def test_too_small_pv_not_bound(self, cluster):
        _, client, _ = cluster
        client.create(PVS, self._pv("pv-small", storage="512Mi"))
        client.create(PVCS, self._pvc("claim-big", storage="1Gi"))
        time.sleep(0.3)
        assert not (client.get(PVCS, "default", "claim-big")
                    .get("spec") or {}).get("volumeName")

    def test_dynamic_provisioning(self, cluster):
        _, client, _ = cluster
        sc = meta.new_object("StorageClass", "fast", None)
        sc["provisioner"] = "tpu.kubernetes.io/host-provisioner"
        client.create(STORAGECLASSES, sc)
        client.create(PVCS, self._pvc("claim-dyn", cls="fast"))
        assert wait_for(lambda: (client.get(PVCS, "default", "claim-dyn")
                                 .get("spec") or {}).get("volumeName"))

    def test_delete_reclaim(self, cluster):
        _, client, _ = cluster
        pv = self._pv("pv-del", policy="Delete")
        client.create(PVS, pv)
        client.create(PVCS, self._pvc("claim-del"))
        assert wait_for(lambda: (client.get(PVCS, "default", "claim-del")
                                 .get("spec") or {}).get("volumeName"))
        client.delete(PVCS, "default", "claim-del")
        # claim unprotected (no pod uses it) -> gone -> PV reclaimed
        assert wait_for(lambda: not any(
            meta.name(p) == "pv-del" for p in client.list(PVS, None)[0]))

    def test_pvc_protection_blocks_delete_while_in_use(self, cluster):
        _, client, _ = cluster
        client.create(PVCS, self._pvc("claim-p"))
        assert wait_for(lambda: "kubernetes.io/pvc-protection" in (
            client.get(PVCS, "default", "claim-p")["metadata"]
            .get("finalizers") or []))
        pod = make_pod("user-pod").node("n1").build()
        pod["spec"]["volumes"] = [{"name": "v",
                                   "persistentVolumeClaim":
                                   {"claimName": "claim-p"}}]
        client.create(PODS, pod)
        time.sleep(0.2)
        client.delete(PVCS, "default", "claim-p")  # -> terminating, not gone
        time.sleep(0.3)
        pvc = client.get(PVCS, "default", "claim-p")
        assert pvc["metadata"].get("deletionTimestamp")
        # pod goes away -> finalizer stripped -> PVC really deleted
        client.delete(PODS, "default", "user-pod")
        assert wait_for(lambda: not any(
            meta.name(c) == "claim-p"
            for c in client.list(PVCS, "default")[0]))

    def test_attach_detach_and_ephemeral(self, cluster):
        _, client, _ = cluster
        client.create(NODES, make_node("vn1").build())
        client.create(PVS, self._pv("pv-att"))
        client.create(PVCS, self._pvc("claim-att"))
        assert wait_for(lambda: (client.get(PVCS, "default", "claim-att")
                                 .get("spec") or {}).get("volumeName"))
        pod = make_pod("att-pod").node("vn1").build()
        pod["spec"]["volumes"] = [
            {"name": "v", "persistentVolumeClaim": {"claimName": "claim-att"}},
            {"name": "scratch", "ephemeral": {"volumeClaimTemplate": {
                "spec": {"accessModes": ["ReadWriteOnce"],
                         "resources": {"requests": {"storage": "1Gi"}}}}}},
        ]
        client.create(PODS, pod)
        assert wait_for(lambda: any(
            (va.get("spec") or {}).get("nodeName") == "vn1"
            for va in client.list(VOLUMEATTACHMENTS, None)[0]))
        assert wait_for(lambda: any(
            meta.name(c) == "att-pod-scratch"
            for c in client.list(PVCS, "default")[0]))
        node = client.get(NODES, "", "vn1")
        assert wait_for(lambda: (client.get(NODES, "", "vn1").get("status")
                                 or {}).get("volumesAttached"))
        # pod deleted -> detach
        client.delete(PODS, "default", "att-pod")
        assert wait_for(lambda: not any(
            (va.get("spec") or {}).get("nodeName") == "vn1"
            for va in client.list(VOLUMEATTACHMENTS, None)[0]))


class TestCascadeDeletion:
    def test_rc_delete_cascades_to_pods(self, cluster):
        _, client, _ = cluster
        rc = meta.new_object("ReplicationController", "rc-gc", "default")
        rc["spec"] = {"replicas": 2, "selector": {"app": "rc-gc"},
                      "template": {"metadata": {"labels": {"app": "rc-gc"}},
                                   "spec": {"containers": [
                                       {"name": "c0", "image": "img"}]}}}
        client.create(REPLICATIONCONTROLLERS, rc)
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 2)
        client.delete(REPLICATIONCONTROLLERS, "default", "rc-gc")
        assert wait_for(lambda: not client.list(PODS, "default")[0])

    def test_pod_delete_cascades_to_ephemeral_pvc(self, cluster):
        _, client, _ = cluster
        pod = make_pod("eph-pod").node("n1").build()
        pod["spec"]["volumes"] = [
            {"name": "scratch", "ephemeral": {"volumeClaimTemplate": {
                "spec": {"accessModes": ["ReadWriteOnce"],
                         "resources": {"requests": {"storage": "1Gi"}}}}}}]
        client.create(PODS, pod)
        assert wait_for(lambda: any(
            meta.name(c) == "eph-pod-scratch"
            for c in client.list(PVCS, "default")[0]))
        client.delete(PODS, "default", "eph-pod")
        assert wait_for(lambda: not any(
            meta.name(c) == "eph-pod-scratch"
            for c in client.list(PVCS, "default")[0]))


class TestBinderWakeups:
    def test_claim_created_before_pv_binds_when_pv_arrives(self, cluster):
        _, client, _ = cluster
        pvc = meta.new_object("PersistentVolumeClaim", "early-claim", "default")
        pvc["spec"] = {"accessModes": ["ReadWriteOnce"],
                       "resources": {"requests": {"storage": "1Gi"}}}
        client.create(PVCS, pvc)
        time.sleep(0.3)  # claim syncs with no PV available
        pv = meta.new_object("PersistentVolume", "late-pv", None)
        pv["spec"] = {"capacity": {"storage": "2Gi"},
                      "accessModes": ["ReadWriteOnce"],
                      "persistentVolumeReclaimPolicy": "Retain"}
        client.create(PVS, pv)
        assert wait_for(lambda: (client.get(PVCS, "default", "early-claim")
                                 .get("spec") or {}).get("volumeName")
                        == "late-pv")


class TestCloudControllerManager:
    def _ccm(self, client):
        from kubernetes_tpu.client import SharedInformerFactory
        from kubernetes_tpu.controllers.cloud import CloudControllerManager
        factory = SharedInformerFactory(client)
        ccm = CloudControllerManager(client, factory)
        factory.start()
        factory.wait_for_cache_sync()
        ccm.run()
        return factory, ccm

    def test_loadbalancer_lifecycle(self, cluster):
        _, client, _ = cluster
        factory, ccm = self._ccm(client)
        try:
            svc = meta.new_object("Service", "lb-svc", "default")
            svc["spec"] = {"type": "LoadBalancer", "clusterIP": "10.96.9.9",
                           "ports": [{"port": 443}]}
            client.create(SERVICES, svc)
            assert wait_for(lambda: ((client.get(SERVICES, "default",
                                                 "lb-svc").get("status")
                                      or {}).get("loadBalancer") or {})
                            .get("ingress"))
            ip = client.get(SERVICES, "default", "lb-svc")[
                "status"]["loadBalancer"]["ingress"][0]["ip"]
            assert ip.startswith("203.0.113.")
            # type change -> deprovision + status cleared
            def retype(o):
                o["spec"]["type"] = "ClusterIP"
                return o
            client.guaranteed_update(SERVICES, "default", "lb-svc", retype)
            assert wait_for(lambda: not (client.get(SERVICES, "default",
                                                    "lb-svc").get("status")
                                         or {}).get("loadBalancer"))
            assert "default/lb-svc" not in ccm.cloud._lbs
        finally:
            ccm.stop()
            factory.stop()

    def test_node_metadata_routes_and_taint(self, cluster):
        _, client, _ = cluster
        factory, ccm = self._ccm(client)
        try:
            n = make_node("cloud-1").build()
            n["spec"]["taints"] = [{
                "key": "node.cloudprovider.kubernetes.io/uninitialized",
                "value": "true", "effect": "NoSchedule"}]
            n["spec"]["podCIDR"] = "10.244.9.0/24"
            client.create(NODES, n)
            assert wait_for(lambda: (client.get(NODES, "", "cloud-1")
                                     .get("spec") or {}).get("providerID"))
            got = client.get(NODES, "", "cloud-1")
            assert meta.labels(got)["topology.kubernetes.io/zone"] \
                == "tpu-zone-a"
            assert not any(
                t.get("key").startswith("node.cloudprovider")
                for t in got["spec"].get("taints") or ())
            assert wait_for(
                lambda: ccm.cloud.routes.get("cloud-1") == "10.244.9.0/24")
            assert wait_for(lambda: any(
                c.get("type") == "NetworkUnavailable"
                and c.get("status") == "False"
                for c in (client.get(NODES, "", "cloud-1").get("status")
                          or {}).get("conditions") or ()))
        finally:
            ccm.stop()
            factory.stop()
