"""Round-4 controller additions: ClusterRole aggregation, EndpointSlice
mirroring, PVC expansion.

Behavioral contracts from pkg/controller/{clusterroleaggregation,
endpointslicemirroring,volume/expand}.
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import (
    CLUSTERROLES, ENDPOINTS, ENDPOINTSLICES, PVCS, PVS, SERVICES,
    STORAGECLASSES,
)
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    mgr = ControllerManager(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    mgr.run()
    yield store, client, mgr
    mgr.stop()
    factory.stop()
    client.close()


class TestClusterRoleAggregation:
    def test_union_of_selected_roles(self, cluster):
        _, client, _ = cluster
        agg = meta.new_object("ClusterRole", "admin-agg", "")
        agg["aggregationRule"] = {"clusterRoleSelectors": [
            {"matchLabels": {"rbac/aggregate-to-admin": "true"}}]}
        agg["rules"] = []
        client.create(CLUSTERROLES, agg)
        for i, res in enumerate(("widgets", "gadgets")):
            r = meta.new_object("ClusterRole", f"part-{i}", "")
            r["metadata"]["labels"] = {"rbac/aggregate-to-admin": "true"}
            r["rules"] = [{"apiGroups": ["example.com"],
                           "resources": [res], "verbs": ["get", "list"]}]
            client.create(CLUSTERROLES, r)

        def aggregated():
            role = client.get(CLUSTERROLES, "", "admin-agg")
            res = {tuple(rule["resources"]) for rule in role.get("rules")
                   or ()}
            return res == {("widgets",), ("gadgets",)}
        assert wait_for(aggregated)

    def test_label_change_updates_union(self, cluster):
        _, client, _ = cluster
        agg = meta.new_object("ClusterRole", "view-agg", "")
        agg["aggregationRule"] = {"clusterRoleSelectors": [
            {"matchLabels": {"agg": "view"}}]}
        client.create(CLUSTERROLES, agg)
        r = meta.new_object("ClusterRole", "late", "")
        r["rules"] = [{"apiGroups": [""], "resources": ["pods"],
                       "verbs": ["get"]}]
        client.create(CLUSTERROLES, r)
        time.sleep(0.3)
        assert not (client.get(CLUSTERROLES, "", "view-agg").get("rules")
                    or [])

        def label(cur):
            cur["metadata"].setdefault("labels", {})["agg"] = "view"
            return cur
        client.guaranteed_update(CLUSTERROLES, "", "late", label)
        assert wait_for(lambda: (client.get(CLUSTERROLES, "", "view-agg")
                                 .get("rules") or []))


class TestEndpointSliceMirroring:
    def _custom_endpoints(self, client, name="ext-svc"):
        svc = meta.new_object("Service", name, "default")
        svc["spec"] = {"ports": [{"port": 80, "protocol": "TCP"}]}
        client.create(SERVICES, svc)  # NO selector: custom endpoints
        ep = meta.new_object("Endpoints", name, "default")
        ep["subsets"] = [{
            "addresses": [{"ip": "10.1.2.3"}, {"ip": "10.1.2.4"}],
            "ports": [{"port": 80, "protocol": "TCP"}]}]
        client.create(ENDPOINTS, ep)
        return svc, ep

    def test_mirrors_custom_endpoints(self, cluster):
        _, client, _ = cluster
        self._custom_endpoints(client)

        def mirrored():
            slices, _ = client.list(ENDPOINTSLICES, "default")
            mine = [s for s in slices
                    if meta.labels(s).get(
                        "kubernetes.io/service-name") == "ext-svc"]
            if not mine:
                return False
            ips = {a for s in mine for e in s["endpoints"]
                   for a in e["addresses"]}
            return ips == {"10.1.2.3", "10.1.2.4"}
        assert wait_for(mirrored)

    def test_skip_mirror_label_respected(self, cluster):
        _, client, _ = cluster
        svc = meta.new_object("Service", "skip-svc", "default")
        svc["spec"] = {"ports": [{"port": 80}]}
        client.create(SERVICES, svc)
        ep = meta.new_object("Endpoints", "skip-svc", "default")
        ep["metadata"]["labels"] = {
            "endpointslice.kubernetes.io/skip-mirror": "true"}
        ep["subsets"] = [{"addresses": [{"ip": "10.9.9.9"}],
                          "ports": [{"port": 80}]}]
        client.create(ENDPOINTS, ep)
        time.sleep(0.4)
        slices, _ = client.list(ENDPOINTSLICES, "default")
        assert not [s for s in slices if meta.labels(s).get(
            "kubernetes.io/service-name") == "skip-svc"]

    def test_mirror_survives_service_events_and_slice_deletion(
            self, cluster):
        """A Service event must not let the normal EndpointSlice
        controller delete the mirror (managed-by filter), and a mirror
        deleted by hand must be recreated (the mirroring controller
        watches slices)."""
        _, client, _ = cluster
        self._custom_endpoints(client, "live-svc")

        def mirror_names():
            return [meta.name(s) for s in
                    client.list(ENDPOINTSLICES, "default")[0]
                    if meta.labels(s).get(
                        "kubernetes.io/service-name") == "live-svc"]
        assert wait_for(mirror_names)
        # poke the Service: annotation edit fires the normal controller
        def annotate(cur):
            cur["metadata"].setdefault("annotations", {})["x"] = "y"
            return cur
        client.guaranteed_update(SERVICES, "default", "live-svc",
                                 annotate)
        time.sleep(0.5)
        assert mirror_names(), "service event deleted the mirror"
        # delete the mirror by hand: must come back
        for nm in mirror_names():
            client.delete(ENDPOINTSLICES, "default", nm)
        assert wait_for(mirror_names), "mirror not recreated"

    def test_deleting_endpoints_removes_mirror(self, cluster):
        _, client, _ = cluster
        self._custom_endpoints(client, "gone-svc")
        assert wait_for(lambda: [
            s for s in client.list(ENDPOINTSLICES, "default")[0]
            if meta.labels(s).get(
                "kubernetes.io/service-name") == "gone-svc"])
        client.delete(ENDPOINTS, "default", "gone-svc")
        assert wait_for(lambda: not [
            s for s in client.list(ENDPOINTSLICES, "default")[0]
            if meta.labels(s).get(
                "kubernetes.io/service-name") == "gone-svc"])


class TestVolumeExpand:
    def _bound_claim(self, client, expandable=True):
        sc = meta.new_object("StorageClass", "fast", "")
        sc["provisioner"] = "sim"
        sc["allowVolumeExpansion"] = expandable
        client.create(STORAGECLASSES, sc)
        pv = meta.new_object("PersistentVolume", "pv-x", "")
        pv["spec"] = {"capacity": {"storage": "1Gi"},
                      "accessModes": ["ReadWriteOnce"],
                      "storageClassName": "fast"}
        client.create(PVS, pv)
        pvc = meta.new_object("PersistentVolumeClaim", "data", "default")
        pvc["spec"] = {"storageClassName": "fast",
                       "accessModes": ["ReadWriteOnce"],
                       "volumeName": "pv-x",
                       "resources": {"requests": {"storage": "1Gi"}}}
        client.create(PVCS, pvc)
        client.update_status(PVCS, {**client.get(PVCS, "default", "data"),
                                    "status": {"phase": "Bound",
                                               "capacity": {
                                                   "storage": "1Gi"}}})
        return pvc

    def test_expands_bound_claim(self, cluster):
        _, client, _ = cluster
        self._bound_claim(client)

        def grow(cur):
            cur["spec"]["resources"]["requests"]["storage"] = "5Gi"
            return cur
        client.guaranteed_update(PVCS, "default", "data", grow)
        assert wait_for(lambda: client.get(PVS, "", "pv-x")["spec"][
            "capacity"]["storage"] == "5Gi")
        assert wait_for(lambda: (client.get(PVCS, "default", "data")
                                 .get("status", {}).get("capacity", {})
                                 .get("storage")) == "5Gi")

    def test_oversized_static_pv_never_shrunk(self, cluster):
        """A 100Gi static PV bound to a 1Gi claim must stay 100Gi (the
        expander compares against the VOLUME's capacity, never a
        status-derived zero)."""
        _, client, _ = cluster
        sc = meta.new_object("StorageClass", "fast", "")
        sc["provisioner"] = "sim"
        sc["allowVolumeExpansion"] = True
        client.create(STORAGECLASSES, sc)
        pv = meta.new_object("PersistentVolume", "pv-big", "")
        pv["spec"] = {"capacity": {"storage": "100Gi"},
                      "accessModes": ["ReadWriteOnce"],
                      "storageClassName": "fast"}
        client.create(PVS, pv)
        pvc = meta.new_object("PersistentVolumeClaim", "small", "default")
        pvc["spec"] = {"storageClassName": "fast",
                       "accessModes": ["ReadWriteOnce"],
                       "volumeName": "pv-big",
                       "resources": {"requests": {"storage": "1Gi"}}}
        client.create(PVCS, pvc)
        client.update_status(PVCS, {
            **client.get(PVCS, "default", "small"),
            "status": {"phase": "Bound"}})
        time.sleep(0.4)
        assert client.get(PVS, "", "pv-big")["spec"]["capacity"][
            "storage"] == "100Gi"

    def test_class_flip_wakes_stalled_expansion(self, cluster):
        """Request grows while the class forbids expansion; flipping
        allowVolumeExpansion on must retry the claim without any other
        PVC event."""
        _, client, _ = cluster
        self._bound_claim(client, expandable=False)

        def grow(cur):
            cur["spec"]["resources"]["requests"]["storage"] = "3Gi"
            return cur
        client.guaranteed_update(PVCS, "default", "data", grow)
        time.sleep(0.3)
        assert client.get(PVS, "", "pv-x")["spec"]["capacity"][
            "storage"] == "1Gi"

        def allow(cur):
            cur["allowVolumeExpansion"] = True
            return cur
        client.guaranteed_update(STORAGECLASSES, "", "fast", allow)
        assert wait_for(lambda: client.get(PVS, "", "pv-x")["spec"][
            "capacity"]["storage"] == "3Gi")

    def test_no_expansion_without_class_permission(self, cluster):
        _, client, _ = cluster
        self._bound_claim(client, expandable=False)

        def grow(cur):
            cur["spec"]["resources"]["requests"]["storage"] = "5Gi"
            return cur
        client.guaranteed_update(PVCS, "default", "data", grow)
        time.sleep(0.4)
        assert client.get(PVS, "", "pv-x")["spec"]["capacity"][
            "storage"] == "1Gi"
