"""Versioned CORE API serving: pods at v1 (hub/storage) + v2alpha1
through the same conversion seam CRDs use.

Reference anchors: pkg/apis/core/v1/conversion.go + defaults.go (the
hub-and-spoke conversion that makes versioned evolution possible),
apimachinery/pkg/runtime/scheme.go (convert-on-serve), and the CRD
multi-version serving behavior in apiextensions-apiserver.
"""

import random
import string

import pytest

from kubernetes_tpu.api import core_versions as corever
from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client.http_client import HTTPClient, HTTPWatch
from kubernetes_tpu.store import kv


@pytest.fixture()
def server():
    store = kv.MemoryStore(history=10_000)
    srv = APIServer(store).start()
    http = HTTPClient.from_url(srv.url)
    yield http, store
    srv.stop()


def v1_pod(name, **spec_extra):
    pod = meta.new_object("Pod", name, "default")
    pod["spec"] = {"containers": [{"name": "c", "image": "i"}],
                   "schedulerName": "default-scheduler",
                   "priority": 7, **spec_extra}
    return pod


class TestConversionFunctions:
    def test_v1_to_v2_regroups_scheduling(self):
        pod = v1_pod("a", priorityClassName="high")
        out = corever.convert("pods", pod, "v2alpha1")
        assert out["apiVersion"] == "v2alpha1"
        sched = out["spec"]["scheduling"]
        assert sched == {"schedulerName": "default-scheduler",
                         "priority": 7, "priorityClassName": "high"}
        assert "schedulerName" not in out["spec"]
        assert "priority" not in out["spec"]
        # input not mutated (pure conversion)
        assert pod["spec"]["priority"] == 7

    def test_round_trip_identity(self):
        pod = v1_pod("b", preemptionPolicy="Never")
        pod["status"] = {"phase": "Pending", "nominatedNodeName": "n1"}
        back = corever.to_storage(
            "pods", corever.convert("pods", pod, "v2alpha1"), "v2alpha1")
        assert back["spec"] == pod["spec"]
        assert back["status"] == pod["status"]

    def test_unknown_fields_survive_both_directions(self):
        pod = v1_pod("c")
        pod["spec"]["futureField"] = {"x": 1}
        v2 = corever.convert("pods", pod, "v2alpha1")
        assert v2["spec"]["futureField"] == {"x": 1}
        v2["spec"]["scheduling"]["futureKnob"] = "y"
        v1 = corever.to_storage("pods", v2, "v2alpha1")
        assert v1["spec"]["scheduling"] == {"futureKnob": "y"}
        # and it survives ANOTHER trip out
        v2b = corever.convert("pods", v1, "v2alpha1")
        assert v2b["spec"]["scheduling"]["futureKnob"] == "y"

    def test_v2_defaulting_fills_scheduler_name(self):
        v2 = {"apiVersion": "v2alpha1", "kind": "Pod",
              "metadata": {"name": "d", "namespace": "default"},
              "spec": {"containers": []}}
        stored = corever.to_storage("pods", v2, "v2alpha1")
        assert stored["spec"]["schedulerName"] == "default-scheduler"

    def test_fuzz_round_trip(self):
        """Arbitrary pods with random subsets of the moved fields and
        random extra fields round-trip exactly (v1 -> v2 -> v1)."""
        rng = random.Random(42)
        moved = ["schedulerName", "priority", "priorityClassName",
                 "preemptionPolicy"]
        for trial in range(200):
            pod = meta.new_object("Pod", f"f{trial}", "default")
            spec = {"containers": [{"name": "c"}]}
            for f in moved:
                if rng.random() < 0.5:
                    spec[f] = rng.choice([0, 5, "x", "default-scheduler"])
            for _ in range(rng.randrange(3)):
                k = "".join(rng.choices(string.ascii_lowercase, k=6))
                spec[k] = rng.choice([1, "v", {"n": True}, [1, 2]])
            pod["spec"] = spec
            if rng.random() < 0.5:
                pod["status"] = {"phase": "Pending"}
                if rng.random() < 0.5:
                    pod["status"]["nominatedNodeName"] = "n"
            snap = meta.deep_copy(pod)
            back = corever.to_storage(
                "pods", corever.convert("pods", pod, "v2alpha1"),
                "v2alpha1")
            # defaulting may ADD schedulerName; remove it for comparison
            # when the original lacked it
            if "schedulerName" not in snap["spec"]:
                back["spec"].pop("schedulerName", None)
            assert back["spec"] == snap["spec"], trial
            assert back.get("status") == snap.get("status"), trial
            assert pod == snap, f"input mutated in trial {trial}"


class TestServedVersions:
    def test_discovery_lists_both_versions(self, server):
        http, _ = server
        doc = http._request("GET", "/api")
        assert set(doc["versions"]) == {"v1", "v2alpha1"}
        rl = http._request("GET", "/api/v2alpha1")
        names = [e["name"] for e in rl["resources"]]
        assert "pods" in names
        assert "pods/status" in names  # served subresources advertised
        assert not any(n.split("/")[0] == "nodes" for n in names)

    def test_create_at_v2_read_at_v1(self, server):
        http, store = server
        v2 = {"apiVersion": "v2alpha1", "kind": "Pod",
              "metadata": {"name": "cv2", "namespace": "default"},
              "spec": {"containers": [{"name": "c"}],
                       "scheduling": {"priority": 9}}}
        created = http._request(
            "POST", "/api/v2alpha1/namespaces/default/pods", v2)
        # response comes back in the REQUEST version
        assert created["spec"]["scheduling"]["priority"] == 9
        # stored (and v1-served) in hub form
        stored = store.get("pods", "default", "cv2")
        assert stored["spec"]["priority"] == 9
        assert "scheduling" not in stored["spec"]
        v1 = http.get("pods", "default", "cv2")
        assert v1["spec"]["priority"] == 9

    def test_create_at_v1_read_at_v2(self, server):
        http, _ = server
        http.create("pods", v1_pod("cv1"))
        got = http._request(
            "GET", "/api/v2alpha1/namespaces/default/pods/cv1")
        assert got["apiVersion"] == "v2alpha1"
        assert got["spec"]["scheduling"]["priority"] == 7
        assert "priority" not in got["spec"]

    def test_list_converts(self, server):
        http, _ = server
        http.create("pods", v1_pod("l1"))
        http.create("pods", v1_pod("l2"))
        lst = http._request("GET", "/api/v2alpha1/namespaces/default/pods")
        assert len(lst["items"]) == 2
        for item in lst["items"]:
            assert "scheduling" in item["spec"]
            assert "priority" not in item["spec"]

    def test_watch_events_convert(self, server):
        http, store = server
        w = HTTPWatch(http.host, http.port,
                      "/api/v2alpha1/namespaces/default/pods?watch=true",
                      http._headers)
        store.create("pods", v1_pod("wv2"))
        ev = w.next(timeout=5.0)
        assert ev is not None
        assert ev.object["apiVersion"] == "v2alpha1"
        assert ev.object["spec"]["scheduling"]["priority"] == 7
        w.stop()

    def test_patch_at_v2_against_v2_shape(self, server):
        http, store = server
        http.create("pods", v1_pod("pv2"))
        http._request(
            "PATCH", "/api/v2alpha1/namespaces/default/pods/pv2",
            {"spec": {"scheduling": {"priority": 42}}},
            content_type="application/strategic-merge-patch+json")
        stored = store.get("pods", "default", "pv2")
        assert stored["spec"]["priority"] == 42
        assert stored["spec"]["schedulerName"] == "default-scheduler"

    def test_status_put_at_v2(self, server):
        http, store = server
        http.create("pods", v1_pod("sv2"))
        got = http._request(
            "GET", "/api/v2alpha1/namespaces/default/pods/sv2")
        got["status"] = {"phase": "Running",
                        "scheduling": {"nominatedNodeName": "nom"}}
        http._request(
            "PUT", "/api/v2alpha1/namespaces/default/pods/sv2/status",
            got)
        stored = store.get("pods", "default", "sv2")
        assert stored["status"]["nominatedNodeName"] == "nom"
        assert "scheduling" not in stored["status"]

    def test_status_write_does_not_touch_spec(self, server):
        """A pod stored WITHOUT schedulerName: a v2 status write must not
        smuggle the v2 default into spec (status endpoints only move
        .status).  The pod is seeded straight into the store — the front
        door now applies v1 write-time defaulting (defaults.go parity),
        so an un-defaulted spec is only reachable from legacy data."""
        http, store = server
        pod = meta.new_object("Pod", "nospec", "default")
        pod["spec"] = {"containers": [{"name": "c"}]}
        store.create("pods", pod)
        http._request(
            "PUT", "/api/v2alpha1/namespaces/default/pods/nospec/status",
            {"status": {"phase": "Running"}})
        stored = store.get("pods", "default", "nospec")
        assert stored["status"]["phase"] == "Running"
        assert "schedulerName" not in stored["spec"]
        # status PATCH at v2: same invariant
        http._request(
            "PATCH",
            "/api/v2alpha1/namespaces/default/pods/nospec/status",
            {"status": {"scheduling": {"nominatedNodeName": "n9"}}},
            content_type="application/strategic-merge-patch+json")
        stored = store.get("pods", "default", "nospec")
        assert stored["status"]["nominatedNodeName"] == "n9"
        assert "schedulerName" not in stored["spec"]

    def test_ssa_apply_at_v2_stores_hub_form(self, server):
        http, store = server
        http.create("pods", v1_pod("ssa2"))
        body = {"apiVersion": "v2alpha1", "kind": "Pod",
                "metadata": {"name": "ssa2", "namespace": "default"},
                "spec": {"scheduling": {"priorityClassName": "crit"}}}
        http._request(
            "PATCH", "/api/v2alpha1/namespaces/default/pods/ssa2"
            "?fieldManager=tester&force=true", body,
            content_type="application/apply-patch+yaml")
        stored = store.get("pods", "default", "ssa2")
        assert stored["spec"].get("priorityClassName") == "crit"
        assert "scheduling" not in stored["spec"]  # hub form, not mixed

    def test_bulk_create_at_v2_stores_hub_form(self, server):
        http, store = server
        resp = http._request(
            "POST", "/api/v2alpha1/namespaces/default/pods",
            {"kind": "List", "apiVersion": "v2alpha1", "items": [
                {"metadata": {"name": "blk2"},
                 "spec": {"containers": [{"name": "c"}],
                          "scheduling": {"priority": 5}}}]})
        assert resp["items"][0]["status"] == "Success"
        stored = store.get("pods", "default", "blk2")
        assert stored["spec"]["priority"] == 5
        assert "scheduling" not in stored["spec"]

    def test_unknown_version_404(self, server):
        http, _ = server
        from kubernetes_tpu.client.http_client import HTTPError
        with pytest.raises((kv.NotFoundError, HTTPError)):
            http._request("GET", "/api/v9/namespaces/default/pods")

    def test_unversioned_resource_404_at_v2(self, server):
        http, _ = server
        with pytest.raises(kv.NotFoundError):
            http._request("GET", "/api/v2alpha1/nodes")


class TestV1WriteDefaulting:
    """pkg/apis/core/v1/defaults.go parity for the modeled fields
    (VERDICT r4 missing #5): objects created through the front door
    carry the defaults every reference client may assume."""

    def _serve(self):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        from kubernetes_tpu.store import kv
        store = kv.MemoryStore()
        server = APIServer(store).start()
        return server, HTTPClient.from_url(server.url)

    def test_pod_spec_and_container_defaults(self):
        server, client = self._serve()
        try:
            pod = client.create("pods", {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "d", "namespace": "default"},
                "spec": {"containers": [
                    {"name": "c0", "image": "repo/img",
                     "ports": [{"containerPort": 80}],
                     "livenessProbe": {"httpGet": {"path": "/", "port": 80}}},
                    {"name": "c1", "image": "repo/img:v2"}]}})
            spec = pod["spec"]
            assert spec["restartPolicy"] == "Always"
            assert spec["dnsPolicy"] == "ClusterFirst"
            assert spec["schedulerName"] == "default-scheduler"
            assert spec["terminationGracePeriodSeconds"] == 30
            assert spec["enableServiceLinks"] is True
            c0, c1 = spec["containers"]
            assert c0["imagePullPolicy"] == "Always"       # no tag
            assert c1["imagePullPolicy"] == "IfNotPresent"  # pinned tag
            assert c0["terminationMessagePath"] == "/dev/termination-log"
            assert c0["ports"][0]["protocol"] == "TCP"
            probe = c0["livenessProbe"]
            assert (probe["timeoutSeconds"], probe["periodSeconds"],
                    probe["successThreshold"], probe["failureThreshold"]) \
                == (1, 10, 1, 3)
            assert probe["httpGet"]["scheme"] == "HTTP"
        finally:
            server.stop()

    def test_service_defaults(self):
        server, client = self._serve()
        try:
            svc = client.create("services", {
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "s", "namespace": "default"},
                "spec": {"selector": {"app": "x"},
                         "ports": [{"port": 8080}]}})
            spec = svc["spec"]
            assert spec["type"] == "ClusterIP"
            assert spec["sessionAffinity"] == "None"
            assert spec["ports"][0]["protocol"] == "TCP"
            assert spec["ports"][0]["targetPort"] == 8080
        finally:
            server.stop()

    def test_secret_pv_pvc_defaults(self):
        server, client = self._serve()
        try:
            sec = client.create("secrets", {
                "apiVersion": "v1", "kind": "Secret",
                "metadata": {"name": "s", "namespace": "default"},
                "data": {}})
            assert sec["type"] == "Opaque"
            pv = client.create("persistentvolumes", {
                "apiVersion": "v1", "kind": "PersistentVolume",
                "metadata": {"name": "pv0"},
                "spec": {"capacity": {"storage": "1Gi"},
                         "hostPath": {"path": "/data"}}})
            assert pv["spec"]["persistentVolumeReclaimPolicy"] == "Retain"
            assert pv["spec"]["volumeMode"] == "Filesystem"
            pvc = client.create("persistentvolumeclaims", {
                "apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": {"name": "c", "namespace": "default"},
                "spec": {"resources": {"requests": {"storage": "1Gi"}}}})
            assert pvc["spec"]["volumeMode"] == "Filesystem"
        finally:
            server.stop()

    def test_defaulting_is_idempotent_and_preserves_user_values(self):
        from kubernetes_tpu.api import core_versions as cv
        pod = {"spec": {"restartPolicy": "Never",
                        "containers": [{"name": "c", "image": "i:v1",
                                        "imagePullPolicy": "Always"}]}}
        cv.default_v1("pods", pod)
        once = __import__("copy").deepcopy(pod)
        cv.default_v1("pods", pod)
        assert pod == once
        assert pod["spec"]["restartPolicy"] == "Never"
        assert pod["spec"]["containers"][0]["imagePullPolicy"] == "Always"
