"""Gang-scheduling tests (BASELINE config #4: all-or-nothing binding)."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODGROUPS, PODS
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.scheduler.plugins import DEFAULT_PLUGINS
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod

GANG_PLUGINS = DEFAULT_PLUGINS[:-1] + ["Coscheduling", "DefaultBinder"]


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory, enabled=GANG_PLUGINS)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(fw)})
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    yield store, client, sched
    sched.stop()
    factory.stop()


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def bound_count(client, group):
    items, _ = client.list(PODS)
    return sum(1 for p in items
               if meta.labels(p).get("scheduling.x-k8s.io/pod-group") == group
               and meta.pod_node_name(p))


def make_group(client, name, min_member, timeout=5):
    pg = meta.new_object("PodGroup", name, "default")
    pg["spec"] = {"minMember": min_member, "scheduleTimeoutSeconds": timeout}
    client.create(PODGROUPS, pg)


def gang_pod(name, group, cpu="100m"):
    return (make_pod(name).labels(**{"scheduling.x-k8s.io/pod-group": group})
            .req(cpu=cpu).build())


class TestCoscheduling:
    def test_gang_binds_together(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g1", 3)
        for i in range(3):
            client.create(PODS, gang_pod(f"g1-{i}", "g1"))
        assert wait_for(lambda: bound_count(client, "g1") == 3, timeout=15)

    def test_partial_gang_never_binds(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g2", 3, timeout=1)
        for i in range(2):  # only 2 of 3 members exist
            client.create(PODS, gang_pod(f"g2-{i}", "g2"))
        time.sleep(1.5)
        assert bound_count(client, "g2") == 0

    def test_gang_completes_when_member_arrives(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g3", 3)
        for i in range(2):
            client.create(PODS, gang_pod(f"g3-{i}", "g3"))
        time.sleep(0.4)
        assert bound_count(client, "g3") == 0
        client.create(PODS, gang_pod("g3-2", "g3"))
        assert wait_for(lambda: bound_count(client, "g3") == 3, timeout=15)

    def test_group_status_updated(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g4", 2)
        for i in range(2):
            client.create(PODS, gang_pod(f"g4-{i}", "g4"))
        assert wait_for(lambda: bound_count(client, "g4") == 2, timeout=15)
        assert wait_for(lambda: (client.get(PODGROUPS, "default", "g4")
                                 .get("status") or {}).get("phase") == "Scheduled")

    def test_non_gang_pods_unaffected(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").build())
        client.create(PODS, make_pod("plain").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "plain")) == "n1")
