"""Gang-scheduling tests (BASELINE config #4: all-or-nothing binding)."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODGROUPS, PODS
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.scheduler.plugins import DEFAULT_PLUGINS
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod

GANG_PLUGINS = DEFAULT_PLUGINS[:-1] + ["Coscheduling", "DefaultBinder"]


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory, enabled=GANG_PLUGINS)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(fw)})
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    yield store, client, sched
    sched.stop()
    factory.stop()


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def bound_count(client, group):
    items, _ = client.list(PODS)
    return sum(1 for p in items
               if meta.labels(p).get("scheduling.x-k8s.io/pod-group") == group
               and meta.pod_node_name(p))


def make_group(client, name, min_member, timeout=5):
    pg = meta.new_object("PodGroup", name, "default")
    pg["spec"] = {"minMember": min_member, "scheduleTimeoutSeconds": timeout}
    client.create(PODGROUPS, pg)


def gang_pod(name, group, cpu="100m"):
    return (make_pod(name).labels(**{"scheduling.x-k8s.io/pod-group": group})
            .req(cpu=cpu).build())


class TestCoscheduling:
    def test_gang_binds_together(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g1", 3)
        for i in range(3):
            client.create(PODS, gang_pod(f"g1-{i}", "g1"))
        assert wait_for(lambda: bound_count(client, "g1") == 3, timeout=15)

    def test_partial_gang_never_binds(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g2", 3, timeout=1)
        for i in range(2):  # only 2 of 3 members exist
            client.create(PODS, gang_pod(f"g2-{i}", "g2"))
        time.sleep(1.5)
        assert bound_count(client, "g2") == 0

    def test_gang_completes_when_member_arrives(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g3", 3)
        for i in range(2):
            client.create(PODS, gang_pod(f"g3-{i}", "g3"))
        time.sleep(0.4)
        assert bound_count(client, "g3") == 0
        client.create(PODS, gang_pod("g3-2", "g3"))
        assert wait_for(lambda: bound_count(client, "g3") == 3, timeout=15)

    def test_group_status_updated(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="4", mem="8Gi").build())
        make_group(client, "g4", 2)
        for i in range(2):
            client.create(PODS, gang_pod(f"g4-{i}", "g4"))
        assert wait_for(lambda: bound_count(client, "g4") == 2, timeout=15)
        assert wait_for(lambda: (client.get(PODGROUPS, "default", "g4")
                                 .get("status") or {}).get("phase") == "Scheduled")

    def test_non_gang_pods_unaffected(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").build())
        client.create(PODS, make_pod("plain").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "plain")) == "n1")


class TestGangAdversarial:
    """The classic gang deadlock paths (VERDICT r2 weak #7): a PodGroup
    straddling batch boundaries under competing load, and a starved
    Permit barrier timing out into Unreserve-all
    (framework/runtime/waiting_pods_map.go semantics)."""

    def _batch_cluster(self, batch_size=4):
        from kubernetes_tpu.ops.backend import TPUBatchBackend
        from kubernetes_tpu.ops.flatten import Caps
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        fw = new_default_framework(client, factory, enabled=GANG_PLUGINS)
        caps = Caps(n_cap=64, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                    s_cap=2, sg_cap=8, asg_cap=8)
        backend = TPUBatchBackend(caps, batch_size=batch_size)
        sched = Scheduler(client, factory, {"default-scheduler": Profile(
            fw, batch_backend=backend, batch_size=batch_size)})
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        return store, client, factory, sched

    def test_gang_straddling_batches_with_competitors(self):
        """batch_size=4, gang of 10 interleaved with 20 competitors:
        the group fills across >=3 device batches while competitors
        churn through the same pipeline — everything must bind."""
        store, client, factory, sched = self._batch_cluster(batch_size=4)
        try:
            for i in range(4):
                client.create(NODES, make_node(f"bn{i}")
                              .capacity(cpu="16", mem="64Gi").build())
            make_group(client, "bigg", 10, timeout=60)
            order = []
            for i in range(10):
                order.append(gang_pod(f"bigg-{i}", "bigg"))
            for i in range(20):
                order.append(make_pod(f"comp-{i}").req(cpu="100m").build())
            # interleave: gang members arrive spread across batches
            for i in range(30):
                client.create(PODS, order[(i * 7) % 30])
            assert wait_for(lambda: bound_count(client, "bigg") == 10,
                            timeout=60)
            assert wait_for(lambda: sum(
                1 for p in client.list(PODS)[0]
                if meta.pod_node_name(p)) == 30, timeout=60)
        finally:
            sched.stop()
            factory.stop()

    def test_starved_permit_times_out_and_unreserves(self):
        """Gang needs 3 x 1cpu but the cluster only fits 2: the two
        assumed members hold capacity at the Permit barrier until the
        group timeout, then Unreserve must release it — proven by a
        plain pod that only fits AFTER the release."""
        store, client, factory, sched = self._batch_cluster(batch_size=8)
        try:
            for i in range(2):
                client.create(NODES, make_node(f"tiny{i}")
                              .capacity(cpu="1", mem="4Gi").build())
            make_group(client, "doomed", 3, timeout=6)
            for i in range(3):
                client.create(PODS, gang_pod(f"doomed-{i}", "doomed",
                                             cpu="1"))
            # two members assume (hold 2/2 cpus) and WAIT; a competitor
            # needing 1 cpu is starved while the barrier holds
            time.sleep(1.0)
            client.create(PODS, make_pod("victim").req(cpu="1").build())
            time.sleep(0.5)
            assert not meta.pod_node_name(
                client.get(PODS, "default", "victim"))
            # kill one member: the group can never reach minMember
            # again, so the ONLY thing that can free the assumed cpus
            # is the barrier timing out into Unreserve — if that path
            # leaked, the victim would stay starved forever
            client.delete(PODS, "default", "doomed-2")
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "victim")), timeout=30)
            assert bound_count(client, "doomed") == 0  # all-or-nothing
        finally:
            sched.stop()
            factory.stop()
