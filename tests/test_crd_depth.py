"""CRD depth: structural pruning/defaulting, CEL rules, multi-version
conversion (None + Webhook), status/scale subresources.

Reference subsystems: apiextensions-apiserver pkg/apiserver/schema/
{pruning,defaulting,cel}, pkg/apiserver/conversion, and the
customresource registry's subresource handling.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver import cel
from kubernetes_tpu.client.http_client import HTTPClient, HTTPError
from kubernetes_tpu.store import kv


@pytest.fixture()
def server():
    store = kv.MemoryStore()
    srv = APIServer(store).start()
    http = HTTPClient.from_url(srv.url)
    yield srv, http
    srv.stop()


def make_crd(http, name, group, plural, kind, versions, extra_spec=None):
    crd = meta.new_object("CustomResourceDefinition", name, None)
    crd["spec"] = {"group": group, "scope": "Namespaced",
                   "names": {"plural": plural, "kind": kind},
                   "versions": versions, **(extra_spec or {})}
    http.create("customresourcedefinitions", crd)
    return crd


def gv_request(http, method, group, version, plural, ns="default",
               name=None, body=None, subresource=None):
    path = f"/apis/{group}/{version}/namespaces/{ns}/{plural}"
    if name:
        path += f"/{name}"
    if subresource:
        path += f"/{subresource}"
    return http._request(method, path, body)


SCHEMA = {
    "type": "object",
    "properties": {
        "spec": {
            "type": "object",
            "properties": {
                "replicas": {"type": "integer", "default": 1},
                "mode": {"type": "string", "default": "auto"},
                "limit": {"type": "integer"},
                "blob": {"type": "object",
                         "x-kubernetes-preserve-unknown-fields": True},
            },
            "x-kubernetes-validations": [
                {"rule": "self.replicas <= 10",
                 "message": "replicas must be at most 10"},
                {"rule": "!has(self.limit) || self.replicas <= self.limit"},
            ],
        },
        "status": {"type": "object",
                   "properties": {"replicas": {"type": "integer"}}},
    },
}


class TestPruningDefaultingCEL:
    def _establish(self, http):
        make_crd(http, "things.d.io", "d.io", "things", "Thing",
                 [{"name": "v1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": SCHEMA}}])

    def test_unknown_fields_pruned_defaults_applied(self, server):
        srv, http = server
        self._establish(http)
        obj = meta.new_object("Thing", "t1", "default")
        obj["spec"] = {"junk": "dropme", "limit": 5,
                       "blob": {"anything": {"goes": 1}}}
        created = gv_request(http, "POST", "d.io", "v1",
                             "things", body=obj)
        assert "junk" not in created["spec"]          # pruned
        assert created["spec"]["replicas"] == 1       # defaulted
        assert created["spec"]["mode"] == "auto"      # defaulted
        assert created["spec"]["blob"] == {"anything": {"goes": 1}}

    def test_cel_rule_rejects_write(self, server):
        srv, http = server
        self._establish(http)
        obj = meta.new_object("Thing", "t2", "default")
        obj["spec"] = {"replicas": 11}
        with pytest.raises(HTTPError) as exc:
            gv_request(http, "POST", "d.io", "v1", "things", body=obj)
        assert exc.value.code == 422
        assert "at most 10" in str(exc.value)
        # cross-field rule
        obj["spec"] = {"replicas": 5, "limit": 3}
        with pytest.raises(HTTPError) as exc:
            gv_request(http, "POST", "d.io", "v1", "things", body=obj)
        assert exc.value.code == 422

    def test_cel_rule_on_update_sees_old_self(self, server):
        srv, http = server
        make_crd(http, "counters.d.io", "d.io", "counters", "Counter",
                 [{"name": "v1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": {
                       "type": "object",
                       "properties": {"spec": {
                           "type": "object",
                           "properties": {"value": {"type": "integer"}},
                           "x-kubernetes-validations": [
                               {"rule": "!has(oldSelf.value) || "
                                        "self.value >= oldSelf.value",
                                "message": "value may only grow"}],
                       }}}}}])
        obj = meta.new_object("Counter", "c1", "default")
        obj["spec"] = {"value": 5}
        created = gv_request(http, "POST", "d.io", "v1", "counters",
                             body=obj)
        created["spec"]["value"] = 7
        updated = gv_request(http, "PUT", "d.io", "v1", "counters",
                             name="c1", body=created)
        updated["spec"]["value"] = 3  # shrink: transition rule fires
        with pytest.raises(HTTPError) as exc:
            gv_request(http, "PUT", "d.io", "v1", "counters",
                       name="c1", body=updated)
        assert exc.value.code == 422
        assert "only grow" in str(exc.value)

    def test_unserved_version_rejected(self, server):
        srv, http = server
        self._establish(http)
        obj = meta.new_object("Thing", "t3", "default")
        obj["spec"] = {}
        with pytest.raises(HTTPError) as exc:
            gv_request(http, "POST", "d.io", "v2", "things", body=obj)
        assert exc.value.code == 422


class TestMultiVersion:
    def test_none_strategy_serves_both_versions(self, server):
        srv, http = server
        schema = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {
                "size": {"type": "integer"}}}}}
        make_crd(http, "boxes.mv.io", "mv.io", "boxes", "Box",
                 [{"name": "v1beta1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": schema}},
                  {"name": "v1", "served": True, "storage": False,
                   "schema": {"openAPIV3Schema": schema}}])
        obj = meta.new_object("Box", "b1", "default")
        obj["apiVersion"] = "mv.io/v1"
        obj["spec"] = {"size": 3}
        created = gv_request(http, "POST", "mv.io", "v1", "boxes",
                             body=obj)
        # stored at the storage version...
        raw = srv.store.get("boxes", "default", "b1")
        assert raw["apiVersion"] == "mv.io/v1beta1"
        # ...served back at whichever version is asked
        at_v1 = gv_request(http, "GET", "mv.io", "v1", "boxes",
                           name="b1")
        assert at_v1["apiVersion"] == "mv.io/v1"
        at_beta = gv_request(http, "GET", "mv.io", "v1beta1", "boxes",
                             name="b1")
        assert at_beta["apiVersion"] == "mv.io/v1beta1"
        assert at_v1["spec"]["size"] == 3

    def test_two_storage_versions_rejected(self, server):
        srv, http = server
        with pytest.raises(HTTPError) as exc:
            make_crd(http, "bad.mv.io", "mv.io", "bads", "Bad",
                     [{"name": "v1", "served": True, "storage": True},
                      {"name": "v2", "served": True, "storage": True}])
        assert exc.value.code == 422

    def test_webhook_conversion(self, server):
        """A conversion webhook that renames spec.size <-> spec.count
        between versions (conversion/converter.go webhook path)."""
        srv, http = server

        class Hook(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                length = int(self.headers["Content-Length"])
                review = json.loads(self.rfile.read(length))
                want = review["request"]["desiredAPIVersion"]
                out = []
                for obj in review["request"]["objects"]:
                    obj = dict(obj, apiVersion=want)
                    spec = dict(obj.get("spec") or {})
                    if want.endswith("/v2") and "size" in spec:
                        spec["count"] = spec.pop("size")
                    elif want.endswith("/v1") and "count" in spec:
                        spec["size"] = spec.pop("count")
                    obj["spec"] = spec
                    out.append(obj)
                body = json.dumps({"response": {
                    "uid": review["request"]["uid"],
                    "convertedObjects": out,
                    "result": {"status": "Success"}}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        hook_server = HTTPServer(("127.0.0.1", 0), Hook)
        threading.Thread(target=hook_server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{hook_server.server_address[1]}/convert"
        try:
            make_crd(http, "jars.wh.io", "wh.io", "jars", "Jar",
                     [{"name": "v1", "served": True, "storage": True},
                      {"name": "v2", "served": True, "storage": False}],
                     extra_spec={"conversion": {
                         "strategy": "Webhook",
                         "webhook": {"clientConfig": {"url": url}}}})
            obj = meta.new_object("Jar", "j1", "default")
            obj["apiVersion"] = "wh.io/v2"
            obj["spec"] = {"count": 4}
            gv_request(http, "POST", "wh.io", "v2", "jars", body=obj)
            raw = srv.store.get("jars", "default", "j1")
            assert raw["apiVersion"] == "wh.io/v1"
            assert raw["spec"] == {"size": 4}  # webhook renamed on store
            at_v2 = gv_request(http, "GET", "wh.io", "v2", "jars",
                               name="j1")
            assert at_v2["apiVersion"] == "wh.io/v2"
            assert at_v2["spec"] == {"count": 4}  # renamed back on read
        finally:
            hook_server.shutdown()
            hook_server.server_close()


class TestCRDSubresources:
    def test_status_gated_by_declaration(self, server):
        srv, http = server
        make_crd(http, "plain.sub.io", "sub.io", "plains", "Plain",
                 [{"name": "v1", "served": True, "storage": True}])
        make_crd(http, "rich.sub.io", "sub.io", "riches", "Rich",
                 [{"name": "v1", "served": True, "storage": True}],
                 extra_spec={"subresources": {
                     "status": {},
                     "scale": {"specReplicasPath": ".spec.replicas",
                               "statusReplicasPath": ".status.replicas"}}})
        for plural, kind, name in (("plains", "Plain", "p1"),
                                   ("riches", "Rich", "r1")):
            obj = meta.new_object(kind, name, "default")
            obj["spec"] = {"replicas": 2}
            gv_request(http, "POST", "sub.io", "v1", plural, body=obj)
        # undeclared -> 404
        with pytest.raises(kv.NotFoundError):
            gv_request(http, "PUT", "sub.io", "v1", "plains", name="p1",
                       body={"status": {"replicas": 2}},
                       subresource="status")
        # declared -> works
        updated = gv_request(http, "PUT", "sub.io", "v1", "riches",
                             name="r1",
                             body={"status": {"replicas": 2}},
                             subresource="status")
        assert updated["status"]["replicas"] == 2

    def test_scale_paths(self, server):
        srv, http = server
        make_crd(http, "flocks.sub.io", "sub.io", "flocks", "Flock",
                 [{"name": "v1", "served": True, "storage": True}],
                 extra_spec={"subresources": {
                     "scale": {"specReplicasPath": ".spec.instances",
                               "statusReplicasPath":
                                   ".status.readyInstances"}}})
        obj = meta.new_object("Flock", "f1", "default")
        obj["spec"] = {"instances": 3}
        obj["status"] = {"readyInstances": 1}
        gv_request(http, "POST", "sub.io", "v1", "flocks", body=obj)
        scale = gv_request(http, "GET", "sub.io", "v1", "flocks",
                           name="f1", subresource="scale")
        assert scale["kind"] == "Scale"
        assert scale["spec"]["replicas"] == 3
        assert scale["status"]["replicas"] == 1
        gv_request(http, "PUT", "sub.io", "v1", "flocks", name="f1",
                   body={"spec": {"replicas": 7}}, subresource="scale")
        raw = srv.store.get("flocks", "default", "f1")
        assert raw["spec"]["instances"] == 7


class TestReviewRegressions:
    def test_transition_rule_skipped_on_create(self, server):
        """A rule referencing oldSelf must not block CREATE."""
        srv, http = server
        make_crd(http, "grows.rr.io", "rr.io", "grows", "Grow",
                 [{"name": "v1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": {
                       "type": "object",
                       "properties": {"spec": {
                           "type": "object",
                           "properties": {"n": {"type": "integer"}},
                           "x-kubernetes-validations": [
                               {"rule": "self.n >= oldSelf.n"}]}}}}}])
        obj = meta.new_object("Grow", "g1", "default")
        obj["spec"] = {"n": 1}
        created = gv_request(http, "POST", "rr.io", "v1", "grows",
                             body=obj)  # must not 422
        created["spec"]["n"] = 0
        with pytest.raises(HTTPError):  # but the update rule still bites
            gv_request(http, "PUT", "rr.io", "v1", "grows", name="g1",
                       body=created)

    def test_map_values_pruned_and_defaulted(self, server):
        srv, http = server
        make_crd(http, "maps.rr.io", "rr.io", "mapthings", "MapThing",
                 [{"name": "v1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": {
                       "type": "object",
                       "properties": {"spec": {
                           "type": "object",
                           "additionalProperties": {
                               "type": "object",
                               "properties": {
                                   "weight": {"type": "integer",
                                              "default": 10}}}}}}}}])
        obj = meta.new_object("MapThing", "m1", "default")
        obj["spec"] = {"zone-a": {"weight": 2, "junk": True},
                       "zone-b": {}}
        created = gv_request(http, "POST", "rr.io", "v1", "mapthings",
                             body=obj)
        assert created["spec"]["zone-a"] == {"weight": 2}  # junk pruned
        assert created["spec"]["zone-b"] == {"weight": 10}  # defaulted

    def test_get_scale_undeclared_404(self, server):
        srv, http = server
        make_crd(http, "nos.rr.io", "rr.io", "nos", "No",
                 [{"name": "v1", "served": True, "storage": True}])
        obj = meta.new_object("No", "n1", "default")
        obj["spec"] = {"replicas": 1}
        gv_request(http, "POST", "rr.io", "v1", "nos", body=obj)
        with pytest.raises(kv.NotFoundError):
            gv_request(http, "GET", "rr.io", "v1", "nos", name="n1",
                       subresource="scale")

    def test_webhook_down_read_is_500_not_crash(self, server):
        srv, http = server
        make_crd(http, "deads.rr.io", "rr.io", "deads", "Dead",
                 [{"name": "v1", "served": True, "storage": True},
                  {"name": "v2", "served": True, "storage": False}],
                 extra_spec={"conversion": {
                     "strategy": "Webhook",
                     "webhook": {"clientConfig": {
                         "url": "http://127.0.0.1:1/convert"}}}})
        obj = meta.new_object("Dead", "d1", "default")
        obj["apiVersion"] = "rr.io/v1"
        obj["spec"] = {}
        gv_request(http, "POST", "rr.io", "v1", "deads", body=obj)
        with pytest.raises(HTTPError) as exc:  # not a dropped conn
            gv_request(http, "GET", "rr.io", "v2", "deads", name="d1")
        assert exc.value.code == 500


class TestCELUnit:
    def test_subset_behaviors(self):
        obj = {"a": [1, 2, 3], "s": "hello", "m": {"k": True}}
        assert cel.evaluate("self.a.map(x, x * 2) == [2, 4, 6]", obj)
        assert cel.evaluate("self.a.filter(x, x > 1) == [2, 3]", obj)
        assert cel.evaluate("self.a.exists_one(x, x == 2)", obj)
        assert cel.evaluate("self.s.contains('ell')", obj)
        assert cel.evaluate("size(self.m) == 1", obj)
        assert cel.evaluate("'x' + 'y' == 'xy'", obj)
        assert cel.evaluate("7 / 2 == 3 && 7 % 2 == 1", obj)
        with pytest.raises(cel.CELError):
            cel.evaluate("1 / 0 == 1", obj)
        with pytest.raises(cel.CELError):
            cel.evaluate("self.a", obj)  # non-boolean result

    def test_division_truncates_toward_zero(self):
        # CEL is C-like: -7/2 == -3 (Python floor would say -4)
        assert cel.evaluate("0 - 7 / 2 == 0 - 3", {})
        assert cel.evaluate("(0 - 7) % 2 == 0 - 1", {})
        assert cel.evaluate("7 / 2 == 3 && 7 % 2 == 1", {})


class TestWatchConversion:
    def test_watch_serves_requested_version(self, server):
        """A watch at the non-storage version must deliver events whose
        objects are converted on the way out (conversion applies to the
        whole read surface, watches included)."""
        import time

        from kubernetes_tpu.client.http_client import HTTPWatch
        srv, http = server
        schema = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {
                "n": {"type": "integer"}}}}}
        make_crd(http, "streams.wc.io", "wc.io", "streamers", "Streamer",
                 [{"name": "v1beta1", "served": True, "storage": True,
                   "schema": {"openAPIV3Schema": schema}},
                  {"name": "v1", "served": True, "storage": False,
                   "schema": {"openAPIV3Schema": schema}}])
        w = HTTPWatch(srv.httpd.server_address[0], srv.port,
                      "/apis/wc.io/v1/namespaces/default/streamers"
                      "?watch=true", {})
        try:
            obj = meta.new_object("Streamer", "s1", "default")
            obj["apiVersion"] = "wc.io/v1beta1"
            obj["spec"] = {"n": 1}
            gv_request(http, "POST", "wc.io", "v1beta1", "streamers",
                       body=obj)
            deadline = time.monotonic() + 15
            ev = None
            while ev is None and time.monotonic() < deadline:
                ev = w.next(timeout=1.0)
            assert ev is not None, "watch event never arrived"
            # stored at v1beta1, but THIS watch asked for v1
            assert ev.object["apiVersion"] == "wc.io/v1"
            assert ev.object["spec"]["n"] == 1
        finally:
            w.stop()
