"""Scheduler cache debugger (dump/compare) + kubeadm join discovery.

Behavioral contracts from pkg/scheduler/internal/cache/debugger and
cmd/kubeadm/app/phases/bootstraptoken.
"""

import base64
import hashlib
import hmac
import time

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.scheduler.debugger import CacheDebugger
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def wait_for(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestCacheDebugger:
    def test_dump_and_compare(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        fw = new_default_framework(client, factory)
        sched = Scheduler(client, factory, {"default-scheduler": Profile(fw)})
        factory.start()
        factory.wait_for_cache_sync()
        try:
            client.create(NODES, make_node("dbg-1").build())
            client.create(PODS, make_pod("p1").node("dbg-1").build())
            assert wait_for(lambda: sched.cache.node_count() == 1)
            dbg = CacheDebugger(sched, client)
            dump = dbg.dump()
            assert dump["cache"]["nodes"] == {"dbg-1": 1}
            diff = dbg.compare()
            assert diff["nodes"] == {"missing": [], "extra": []}
            assert diff["pods"] == {"missing": [], "extra": []}
            # poison the cache: remove the node behind the informer's back
            sched.cache.remove_node(make_node("dbg-1").build())
            diff = dbg.compare()
            assert diff["nodes"]["missing"] == ["dbg-1"]
        finally:
            factory.stop()


class TestKubeadmDiscovery:
    def test_signature_validates_and_rejects(self):
        # the exact verification join() performs, against BootstrapSigner's
        # published signature
        kubeconfig = "apiVersion: v1\nkind: Config\n"
        secret = "s3cret"
        sig = base64.urlsafe_b64encode(hmac.new(
            secret.encode(), kubeconfig.encode(),
            hashlib.sha256).digest()).decode("ascii")
        good = base64.urlsafe_b64encode(hmac.new(
            b"s3cret", kubeconfig.encode(),
            hashlib.sha256).digest()).decode("ascii")
        assert hmac.compare_digest(sig, good)
        bad = base64.urlsafe_b64encode(hmac.new(
            b"wrong", kubeconfig.encode(),
            hashlib.sha256).digest()).decode("ascii")
        assert not hmac.compare_digest(sig, bad)
