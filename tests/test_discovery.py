"""Server-side discovery + OpenAPI (endpoints/discovery/, kube-openapi).

The contract under test: a client that knows NOTHING but the server URL
can enumerate groups/versions/resources — including CRD-defined kinds —
and kubectl resolves resources from these endpoints, not its baked-in
table.
"""

import io
import json
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.cli.kubectl import Kubectl
from kubernetes_tpu.client.http_client import HTTPClient
from kubernetes_tpu.store import kv


@pytest.fixture(scope="module")
def server():
    store = kv.MemoryStore()
    srv = APIServer(store).start()
    http = HTTPClient.from_url(srv.url)
    # a CRD so discovery covers dynamically-added resources
    crd = meta.new_object("CustomResourceDefinition", "crontabs.stable.tpu",
                          None)
    crd["spec"] = {
        "group": "stable.tpu",
        "scope": "Namespaced",
        "names": {"plural": "crontabs", "kind": "CronTab",
                  "singular": "crontab", "shortNames": ["ct"]},
        "versions": [{"name": "v1", "served": True, "storage": True,
                      "schema": {"openAPIV3Schema": {
                          "type": "object",
                          "properties": {"spec": {"type": "object"}}}}}],
    }
    http.create("customresourcedefinitions", crd)
    yield srv, http
    srv.stop()


def fetch(srv, path):
    with urllib.request.urlopen(srv.url + path) as resp:
        return json.loads(resp.read())


class TestDiscovery:
    def test_api_versions(self, server):
        srv, _ = server
        doc = fetch(srv, "/api")
        assert doc["kind"] == "APIVersions"
        assert "v1" in doc["versions"]  # hub; extra served versions OK

    def test_core_resources(self, server):
        srv, _ = server
        doc = fetch(srv, "/api/v1")
        assert doc["kind"] == "APIResourceList"
        by_name = {r["name"]: r for r in doc["resources"]}
        assert by_name["pods"]["kind"] == "Pod"
        assert by_name["pods"]["namespaced"] is True
        assert by_name["nodes"]["namespaced"] is False
        assert "po" in by_name["pods"]["shortNames"]
        # subresources surface (exec/log/token are real routes now)
        assert by_name["pods/exec"]["verbs"] == ["create", "get"]
        assert by_name["pods/log"]["verbs"] == ["get"]
        assert by_name["serviceaccounts/token"]["verbs"] == ["create"]

    def test_group_list_includes_crd_group(self, server):
        srv, _ = server
        doc = fetch(srv, "/apis")
        groups = {g["name"]: g for g in doc["groups"]}
        assert "apps" in groups
        assert "stable.tpu" in groups
        apps = groups["apps"]
        assert apps["preferredVersion"]["groupVersion"] == "apps/v1"
        assert {"groupVersion": "apps/v1", "version": "v1"} \
            in apps["versions"]
        assert groups["autoscaling"]["preferredVersion"][
            "groupVersion"] == "autoscaling/v2"

    def test_group_detail_and_resources(self, server):
        srv, _ = server
        doc = fetch(srv, "/apis/apps")
        assert doc["kind"] == "APIGroup" and doc["name"] == "apps"
        rl = fetch(srv, "/apis/apps/v1")
        by_name = {r["name"]: r for r in rl["resources"]}
        assert by_name["deployments"]["kind"] == "Deployment"
        assert "deploy" in by_name["deployments"]["shortNames"]
        assert by_name["deployments/scale"]["kind"] == "Scale"

    def test_crd_resources_served(self, server):
        srv, _ = server
        rl = fetch(srv, "/apis/stable.tpu/v1")
        by_name = {r["name"]: r for r in rl["resources"]}
        assert by_name["crontabs"]["kind"] == "CronTab"
        assert by_name["crontabs"]["shortNames"] == ["ct"]

    def test_unknown_group_404(self, server):
        srv, _ = server
        with pytest.raises(urllib.error.HTTPError) as exc:
            fetch(srv, "/apis/no.such.group")
        assert exc.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            fetch(srv, "/apis/apps/v9")
        assert exc.value.code == 404

    def test_openapi_v2(self, server):
        srv, _ = server
        doc = fetch(srv, "/openapi/v2")
        assert doc["swagger"] == "2.0"
        assert "/api/v1/namespaces/{namespace}/pods" in doc["paths"]
        assert ("/apis/apps/v1/namespaces/{namespace}/deployments"
                in doc["paths"])
        # the CRD embeds its real schema
        ct = doc["definitions"]["stable.tpu/v1.CronTab"]
        assert ct["properties"]["spec"]["type"] == "object"

    def test_openapi_v3_index_and_group_docs(self, server):
        """kube-openapi handler3 shape: /openapi/v3 is a discovery index
        of per-group-version documents; each doc is OpenAPI 3.0 with
        components.schemas and rewritten $refs."""
        srv, _ = server
        idx = fetch(srv, "/openapi/v3")
        paths = idx["paths"]
        assert paths["api/v1"]["serverRelativeURL"] == "/openapi/v3/api/v1"
        assert "apis/apps/v1" in paths
        assert "apis/stable.tpu/v1" in paths  # CRD group listed
        assert "api/v2alpha1" in paths        # versioned core group
        doc = fetch(srv, "/openapi/v3/apis/apps/v1")
        assert doc["openapi"] == "3.0.0"
        assert ("/apis/apps/v1/namespaces/{namespace}/deployments"
                in doc["paths"])
        schemas = doc["components"]["schemas"]
        # refs rewritten from swagger-2 definitions to v3 components
        dep = schemas["apps/v1.Deployment"]
        ref = dep["properties"]["metadata"]["$ref"]
        assert ref == "#/components/schemas/v1.ObjectMeta"
        core = fetch(srv, "/openapi/v3/api/v1")
        assert "/api/v1/namespaces/{namespace}/pods" in core["paths"]
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as exc:
            fetch(srv, "/openapi/v3/apis/no.such/v1")
        assert exc.value.code == 404
        # non-index keys must 404, not return merged catch-all docs
        for bad in ("/openapi/v3/apis", "/openapi/v3/apis/apps"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                fetch(srv, bad)
            assert exc.value.code == 404, bad
        # non-hub core versions carry their real routes, never empty
        v2a = fetch(srv, "/openapi/v3/api/v2alpha1")
        assert ("/api/v2alpha1/namespaces/{namespace}/pods"
                in v2a["paths"])


class TestKubectlDiscovery:
    def test_crd_kind_resolves_via_discovery(self, server):
        srv, http = server
        obj = meta.new_object("CronTab", "nightly", "default")
        obj["spec"] = {}
        http.create("crontabs", obj)
        for alias in ("ct", "crontab", "CronTab", "crontabs"):
            out = io.StringIO()
            k = Kubectl(http, out)
            assert k.get(alias, None, "default", None) == 0, alias
            assert "nightly" in out.getvalue(), alias

    def test_beta_only_crd_group_resolves(self, server):
        """A group served ONLY at v1beta1 must advertise that version
        as preferred (no phantom v1) and resolve through kubectl."""
        srv, http = server
        crd = meta.new_object("CustomResourceDefinition",
                              "widgets.acme.io", None)
        crd["spec"] = {
            "group": "acme.io", "scope": "Namespaced",
            "names": {"plural": "widgets", "kind": "Widget",
                      "shortNames": ["wg"]},
            "versions": [{"name": "v1beta1", "served": True,
                          "storage": True}],
        }
        http.create("customresourcedefinitions", crd)
        groups = {g["name"]: g for g in fetch(srv, "/apis")["groups"]}
        assert groups["acme.io"]["preferredVersion"][
            "groupVersion"] == "acme.io/v1beta1"
        obj = meta.new_object("Widget", "w1", "default")
        http.create("widgets", obj)
        out = io.StringIO()
        k = Kubectl(http, out)
        assert k.get("wg", None, "default", None) == 0
        assert "w1" in out.getvalue()
        # ...and the bad group didn't truncate the rest of the map
        assert k.resolve("CronTab") == "crontabs"
        assert k.resolve("deploy") == "deployments"

    def test_crd_applied_via_kubectl_establishes_and_serves(
            self, server, tmp_path):
        """kubectl apply of a CRD + an instance of it in sequence: the
        SSA create path must establish the CRD (not just POST), and
        kubectl must re-discover mid-run to resolve the new kind."""
        srv, http = server
        import yaml as yamllib
        crd_f = tmp_path / "crd.yaml"
        crd_f.write_text(yamllib.safe_dump({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": "gadgets.apply.io"},
            "spec": {"group": "apply.io", "scope": "Namespaced",
                     "names": {"plural": "gadgets", "kind": "Gadget",
                               "shortNames": ["gd"]},
                     "versions": [{"name": "v1", "served": True,
                                   "storage": True}]}}))
        inst_f = tmp_path / "gadget.yaml"
        inst_f.write_text(yamllib.safe_dump({
            "apiVersion": "apply.io/v1", "kind": "Gadget",
            "metadata": {"name": "g1"}, "spec": {}}))
        out = io.StringIO()
        k = Kubectl(http, out)
        assert k.apply(str(crd_f), "default") == 0
        assert k.apply(str(inst_f), "default") == 0, out.getvalue()
        out2 = io.StringIO()
        k2 = Kubectl(http, out2)
        assert k2.get("gd", None, "default", None) == 0
        assert "g1" in out2.getvalue()
        rl = fetch(srv, "/apis/apply.io/v1")
        assert any(r["name"] == "gadgets" for r in rl["resources"])

    def test_static_aliases_need_no_request(self, server):
        srv, http = server
        k = Kubectl(http, io.StringIO())
        assert k.resolve("po") == "pods"
        assert k._discovery is None  # no discovery round-trip burned
