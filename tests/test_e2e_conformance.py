"""E2E conformance tier: everything over the REAL HTTP API against a live
in-process control plane (apiserver + scheduler + controllers + hollow
nodes) — the reference's test/e2e shape (ginkgo suites against a running
cluster), reduced to the core conformance behaviors:

  - workloads: Deployment -> ReplicaSet -> Pods scheduled and Running
  - services: selector -> EndpointSlice -> kube-proxy routes to a backend
  - storage: PVC -> dynamic provisioning -> Bound, protection finalizer
  - scheduling: taints keep pods off tainted nodes until tolerated
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.http_client import HTTPClient
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.kubelet import start_hollow_nodes
from kubernetes_tpu.proxy.proxier import ServiceProxy
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import wait_for


@pytest.fixture(scope="module")
def e2e():
    """A full cluster; the TEST talks to it exclusively over HTTP."""
    store = kv.MemoryStore(history=1_000_000)
    server = APIServer(store).start()
    local = LocalClient(store)
    factory = SharedInformerFactory(local)
    fw = new_default_framework(local, factory)
    sched = Scheduler(local, factory, {"default-scheduler": Profile(fw)})
    mgr = ControllerManager(local, factory)
    endpoints = EndpointsController(local, factory)
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    mgr.run()
    endpoints.run()
    kubelets = start_hollow_nodes(local, factory, 3)
    proxy = ServiceProxy(local, factory, "hollow-0").start()

    http = HTTPClient.from_url(server.url)
    yield http, proxy
    proxy.stop()
    for k in kubelets:
        k.stop()
    endpoints.stop()
    mgr.stop()
    sched.stop()
    factory.stop()
    server.stop()
    local.close()


def _deploy(http, name, replicas=3):
    dep = meta.new_object("Deployment", name, "default")
    dep["spec"] = {
        "replicas": replicas,
        "selector": {"matchLabels": {"app": name}},
        "template": {"metadata": {"labels": {"app": name}},
                     "spec": {"containers": [{
                         "name": "c0", "image": "img",
                         "resources": {"requests": {"cpu": "100m",
                                                    "memory": "64Mi"}}}]}}}
    http.create("deployments", dep)

    def running():
        pods, _ = http.list("pods", "default")
        mine = [p for p in pods if meta.labels(p).get("app") == name]
        return (len(mine) == replicas
                and all(meta.pod_node_name(p) for p in mine)
                and all((p.get("status") or {}).get("phase") == "Running"
                        for p in mine))
    assert wait_for(running)


def test_workloads_deployment_to_running_pods(e2e):
    http, _ = e2e
    _deploy(http, "conf-web")
    # owner chain: pod -> ReplicaSet -> Deployment
    pods, _ = http.list("pods", "default")
    pod = next(p for p in pods if meta.labels(p).get("app") == "conf-web")
    rs_ref = meta.controller_ref(pod)
    assert rs_ref["kind"] == "ReplicaSet"
    rs = http.get("replicasets", "default", rs_ref["name"])
    assert meta.controller_ref(rs)["kind"] == "Deployment"


def test_service_endpointslice_proxy_path(e2e):
    http, proxy = e2e
    _deploy(http, "conf-be", replicas=2)  # own backends: order-independent
    svc = meta.new_object("Service", "conf-svc", "default")
    svc["spec"] = {"clusterIP": "10.96.7.7", "selector": {"app": "conf-be"},
                   "ports": [{"port": 80, "protocol": "TCP"}]}
    http.create("services", svc)
    assert wait_for(lambda: any(
        meta.labels(sl).get("kubernetes.io/service-name") == "conf-svc"
        and sl.get("endpoints")
        for sl in http.list("endpointslices", "default")[0]))
    assert wait_for(lambda: proxy.route("10.96.7.7", 80) is not None)
    backend_ip, backend_port = proxy.route("10.96.7.7", 80)
    pods, _ = http.list("pods", "default")
    pod_ips = {(p.get("status") or {}).get("podIP") for p in pods}
    assert backend_ip in pod_ips


def test_storage_dynamic_provisioning_and_protection(e2e):
    http, _ = e2e
    sc = meta.new_object("StorageClass", "conf-fast", None)
    sc["provisioner"] = "tpu.kubernetes.io/host-provisioner"
    http.create("storageclasses", sc)
    pvc = meta.new_object("PersistentVolumeClaim", "conf-claim", "default")
    pvc["spec"] = {"accessModes": ["ReadWriteOnce"],
                   "storageClassName": "conf-fast",
                   "resources": {"requests": {"storage": "1Gi"}}}
    http.create("persistentvolumeclaims", pvc)
    assert wait_for(lambda: (http.get("persistentvolumeclaims", "default",
                                      "conf-claim").get("status") or {})
                    .get("phase") == "Bound")
    got = http.get("persistentvolumeclaims", "default", "conf-claim")
    assert "kubernetes.io/pvc-protection" in got["metadata"]["finalizers"]
    pv = http.get("persistentvolumes", "", got["spec"]["volumeName"])
    assert (pv.get("spec") or {}).get("claimRef", {}).get(
        "name") == "conf-claim"


def test_scheduling_taints_and_tolerations(e2e):
    http, _ = e2e

    def taint(n):
        n.setdefault("spec", {})["taints"] = [
            {"key": "conf", "value": "x", "effect": "NoSchedule"}]
        return n

    def untaint(n):
        n.setdefault("spec", {}).pop("taints", None)
        return n

    try:
        # taint every node; an intolerant pod must stay Pending
        for i in range(3):
            http.guaranteed_update("nodes", "", f"hollow-{i}", taint)
        # the scheduler's node informer may lag the taint writes; retry
        # with fresh intolerant pods until one is REJECTED (deterministic:
        # each attempt ends in either a bind — informer lagged, retry — or
        # an Unschedulable condition)
        taint_pod = None
        for attempt in range(10):
            name = f"conf-taint-{attempt}"
            pod = meta.new_object("Pod", name, "default")
            pod["spec"] = {"containers": [{"name": "c0", "image": "img"}],
                           "schedulerName": "default-scheduler"}
            http.create("pods", pod)

            def settled(n=name):
                cur = http.get("pods", "default", n)
                return meta.pod_node_name(cur) or any(
                    c.get("reason") == "Unschedulable"
                    for c in (cur.get("status") or {}).get("conditions")
                    or ())
            assert wait_for(settled)
            if not meta.pod_node_name(http.get("pods", "default", name)):
                taint_pod = name
                break
            http.delete("pods", "default", name)  # raced the informer
        assert taint_pod, "scheduler never observed the taints"
        # tolerating pod schedules
        tpod = meta.new_object("Pod", "conf-tol", "default")
        tpod["spec"] = {"containers": [{"name": "c0", "image": "img"}],
                        "tolerations": [{"key": "conf", "operator": "Exists",
                                         "effect": "NoSchedule"}],
                        "schedulerName": "default-scheduler"}
        http.create("pods", tpod)
        assert wait_for(lambda: meta.pod_node_name(
            http.get("pods", "default", "conf-tol")))
    finally:
        # leave the shared nodes clean for whatever runs after
        for i in range(3):
            http.guaranteed_update("nodes", "", f"hollow-{i}", untaint)
    # untaint -> the pending pod gets picked up on the cluster event
    assert wait_for(lambda: meta.pod_node_name(
        http.get("pods", "default", taint_pod)))
