"""Egress selector (konnectivity seam) + storage-version GC.

Reference:
  staging/src/k8s.io/apiserver/pkg/server/egressselector/egress_selector.go:40
  pkg/controller/storageversiongc/gc_controller.go
"""

import http.server
import socketserver
import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.egress import (
    CLUSTER, EgressSelector, HTTPConnectDialer, default_selector,
)
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import LEASES
from kubernetes_tpu.controllers.storageversion import (
    STORAGEVERSIONS, StorageVersionGC, publish_identity,
    publish_storage_versions,
)
from kubernetes_tpu.store import kv


def wait_for(pred, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class _ConnectProxy(threading.Thread):
    """Tiny HTTP CONNECT proxy: tunnels and counts connections."""

    def __init__(self):
        super().__init__(daemon=True)
        self.tunnels = 0
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_CONNECT(self):
                import socket
                host, _, port = self.path.partition(":")
                upstream = socket.create_connection((host, int(port)))
                outer.tunnels += 1
                self.send_response(200, "Connection Established")
                self.end_headers()
                # bidirectional relay until either side closes
                conns = [self.connection, upstream]
                import select
                while True:
                    r, _, _ = select.select(conns, [], [], 5)
                    if not r:
                        break
                    done = False
                    for s in r:
                        data = s.recv(65536)
                        if not data:
                            done = True
                            break
                        (upstream if s is self.connection
                         else self.connection).sendall(data)
                    if done:
                        break
                upstream.close()

        self.httpd = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                                     Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]

    def run(self):
        self.httpd.serve_forever()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestEgressSelector:
    def test_direct_default(self):
        store = kv.MemoryStore()
        server = APIServer(store).start()
        try:
            sel = EgressSelector()
            req = urllib.request.Request(server.url + "/healthz")
            with sel.open(CLUSTER, req, 5) as resp:
                assert resp.status == 200
        finally:
            server.stop()

    def test_http_connect_dialer_tunnels(self):
        store = kv.MemoryStore()
        server = APIServer(store).start()
        proxy = _ConnectProxy()
        proxy.start()
        try:
            sel = EgressSelector()
            sel.register(CLUSTER, HTTPConnectDialer("127.0.0.1",
                                                    proxy.port))
            req = urllib.request.Request(server.url + "/healthz")
            resp = sel.open(CLUSTER, req, 5)
            import json
            assert json.loads(resp.read())["status"] == "ok"
            assert proxy.tunnels == 1
        finally:
            proxy.stop()
            server.stop()

    def test_aggregator_rides_the_selector(self):
        """The aggregation proxy consults the process-global selector:
        swapping the cluster dialer reroutes aggregated API traffic
        without touching the aggregator."""
        backend = APIServer(kv.MemoryStore()).start()
        front_store = kv.MemoryStore()
        front = APIServer(front_store).start()
        proxy = _ConnectProxy()
        proxy.start()
        try:
            svc = meta.new_object("APIService", "v1.metrics.example.io",
                                  None)
            svc["spec"] = {"group": "metrics.example.io", "version": "v1",
                           "service": {"url": backend.url}}
            front_store.create("apiservices", svc)
            default_selector.register(
                CLUSTER, HTTPConnectDialer("127.0.0.1", proxy.port))
            req = urllib.request.Request(
                front.url + "/apis/metrics.example.io/v1/widgets")
            with urllib.request.urlopen(req, timeout=10):
                pass
            assert proxy.tunnels >= 1
        finally:
            default_selector.reset(CLUSTER)
            proxy.stop()
            front.stop()
            backend.stop()


@pytest.fixture
def gc_env():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    ctrl = StorageVersionGC(client, factory, resync=0.2)
    factory.start()
    factory.wait_for_cache_sync()
    ctrl.run()
    yield store, client, ctrl
    ctrl.stop()
    factory.stop()


class TestStorageVersionGC:
    def test_publish_and_gc_on_lease_delete(self, gc_env):
        store, client, ctrl = gc_env
        publish_identity(client, "apiserver-a")
        publish_identity(client, "apiserver-b")
        publish_storage_versions(client, "apiserver-a")
        publish_storage_versions(client, "apiserver-b")
        sv = store.get(STORAGEVERSIONS, "", "core.pods")
        assert len(sv["status"]["storageVersions"]) == 2
        assert sv["status"]["commonEncodingVersion"] == "v1"

        # server B dies: its lease is deleted -> entries stripped
        client.delete(LEASES, "kube-system", "apiserver-b")
        assert wait_for(lambda: len(
            store.get(STORAGEVERSIONS, "", "core.pods")["status"]
            ["storageVersions"]) == 1)
        left = store.get(STORAGEVERSIONS, "", "core.pods")
        assert left["status"]["storageVersions"][0][
            "apiServerID"] == "apiserver-a"

    def test_sv_object_deleted_when_no_servers_remain(self, gc_env):
        store, client, ctrl = gc_env
        publish_identity(client, "apiserver-x")
        publish_storage_versions(client, "apiserver-x", resources=("pods",))
        client.delete(LEASES, "kube-system", "apiserver-x")

        def gone():
            try:
                store.get(STORAGEVERSIONS, "", "core.pods")
                return False
            except kv.NotFoundError:
                return True
        assert wait_for(gone)

    def test_expired_lease_is_dead(self, gc_env):
        store, client, ctrl = gc_env
        publish_identity(client, "apiserver-old")
        publish_storage_versions(client, "apiserver-old",
                                 resources=("pods",))
        # age the lease past its TTL (no delete event — the periodic
        # sweep must catch it)
        def age(cur):
            cur["spec"]["renewTime"] = time.time() - 3600
            return cur
        client.guaranteed_update(LEASES, "kube-system", "apiserver-old",
                                 age)

        def gone():
            try:
                store.get(STORAGEVERSIONS, "", "core.pods")
                return False
            except kv.NotFoundError:
                return True
        assert wait_for(gone)

    def test_renewal_keeps_entries(self, gc_env):
        store, client, ctrl = gc_env
        publish_identity(client, "apiserver-live")
        publish_storage_versions(client, "apiserver-live",
                                 resources=("pods",))
        time.sleep(0.6)  # several sweep cycles
        sv = store.get(STORAGEVERSIONS, "", "core.pods")
        assert len(sv["status"]["storageVersions"]) == 1
