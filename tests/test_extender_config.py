"""HTTP extender + KubeSchedulerConfiguration tests.

Reference shapes: pkg/scheduler/extender_test.go (with a live HTTP test
server, like testing/fake_extender.go), pkg/scheduler/apis/config/
validation tests, apis/config/v1/default_plugins_test.go merge rules.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS, LocalClient
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.scheduler.config import (
    ConfigError, build_framework_from_profile, load_config,
    scheduler_from_config,
)
from kubernetes_tpu.scheduler.extender import HTTPExtender
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


class _ExtenderServer:
    """A scriptable extender webhook (testing/fake_extender.go role)."""

    def __init__(self, filter_fn=None, prioritize_fn=None, bind_fn=None,
                 fail=False):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                args = json.loads(self.rfile.read(n) or b"{}")
                outer.calls.append(self.path)
                if outer.fail:
                    self.send_response(500)
                    self.end_headers()
                    return
                if self.path == "/filter":
                    body = outer.filter_fn(args)
                elif self.path == "/prioritize":
                    body = outer.prioritize_fn(args)
                elif self.path == "/bind":
                    body = outer.bind_fn(args)
                else:
                    body = {"error": f"unknown verb {self.path}"}
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.calls: list[str] = []
        self.fail = fail
        self.filter_fn = filter_fn or (lambda a: {"nodenames": a.get("nodenames")})
        self.prioritize_fn = prioritize_fn or (lambda a: [])
        self.bind_fn = bind_fn or (lambda a: {})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()


def wait_for(pred, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    yield store, client, factory
    factory.stop()


def run_sched(client, factory, extenders):
    sched = new_scheduler(client, factory)
    sched.extenders = extenders
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    return sched


class TestHTTPExtender:
    def test_filter_restricts_nodes(self, cluster):
        store, client, factory = cluster
        srv = _ExtenderServer(filter_fn=lambda a: {
            "nodenames": [n for n in a["nodenames"] if n == "n2"]})
        ext = HTTPExtender(srv.url, filter_verb="filter",
                           node_cache_capable=True)
        sched = run_sched(client, factory, [ext])
        try:
            for n in ("n1", "n2", "n3"):
                client.create(NODES, make_node(n).build())
            client.create(PODS, make_pod("p").req(cpu="100m").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p")) == "n2")
            assert "/filter" in srv.calls
        finally:
            sched.stop()
            srv.stop()

    def test_failed_nodes_map(self, cluster):
        store, client, factory = cluster
        srv = _ExtenderServer(filter_fn=lambda a: {
            "nodenames": None,
            "failedNodes": {n: "nope" for n in a["nodenames"]}})
        ext = HTTPExtender(srv.url, filter_verb="filter",
                           node_cache_capable=True)
        sched = run_sched(client, factory, [ext])
        try:
            client.create(NODES, make_node("n1").build())
            client.create(PODS, make_pod("p").req(cpu="100m").build())
            assert wait_for(lambda: any(
                c.get("reason") == "Unschedulable"
                for c in (client.get(PODS, "default", "p").get("status")
                          or {}).get("conditions", [])))
            assert not meta.pod_node_name(client.get(PODS, "default", "p"))
        finally:
            sched.stop()
            srv.stop()

    def test_prioritize_steers_selection(self, cluster):
        store, client, factory = cluster
        srv = _ExtenderServer(prioritize_fn=lambda a: [
            {"host": n, "score": 100 if n == "n3" else 0}
            for n in a["nodenames"]])
        ext = HTTPExtender(srv.url, prioritize_verb="prioritize",
                           weight=10, node_cache_capable=True)
        sched = run_sched(client, factory, [ext])
        try:
            for n in ("n1", "n2", "n3"):
                client.create(NODES, make_node(n).build())
            client.create(PODS, make_pod("p").req(cpu="100m").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p")) == "n3")
        finally:
            sched.stop()
            srv.stop()

    def test_bind_delegation(self, cluster):
        store, client, factory = cluster
        bound = {}

        def bind_fn(args):
            bound.update(args)
            client.bind(client.get(PODS, args["podNamespace"],
                                   args["podName"]), args["node"])
            return {}

        srv = _ExtenderServer(bind_fn=bind_fn)
        ext = HTTPExtender(srv.url, bind_verb="bind", node_cache_capable=True)
        sched = run_sched(client, factory, [ext])
        try:
            client.create(NODES, make_node("n1").build())
            client.create(PODS, make_pod("p").req(cpu="100m").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p")) == "n1")
            assert bound["node"] == "n1" and bound["podName"] == "p"
        finally:
            sched.stop()
            srv.stop()

    def test_ignorable_extender_error_skipped(self, cluster):
        store, client, factory = cluster
        srv = _ExtenderServer(fail=True)
        ext = HTTPExtender(srv.url, filter_verb="filter",
                           node_cache_capable=True, ignorable=True)
        sched = run_sched(client, factory, [ext])
        try:
            client.create(NODES, make_node("n1").build())
            client.create(PODS, make_pod("p").req(cpu="100m").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p")) == "n1")
        finally:
            sched.stop()
            srv.stop()

    def test_managed_resources_gates_interest(self):
        ext = HTTPExtender("http://x", filter_verb="filter",
                           managed_resources=["example.com/gpu"])
        plain = make_pod("p").req(cpu="1").build()
        gpu = make_pod("g").req(**{"example.com/gpu": "1"}).build()
        assert not ext.is_interested(plain)
        assert ext.is_interested(gpu)
        assert HTTPExtender("http://x").is_interested(plain)


class TestSchedulerConfig:
    def test_defaults(self):
        cfg = load_config({})
        assert cfg.parallelism == 16
        assert len(cfg.profiles) == 1
        assert cfg.profiles[0].scheduler_name == "default-scheduler"

    def test_yaml_round_trip(self, tmp_path):
        path = tmp_path / "cfg.yaml"
        path.write_text("""
apiVersion: kubescheduler.config.k8s.io/v1
kind: KubeSchedulerConfiguration
percentageOfNodesToScore: 50
profiles:
  - schedulerName: my-sched
    plugins:
      score:
        disabled: [{name: ImageLocality}]
""")
        cfg = load_config(str(path))
        assert cfg.percentage_of_nodes_to_score == 50
        assert cfg.profiles[0].scheduler_name == "my-sched"

    def test_validation_errors(self):
        with pytest.raises(ConfigError):
            load_config({"kind": "NotAConfig"})
        with pytest.raises(ConfigError):
            load_config({"parallelism": 0})
        with pytest.raises(ConfigError):
            load_config({"profiles": [
                {"schedulerName": "a"}, {"schedulerName": "a"}]})
        with pytest.raises(ConfigError):
            load_config({"profiles": [{"plugins": {"noSuchPoint": {}}}]})
        with pytest.raises(ConfigError):
            load_config({"profiles": [{"plugins": {
                "filter": {"enabled": [{"name": "Bogus"}]}}}]})

    def test_scaleout_stanza_parses(self):
        cfg = load_config({"scaleOut": {
            "instanceCount": 4, "instanceIndex": 2,
            "partitionBy": "namespaceHash", "ringSlices": 128,
            "leaseDurationSeconds": 15, "renewIntervalSeconds": 3}})
        so = cfg.scale_out
        assert so.enabled
        assert (so.instance_count, so.instance_index) == (4, 2)
        assert so.partition_by == "namespaceHash"
        assert so.ring_slices == 128
        assert (so.lease_duration, so.renew_interval) == (15, 3)
        # default: single instance, layer off
        assert not load_config({}).scale_out.enabled

    def test_scaleout_validation_errors(self):
        for bad in (
                {"noSuchKey": 1},
                {"instanceCount": 0},
                {"instanceCount": 2, "instanceIndex": 2},
                {"instanceCount": 2, "instanceIndex": -1},
                {"partitionBy": "consistentHashing"},
                {"instanceCount": 8, "ringSlices": 4},
                {"leaseDurationSeconds": 0},
                {"renewIntervalSeconds": 0},
                {"leaseDurationSeconds": 1, "renewIntervalSeconds": 2}):
            with pytest.raises(ConfigError):
                load_config({"scaleOut": bad})

    def test_backend_stanza_parses(self):
        cfg = load_config({"backend": {
            "kind": "sharded", "batchSize": 512, "kCap": 2048}})
        be = cfg.backend
        assert be.selected
        assert (be.kind, be.batch_size, be.k_cap) == ("sharded", 512, 2048)
        # default: tpu single-chip, nothing selected explicitly
        d = load_config({}).backend
        assert d.kind == "tpu" and not d.selected

    def test_backend_validation_errors(self):
        for bad in ({"noSuchKey": 1},
                    {"kind": "gpu"},
                    {"batchSize": -1},
                    {"kCap": -8}):
            with pytest.raises(ConfigError):
                load_config({"backend": bad})

    def test_backend_policy_reaches_scheduler(self, cluster):
        store, client, factory = cluster
        cfg = load_config({"backend": {"kind": "sharded"}})
        sched = scheduler_from_config(client, factory, cfg)
        assert sched.backend_policy.kind == "sharded"

    def test_point_scoped_disable(self):
        cfg = load_config({"profiles": [{"plugins": {
            "score": {"disabled": [{"name": "NodeResourcesFit"}]}}}]})
        fw = build_framework_from_profile(None, None, cfg.profiles[0])
        score_names = {p.name for p, _ in fw.score}
        filter_names = {p.name for p in fw.filter}
        assert "NodeResourcesFit" not in score_names
        assert "NodeResourcesFit" in filter_names

    def test_multipoint_disable_all(self):
        cfg = load_config({"profiles": [{"plugins": {
            "multiPoint": {"disabled": [{"name": "*"}],
                           "enabled": [{"name": "NodeResourcesFit"},
                                       {"name": "PrioritySort"},
                                       {"name": "DefaultBinder"}]}}}]})
        fw = build_framework_from_profile(None, None, cfg.profiles[0])
        assert {p.name for p in fw.filter} == {"NodeResourcesFit"}
        assert fw.queue_sort is not None

    def test_score_weight_override(self):
        cfg = load_config({"profiles": [{"plugins": {
            "score": {"enabled": [{"name": "TaintToleration",
                                   "weight": 7}]}}}]})
        fw = build_framework_from_profile(None, None, cfg.profiles[0])
        weights = {p.name: w for p, w in fw.score}
        assert weights["TaintToleration"] == 7

    def test_plugin_args_passed(self):
        cfg = load_config({"profiles": [{"pluginConfig": [
            {"name": "NodeResourcesFit",
             "args": {"strategy": "MostAllocated"}}]}]})
        fw = build_framework_from_profile(None, None, cfg.profiles[0])
        fit = next(p for p in fw.filter if p.name == "NodeResourcesFit")
        assert fit.strategy == "MostAllocated"

    def test_scheduler_from_config_schedules(self, cluster):
        store, client, factory = cluster
        cfg = load_config({
            "podInitialBackoffSeconds": 0.5,
            "profiles": [{"schedulerName": "custom"},
                         {"schedulerName": "default-scheduler"}]})
        sched = scheduler_from_config(client, factory, cfg)
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        try:
            client.create(NODES, make_node("n1").build())
            client.create(PODS, make_pod("p").req(cpu="100m")
                          .scheduler("custom").build())
            assert wait_for(lambda: meta.pod_node_name(
                client.get(PODS, "default", "p")) == "n1")
        finally:
            sched.stop()

    def test_extenders_from_config(self):
        cfg = load_config({"extenders": [
            {"urlPrefix": "http://127.0.0.1:9999", "filterVerb": "filter",
             "weight": 3, "ignorable": True,
             "managedResources": [{"name": "example.com/gpu"}]}]})
        from kubernetes_tpu.scheduler.extender import build_extenders
        exts = build_extenders(cfg.extenders)
        assert len(exts) == 1
        assert exts[0].weight == 3 and exts[0].is_ignorable()
