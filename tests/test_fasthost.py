"""Differential tests for the native host helpers (native/fasthost).

Every helper has a pure-Python twin; these tests drive both over a
corpus of pod shapes — plain, affinity-carrying, ported, scalar-
resourced, pinned, malformed — and require byte-identical results, so
the native fast path can never silently diverge from the semantics the
rest of the tree is tested against.
"""

from __future__ import annotations

import numpy as np
import pytest

from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.utils import fasthost


def pods_corpus() -> list[dict]:
    base = {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "p", "namespace": "default", "uid": "u-1",
                         "labels": {"app": "x"}},
            "spec": {"containers": [{"name": "c0", "image": "img",
                                     "resources": {"requests": {
                                         "cpu": "100m", "memory": "128Mi"}}}]}}
    import copy
    out = [copy.deepcopy(base)]
    p = copy.deepcopy(base)  # no namespace, no labels, no requests
    del p["metadata"]["namespace"]
    del p["metadata"]["labels"]
    p["spec"]["containers"][0].pop("resources")
    out.append(p)
    p = copy.deepcopy(base)  # priority + schedulerName + tolerations
    p["spec"]["priority"] = 10
    p["spec"]["schedulerName"] = "other"
    p["spec"]["tolerations"] = [{"key": "k", "operator": "Exists"}]
    out.append(p)
    p = copy.deepcopy(base)  # anti-affinity
    p["spec"]["affinity"] = {"podAntiAffinity": {
        "requiredDuringSchedulingIgnoredDuringExecution": [
            {"topologyKey": "kubernetes.io/hostname",
             "labelSelector": {"matchLabels": {"app": "x"}}}]}}
    out.append(p)
    p = copy.deepcopy(base)  # node selector
    p["spec"]["nodeSelector"] = {"zone": "a"}
    out.append(p)
    p = copy.deepcopy(base)  # host port
    p["spec"]["containers"][0]["ports"] = [{"containerPort": 80,
                                            "hostPort": 8080}]
    out.append(p)
    p = copy.deepcopy(base)  # container port, NO host port
    p["spec"]["containers"][0]["ports"] = [{"containerPort": 80}]
    out.append(p)
    p = copy.deepcopy(base)  # PVC volume
    p["spec"]["volumes"] = [{"name": "v",
                             "persistentVolumeClaim": {"claimName": "c"}}]
    out.append(p)
    p = copy.deepcopy(base)  # secret volume (still plain)
    p["spec"]["volumes"] = [{"name": "v", "secret": {"secretName": "s"}}]
    out.append(p)
    p = copy.deepcopy(base)  # pinned
    p["spec"]["nodeName"] = "node-1"
    out.append(p)
    p = copy.deepcopy(base)  # nominated
    p["status"] = {"nominatedNodeName": "node-2"}
    out.append(p)
    p = copy.deepcopy(base)  # scalar resource
    p["spec"]["containers"][0]["resources"]["requests"]["example.com/gpu"] = "1"
    out.append(p)
    p = copy.deepcopy(base)  # two containers
    p["spec"]["containers"].append({"name": "c1", "resources": {
        "requests": {"cpu": "50m"}}})
    out.append(p)
    p = copy.deepcopy(base)  # initContainers
    p["spec"]["initContainers"] = [{"name": "i0", "resources": {
        "requests": {"cpu": "2"}}}]
    out.append(p)
    p = copy.deepcopy(base)  # initContainer with a HOST port (plain=False)
    p["spec"]["initContainers"] = [{"name": "i0",
                                    "ports": [{"containerPort": 53,
                                               "hostPort": 5353}]}]
    out.append(p)
    p = copy.deepcopy(base)  # topology spread
    p["spec"]["topologySpreadConstraints"] = [{
        "maxSkew": 1, "topologyKey": "zone",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "x"}}}]
    out.append(p)
    # explicit JSON null is NOT the same as the key being absent — the
    # Python path's spec.get("schedulerName", default) returns None, so
    # the native path must punt these to Python, not coalesce them
    p = copy.deepcopy(base)  # explicit-null schedulerName
    p["spec"]["schedulerName"] = None
    out.append(p)
    p = copy.deepcopy(base)  # explicit-null uid
    p["metadata"]["uid"] = None
    out.append(p)
    p = copy.deepcopy(base)  # explicit-null labels
    p["metadata"]["labels"] = None
    out.append(p)
    p = copy.deepcopy(base)  # all three nulled at once
    p["spec"]["schedulerName"] = None
    p["metadata"]["uid"] = None
    p["metadata"]["labels"] = None
    out.append(p)
    return out


FIELDS = ["key", "uid", "labels", "priority", "scheduler_name",
          "nominated_node_name", "node_selector", "tolerations",
          "host_ports", "topology_spread_constraints", "plain"]


@pytest.mark.skipif(not fasthost.is_native(), reason="extension not built")
@pytest.mark.parametrize("i,pod", list(enumerate(pods_corpus())))
def test_podinfo_native_vs_python(i, pod, monkeypatch):
    fast = PodInfo(pod)
    monkeypatch.setattr(fasthost, "_native", None)  # force Python path
    slow = PodInfo(pod)
    for f in FIELDS:
        assert getattr(fast, f) == getattr(slow, f), (i, f)
    for f in ("request", "request_nonzero"):
        a, b = getattr(fast, f), getattr(slow, f)
        assert (a.milli_cpu, a.memory, a.ephemeral_storage, a.scalar) == \
               (b.milli_cpu, b.memory, b.ephemeral_storage, b.scalar), (i, f)
    for f in ("required_affinity_terms", "required_anti_affinity_terms",
              "preferred_affinity_terms", "preferred_anti_affinity_terms",
              "node_affinity_required", "node_affinity_preferred"):
        assert len(getattr(fast, f)) == len(getattr(slow, f)), (i, f)


@pytest.mark.skipif(not fasthost.is_native(), reason="extension not built")
def test_build_assumed_native_vs_python(monkeypatch):
    pods = pods_corpus()
    names = [f"node-{i}" for i in range(len(pods))]
    fast = fasthost.build_assumed(pods, names)
    monkeypatch.setattr(fasthost, "_native", None)
    slow = fasthost.build_assumed(pods, names)
    assert fast == slow
    for orig, a, n in zip(pods, fast, names):
        assert a["spec"]["nodeName"] == n
        assert a is not orig and a["spec"] is not orig.get("spec")
        assert orig.get("spec", {}).get("nodeName") != n or orig is None


@pytest.mark.skipif(not fasthost.is_native(), reason="extension not built")
def test_req_columns_native_vs_python(monkeypatch):
    infos = [PodInfo(p) for p in pods_corpus()]
    n = len(infos)
    a_req = np.zeros((n + 2, 8), np.float32)
    a_nz = np.zeros((n + 2, 8), np.float32)
    fasthost.req_columns(infos, a_req, a_nz)
    monkeypatch.setattr(fasthost, "_native", None)
    b_req = np.zeros((n + 2, 8), np.float32)
    b_nz = np.zeros((n + 2, 8), np.float32)
    fasthost.req_columns(infos, b_req, b_nz)
    np.testing.assert_array_equal(a_req[:, :3], b_req[:, :3])
    np.testing.assert_array_equal(a_nz[:, :3], b_nz[:, :3])


@pytest.mark.skipif(not fasthost.is_native(), reason="extension not built")
def test_pod_scan_rejects_non_dict():
    with pytest.raises(TypeError):
        fasthost._native.pod_scan_into([1, 2], None, (None,) * 5)
    with pytest.raises(TypeError):
        fasthost._native.build_assumed([{"a": 1}], ["x", "y"])
