"""APF fair queuing: shuffle sharding, round-robin dispatch,
API-object-driven configuration.

Reference: staging/src/k8s.io/apiserver/pkg/util/flowcontrol/
fairqueuing/queueset/queueset.go (dispatch fairness),
shufflesharding/dealer.go (hand dealing), apf_controller.go
(FlowSchema/PriorityLevelConfiguration as config source).
"""

import threading
import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import flowcontrol as fc
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import wait_for


class TestShuffleSharding:
    def test_hand_properties(self):
        hand = fc.shuffle_shard_hand("alice", 128, 8)
        assert len(hand) == 8
        assert len(set(hand)) == 8              # distinct queues
        assert all(0 <= i < 128 for i in hand)
        assert fc.shuffle_shard_hand("alice", 128, 8) == hand  # stable
        assert fc.shuffle_shard_hand("bob", 128, 8) != hand

    def test_small_pool_degenerates_to_all(self):
        assert sorted(fc.shuffle_shard_hand("x", 4, 8)) == [0, 1, 2, 3]

    def test_hands_spread(self):
        """Two flows' hands should rarely fully collide — with 32
        queues / hand 4, distinct users land on distinct queue sets."""
        hands = [set(fc.shuffle_shard_hand(f"user-{i}", 32, 4))
                 for i in range(50)]
        full_collisions = sum(1 for i in range(50) for j in range(i)
                              if hands[i] == hands[j])
        assert full_collisions <= 1


class TestDrowningFlow:
    def test_noisy_flow_cannot_starve_peer(self):
        """One elephant flow with 30 queued requests; a mouse flow's
        single request must be admitted within the first few dispatches
        (round-robin across queues), NOT after the elephant drains."""
        lvl = fc.PriorityLevel("t", seats=1, queues=16, queue_length=50,
                               hand_size=4)
        order: list[str] = []
        order_lock = threading.Lock()
        assert lvl.acquire(flow_key="warm")  # hold the only seat

        def worker(flow, tag):
            lvl.acquire(flow_key=flow, timeout=30.0)
            with order_lock:
                order.append(tag)
            lvl.release()

        threads = []
        for i in range(30):
            t = threading.Thread(target=worker,
                                 args=("elephant", "E"), daemon=True)
            t.start()
            threads.append(t)
        # let the elephants enqueue first — worst case for the mouse
        assert wait_for(lambda: lvl.stats()["waiting"] == 30)
        t = threading.Thread(target=worker, args=("mouse", "M"),
                             daemon=True)
        t.start()
        threads.append(t)
        assert wait_for(lambda: lvl.stats()["waiting"] == 31)
        lvl.release()  # open the floodgate
        for t in threads:
            t.join(timeout=30.0)
        assert len(order) == 31
        mouse_pos = order.index("M")
        # elephant hand <= 4 queues, mouse picks a different/shorter
        # queue: round-robin must reach it within one sweep
        assert mouse_pos <= 4, f"mouse dispatched at position {mouse_pos}"

    def test_elephant_fills_only_its_hand(self):
        """Queue-full rejection hits the elephant (its hand saturated)
        while a fresh flow still queues fine."""
        lvl = fc.PriorityLevel("t", seats=1, queues=8, queue_length=2,
                               hand_size=2)
        lvl.acquire(flow_key="warm")
        accepted = 0
        with pytest.raises(fc.RejectedError):
            for _ in range(50):
                threading.Thread(
                    target=lambda: (lvl.acquire("elephant", timeout=20),
                                    lvl.release()),
                    daemon=True).start()
                time.sleep(0.005)
                accepted += 1
                # force synchronous rejection check
                if lvl.stats()["waiting"] >= 4:
                    lvl.acquire("elephant", timeout=20)
        # elephant saturated its 2-queue hand (2*2 slots), but...
        assert 4 <= accepted <= 6
        mouse_done = threading.Event()
        threading.Thread(
            target=lambda: (lvl.acquire("mouse", timeout=20),
                            mouse_done.set(), lvl.release()),
            daemon=True).start()
        time.sleep(0.05)
        lvl.release()
        assert mouse_done.wait(10.0)  # mouse unaffected by the 429s


class TestAPIObjectConfig:
    def _plc(self, name, seats, queues=8, qlen=5, hand=2):
        obj = meta.new_object("PriorityLevelConfiguration", name, None)
        obj["spec"] = {"type": "Limited", "limited": {
            "nominalConcurrencyShares": seats,
            "limitResponse": {"type": "Queue", "queuing": {
                "queues": queues, "queueLengthLimit": qlen,
                "handSize": hand}}}}
        return obj

    def _schema(self, name, level, precedence, user=None, group=None,
                resources=None):
        obj = meta.new_object("FlowSchema", name, None)
        subjects = []
        if user:
            subjects.append({"kind": "User", "name": user})
        if group:
            subjects.append({"kind": "Group", "name": group})
        rule = {"subjects": subjects}
        if resources:
            rule["resourceRules"] = [{"verbs": ["*"],
                                      "resources": resources}]
        obj["spec"] = {
            "priorityLevelConfiguration": {"name": level},
            "matchingPrecedence": precedence,
            "rules": [rule]}
        return obj

    def test_stored_objects_drive_dispatch(self):
        store = kv.MemoryStore()
        store.create(fc.PRIORITYLEVELS, self._plc("batch-lane", 3))
        store.create(fc.FLOWSCHEMAS,
                     self._schema("batch-users", "batch-lane", 50,
                                  group="batch-jobs"))
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            lvl = d.classify("worker-7", "create", "jobs",
                             groups=("batch-jobs",))
            assert lvl.name == "batch-lane"
            assert lvl.seats == 3
            # non-members keep the default routing
            assert d.classify("alice", "get", "pods",
                              groups=()).name == "global-default"
        finally:
            d.stop()

    def test_config_watch_applies_new_objects(self):
        store = kv.MemoryStore()
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            assert d.classify("vip", "get", "pods").name == \
                "global-default"
            store.create(fc.PRIORITYLEVELS, self._plc("vip-lane", 9))
            store.create(fc.FLOWSCHEMAS,
                         self._schema("vip-schema", "vip-lane", 10,
                                      user="vip"))
            assert wait_for(lambda: d.classify(
                "vip", "get", "pods").name == "vip-lane", timeout=5.0)
            assert d.levels["vip-lane"].seats == 9
        finally:
            d.stop()

    def test_exempt_level_object(self):
        store = kv.MemoryStore()
        obj = meta.new_object("PriorityLevelConfiguration", "sys-exempt",
                              None)
        obj["spec"] = {"type": "Exempt"}
        store.create(fc.PRIORITYLEVELS, obj)
        store.create(fc.FLOWSCHEMAS,
                     self._schema("root", "sys-exempt", 1, user="root"))
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            lvl = d.classify("root", "delete", "nodes")
            assert lvl.exempt
            for _ in range(100):
                assert lvl.acquire("root")  # never blocks
        finally:
            d.stop()

    def test_resource_rule_scoping(self):
        store = kv.MemoryStore()
        store.create(fc.PRIORITYLEVELS, self._plc("pods-only", 2))
        store.create(fc.FLOWSCHEMAS,
                     self._schema("pods-only-s", "pods-only", 20,
                                  user="*", resources=["pods"]))
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            assert d.classify("x", "get", "pods").name == "pods-only"
            assert d.classify("x", "get", "nodes").name != "pods-only"
        finally:
            d.stop()


class TestConfigLifecycle:
    def test_reload_keeps_live_level_object(self):
        """A config update must reconfigure the EXISTING level — a
        replacement object would leak the seats held by in-flight
        tickets that release() on the old one."""
        store = kv.MemoryStore()
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            before = d.levels["global-default"]
            ticket = d.admit("alice", "get", "pods")  # holds a seat
            plc = meta.new_object("PriorityLevelConfiguration",
                                  "global-default", None)
            plc["spec"] = {"type": "Limited", "limited": {
                "nominalConcurrencyShares": 2,
                "limitResponse": {"type": "Queue", "queuing": {
                    "queues": 4, "queueLengthLimit": 3,
                    "handSize": 2}}}}
            store.create(fc.PRIORITYLEVELS, plc)
            assert wait_for(
                lambda: d.levels["global-default"].seats == 2)
            assert d.levels["global-default"] is before  # same object
            assert before.stats()["in_flight"] == 1
            ticket.__exit__()
            assert before.stats()["in_flight"] == 0  # seat came back
        finally:
            d.stop()

    def test_deleting_objects_reverts_to_defaults(self):
        store = kv.MemoryStore()
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            plc = meta.new_object("PriorityLevelConfiguration",
                                  "global-default", None)
            plc["spec"] = {"type": "Limited",
                           "limited": {"nominalConcurrencyShares": 1}}
            store.create(fc.PRIORITYLEVELS, plc)
            fs_obj = meta.new_object("FlowSchema", "route-bob", None)
            fs_obj["spec"] = {
                "priorityLevelConfiguration": {"name": "leader-election"},
                "matchingPrecedence": 5,
                "rules": [{"subjects": [{"kind": "User",
                                         "name": "bob"}]}]}
            store.create(fc.FLOWSCHEMAS, fs_obj)
            assert wait_for(
                lambda: d.levels["global-default"].seats == 1)
            assert wait_for(lambda: d.classify(
                "bob", "get", "pods").name == "leader-election")
            store.delete(fc.PRIORITYLEVELS, "", "global-default")
            store.delete(fc.FLOWSCHEMAS, "", "route-bob")
            assert wait_for(
                lambda: d.levels["global-default"].seats == 20)
            assert wait_for(lambda: d.classify(
                "bob", "get", "pods").name == "global-default")
        finally:
            d.stop()

    def test_reject_limit_response(self):
        store = kv.MemoryStore()
        plc = meta.new_object("PriorityLevelConfiguration", "shed", None)
        plc["spec"] = {"type": "Limited", "limited": {
            "nominalConcurrencyShares": 1,
            "limitResponse": {"type": "Reject"}}}
        store.create(fc.PRIORITYLEVELS, plc)
        fs_obj = meta.new_object("FlowSchema", "shed-all", None)
        fs_obj["spec"] = {"priorityLevelConfiguration": {"name": "shed"},
                          "matchingPrecedence": 1, "rules": []}
        store.create(fc.FLOWSCHEMAS, fs_obj)
        d = fc.Dispatcher()
        d.bind_store(store)
        try:
            lvl = d.classify("x", "get", "pods")
            assert lvl.name == "shed"
            lvl.acquire("x")
            t0 = time.monotonic()
            with pytest.raises(fc.RejectedError):
                lvl.acquire("x", timeout=10.0)  # rejects NOW, no wait
            assert time.monotonic() - t0 < 1.0
        finally:
            d.stop()

    def test_non_resource_rules_do_not_match_resources(self):
        obj = meta.new_object("FlowSchema", "probes", None)
        obj["spec"] = {
            "priorityLevelConfiguration": {"name": "exempt"},
            "matchingPrecedence": 2,
            "rules": [{"subjects": [{"kind": "Group", "name": "*"}],
                       "nonResourceRules": [
                           {"verbs": ["get"],
                            "nonResourceURLs": ["/healthz"]}]}]}
        fs = fc._schema_from_object(obj)
        assert not fs.match_with_groups("anyone", "get", "pods",
                                        ("system:authenticated",))


class TestServerIntegration:
    def test_drowning_flow_through_http(self):
        """Two users at the same 1-seat level over real HTTP: the noisy
        user's backlog must not starve the quiet one."""
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        from kubernetes_tpu.testing import make_pod
        store = kv.MemoryStore()
        levels = (("tiny", 1, 8, 20, False), ("catch-all", 5, 1, 50,
                                              False))
        schemas = [fc.FlowSchema("all", "tiny", 1)]
        tokens = {"tok-noisy": ("noisy", ()),
                  "tok-quiet": ("quiet", ())}
        srv = APIServer(store, tokens=tokens,
                        flow_dispatcher=fc.Dispatcher(
                            levels=levels, schemas=schemas,
                            queue_timeout=20.0)).start()
        try:
            noisy = HTTPClient.from_url(srv.url, token="tok-noisy")
            quiet = HTTPClient.from_url(srv.url, token="tok-quiet")
            results = []
            lock = threading.Lock()

            def do(client, tag, name):
                t0 = time.monotonic()
                client.create("pods", make_pod(name).build())
                with lock:
                    results.append((tag, time.monotonic() - t0))

            threads = [threading.Thread(
                target=do, args=(noisy, "N", f"noisy-{i}"), daemon=True)
                for i in range(12)]
            for t in threads:
                t.start()
            time.sleep(0.05)
            tq = threading.Thread(target=do,
                                  args=(quiet, "Q", "quiet-0"),
                                  daemon=True)
            tq.start()
            threads.append(tq)
            for t in threads:
                t.join(timeout=30.0)
            assert len(results) == 13  # nobody starved/429ed
            quiet_time = next(d for tag, d in results if tag == "Q")
            assert quiet_time < 5.0
        finally:
            srv.stop()
