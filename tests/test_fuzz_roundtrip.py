"""Property/fuzz tests for the wire-mangling code paths.

Reference: staging/src/k8s.io/api/roundtrip_test.go + apimachinery
fuzzers — the reference round-trips every API type through every codec
under a fuzzer; the equivalents here are the patch appliers
(apiserver/patch.py — RFC 7386 / RFC 6902 / strategic merge), quantity
parsing (api/quantity.py), the WAL record framing (store/wal.py), and
the managedFields leaf<->trie forms (apiserver/managedfields.py).
Deterministic seeds: failures reproduce.
"""

import json
import random
import string

import pytest

from kubernetes_tpu.api import quantity
from kubernetes_tpu.apiserver import managedfields as mf
from kubernetes_tpu.apiserver import patch as patchlib

SEED = 20260730


def rnd_scalar(rng):
    return rng.choice([
        None, True, False, rng.randint(-10**6, 10**6),
        round(rng.uniform(-100, 100), 3),
        "".join(rng.choices(string.ascii_lowercase, k=rng.randint(0, 8))),
    ])


def rnd_json(rng, depth=3):
    if depth == 0 or rng.random() < 0.3:
        return rnd_scalar(rng)
    if rng.random() < 0.5:
        return {f"k{i}": rnd_json(rng, depth - 1)
                for i in range(rng.randint(0, 4))}
    return [rnd_json(rng, depth - 1) for _ in range(rng.randint(0, 4))]


class TestJSONMergePatchProperties:
    """RFC 7386 laws, checked on random documents."""

    def test_patch_with_self_replaces_maps_not_identity_for_lists(self):
        rng = random.Random(SEED)
        for _ in range(300):
            doc = rnd_json(rng)
            out = patchlib.json_merge_patch(doc, doc)
            # applying a document to itself yields itself MINUS null map
            # values (null = delete directive)
            if not isinstance(doc, dict):
                assert out == doc
        # map law: patching X with X drops null-valued keys
        out = patchlib.json_merge_patch({"a": 1, "b": None},
                                        {"a": 1, "b": None})
        assert out == {"a": 1}

    def test_null_patch_values_delete_at_merged_levels(self):
        """RFC 7386: a null in the PATCH deletes the key wherever the
        merge recurses (nulls already in the target persist — they are
        data, not directives)."""
        rng = random.Random(SEED + 1)

        def check(out, p):
            if not isinstance(out, dict) or not isinstance(p, dict):
                return
            for k, pv in p.items():
                if pv is None:
                    assert k not in out
                elif k in out and isinstance(pv, dict):
                    check(out[k], pv)

        for _ in range(300):
            target, p = rnd_json(rng), rnd_json(rng)
            out = patchlib.json_merge_patch(target, p)
            check(out, p)

    def test_patch_is_right_absorbing(self):
        """merge(X, P) == merge(merge(X, P), P) for delete-free patches
        (idempotence — RFC 7386 application is last-write-wins)."""
        rng = random.Random(SEED + 2)

        def drop_nulls(v):
            if isinstance(v, dict):
                return {k: drop_nulls(x) for k, x in v.items()
                        if x is not None}
            if isinstance(v, list):
                return [drop_nulls(x) for x in v]
            return v

        for _ in range(300):
            target, p = rnd_json(rng), drop_nulls(rnd_json(rng))
            once = patchlib.json_merge_patch(target, p)
            twice = patchlib.json_merge_patch(once, p)
            assert once == twice


class TestJSONPatchProperties:
    def test_add_then_remove_is_identity(self):
        rng = random.Random(SEED + 3)
        for _ in range(200):
            doc = {f"k{i}": rnd_json(rng, 2) for i in range(3)}
            val = rnd_json(rng, 2)
            out = patchlib.json_patch(doc, [
                {"op": "add", "path": "/new", "value": val},
                {"op": "remove", "path": "/new"}])
            assert out == doc

    def test_replace_missing_path_raises_not_corrupts(self):
        rng = random.Random(SEED + 4)
        for _ in range(200):
            doc = {f"k{i}": rnd_json(rng, 2) for i in range(2)}
            before = json.loads(json.dumps(doc))
            with pytest.raises(patchlib.PatchError):
                patchlib.json_patch(doc, [
                    {"op": "replace", "path": "/nope/deep", "value": 1}])
            assert doc == before  # failed patch left the doc untouched

    def test_move_equals_remove_plus_add(self):
        rng = random.Random(SEED + 5)
        for _ in range(200):
            v1, v2 = rnd_json(rng, 2), rnd_json(rng, 2)
            doc = {"a": v1, "b": v2}
            moved = patchlib.json_patch(doc, [
                {"op": "move", "from": "/a", "path": "/c"}])
            assert moved == {"b": v2, "c": v1}


class TestStrategicMergeProperties:
    def containers(self, rng, names):
        return [{"name": n, "image": f"img{rng.randint(0, 9)}"}
                for n in names]

    def test_merge_keyed_lists_never_duplicate_keys(self):
        rng = random.Random(SEED + 6)
        for _ in range(200):
            tnames = rng.sample("abcdef", rng.randint(0, 4))
            pnames = rng.sample("abcdef", rng.randint(0, 4))
            target = {"containers": self.containers(rng, tnames)}
            p = {"containers": self.containers(rng, pnames)}
            out = patchlib.strategic_merge_patch(target, p)
            names = [c["name"] for c in out["containers"]]
            assert len(names) == len(set(names)), (target, p, out)
            # every patch element's image won (merge is patch-wins)
            by_name = {c["name"]: c for c in out["containers"]}
            for c in p["containers"]:
                assert by_name[c["name"]]["image"] == c["image"]

    def test_dollar_patch_delete_removes_element(self):
        out = patchlib.strategic_merge_patch(
            {"containers": [{"name": "a", "image": "x"},
                            {"name": "b", "image": "y"}]},
            {"containers": [{"name": "a", "$patch": "delete"}]})
        assert [c["name"] for c in out["containers"]] == ["b"]

    def test_unkeyed_fields_replace_wholesale(self):
        rng = random.Random(SEED + 7)
        for _ in range(100):
            a, b = rnd_json(rng, 2), rnd_json(rng, 2)
            if isinstance(b, dict) or b is None:
                continue
            out = patchlib.strategic_merge_patch({"x": a}, {"x": b})
            assert out["x"] == b


class TestQuantityFuzz:
    def test_cpu_roundtrip(self):
        rng = random.Random(SEED + 8)
        for _ in range(500):
            milli = rng.randint(0, 10**7)
            s = quantity.format_cpu_milli(milli)
            assert quantity.parse_cpu_milli(s) == milli

    def test_mem_roundtrip_power_of_two(self):
        rng = random.Random(SEED + 9)
        for _ in range(500):
            n = rng.randint(0, 2**48)
            s = quantity.format_mem_bytes(n)
            # formatting may canonicalize to a unit; parsing it back must
            # preserve the exact byte count
            assert quantity.parse_mem_bytes(s) == n, (n, s)

    def test_parse_accepts_all_suffixes(self):
        for suffix, mult in [("", 1), ("k", 1000), ("M", 1000**2),
                             ("G", 1000**3), ("T", 1000**4),
                             ("Ki", 1024), ("Mi", 1024**2),
                             ("Gi", 1024**3), ("Ti", 1024**4)]:
            assert quantity.parse_quantity(f"3{suffix}") == 3 * mult

    def test_garbage_raises_not_hangs(self):
        rng = random.Random(SEED + 10)
        for _ in range(300):
            s = "".join(rng.choices(string.printable, k=rng.randint(1, 12)))
            try:
                quantity.parse_quantity(s)
            except (ValueError, KeyError):
                pass  # rejection is fine; silent nonsense is not


class TestWALFraming:
    def test_random_records_roundtrip_and_torn_tails_never_corrupt(self, tmp_path):
        from kubernetes_tpu.store import wal
        rng = random.Random(SEED + 11)
        for trial in range(20):
            d = tmp_path / f"t{trial}"
            w = wal.WriteAheadLog(str(d))
            entries = []
            for i in range(rng.randint(1, 30)):
                if rng.random() < 0.8:
                    obj = rnd_json(rng, 2)
                    entries.append((wal.PUT, i + 1, "pods", f"ns/p{i}", obj))
                else:
                    entries.append((wal.DELETE, i + 1, "pods", f"ns/p{i}"))
            w.append_many(entries)
            w.close()
            log = d / wal.WriteAheadLog.LOG
            blob = log.read_bytes()
            cut = rng.randint(0, len(blob))
            log.write_bytes(blob[:cut])
            # recovery must parse a PREFIX of the entries, never garbage
            rev, data, valid, replayed = wal.WriteAheadLog.recover(str(d))
            assert valid <= cut
            assert replayed <= len(entries)
            if replayed:
                assert rev == entries[replayed - 1][1]


class TestManagedFieldsRoundtrip:
    def test_leaves_trie_roundtrip(self):
        rng = random.Random(SEED + 12)
        for _ in range(200):
            obj = {"apiVersion": "v1", "kind": "X",
                   "metadata": {"name": "x"},
                   "spec": rnd_json(rng, 3)}
            leaves = mf.leaves_of(obj)
            assert mf.trie_to_leaves(mf.leaves_to_trie(leaves)) == leaves

    def test_get_at_matches_leaves(self):
        rng = random.Random(SEED + 13)
        for _ in range(200):
            obj = {"apiVersion": "v1", "kind": "X",
                   "metadata": {"name": "x"},
                   "spec": rnd_json(rng, 3)}
            for path in mf.leaves_of(obj):
                assert mf.get_at(obj, path) is not mf._MISSING, path
