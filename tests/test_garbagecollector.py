"""Garbage collector: dependency graph, cascading deletion, finalizers.

Reference semantics:
  pkg/controller/garbagecollector/garbagecollector.go attemptToDeleteItem
  (solid/dangling/waiting owner classification),
  graph_builder.go (uid graph over ownerReferences),
  foregroundDeletion / orphan finalizer processing,
  blockOwnerDeletion.
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.controllers.garbagecollector import GarbageCollector
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def gc_cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    gc = GarbageCollector(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    gc.run()
    yield store, client, gc
    gc.stop()
    factory.stop()


def owner_ref(owner, block=False, controller=True):
    ref = {"apiVersion": owner.get("apiVersion", "v1"),
           "kind": owner["kind"], "name": meta.name(owner),
           "uid": meta.uid(owner), "controller": controller}
    if block:
        ref["blockOwnerDeletion"] = True
    return ref


def make_owned(kind, name, owners, ns="default"):
    obj = meta.new_object(kind, name, ns)
    obj["metadata"]["ownerReferences"] = [
        owner_ref(o, block=b) for o, b in owners]
    return obj


def gone(store, resource, ns, name):
    def check():
        try:
            store.get(resource, ns, name)
            return False
        except kv.NotFoundError:
            return True
    return check


class TestBackgroundCascade:
    def test_two_level_cascade(self, gc_cluster):
        store, client, gc = gc_cluster
        dep = client.create("deployments",
                            meta.new_object("Deployment", "web"))
        rs = client.create("replicasets", make_owned(
            "ReplicaSet", "web-1", [(dep, False)]))
        for i in range(3):
            client.create("pods", make_owned(
                "Pod", f"web-1-{i}", [(rs, False)]))
        assert wait_for(lambda: gc.graph_size() >= 5)

        client.delete("deployments", "default", "web")
        assert wait_for(gone(store, "replicasets", "default", "web-1"))
        for i in range(3):
            assert wait_for(gone(store, "pods", "default", f"web-1-{i}"))

    def test_solid_owner_keeps_dependent(self, gc_cluster):
        store, client, gc = gc_cluster
        rs = client.create("replicasets",
                           meta.new_object("ReplicaSet", "keep"))
        client.create("pods", make_owned("Pod", "keep-0", [(rs, False)]))
        time.sleep(0.5)  # give the GC a chance to do the wrong thing
        assert store.get("pods", "default", "keep-0") is not None

    def test_one_solid_owner_among_dangling_keeps(self, gc_cluster):
        store, client, gc = gc_cluster
        a = client.create("replicasets", meta.new_object("ReplicaSet", "a"))
        b = client.create("jobs", meta.new_object("Job", "b"))
        client.create("pods", make_owned("Pod", "shared",
                                         [(a, False), (b, False)]))
        client.delete("replicasets", "default", "a")
        time.sleep(0.5)
        assert store.get("pods", "default", "shared") is not None
        client.delete("jobs", "default", "b")
        assert wait_for(gone(store, "pods", "default", "shared"))

    def test_recreated_owner_is_not_my_owner(self, gc_cluster):
        store, client, gc = gc_cluster
        rs = client.create("replicasets", meta.new_object("ReplicaSet", "r"))
        client.create("pods", make_owned("Pod", "r-0", [(rs, False)]))
        client.delete("replicasets", "default", "r")
        # recreate under the same name: new uid, so the pod is STILL an
        # orphan (uid mismatch = dangling)
        client.create("replicasets", meta.new_object("ReplicaSet", "r"))
        assert wait_for(gone(store, "pods", "default", "r-0"))

    def test_unknown_owner_kind_never_cascades(self, gc_cluster):
        store, client, gc = gc_cluster
        pod = meta.new_object("Pod", "cr-owned")
        pod["metadata"]["ownerReferences"] = [{
            "apiVersion": "example.com/v1", "kind": "Widget",
            "name": "w", "uid": "w-uid-1"}]
        client.create("pods", pod)
        time.sleep(0.5)
        assert store.get("pods", "default", "cr-owned") is not None


class TestForegroundDeletion:
    def test_foreground_deletes_blocking_dependents_first(self, gc_cluster):
        store, client, gc = gc_cluster
        rs = client.create("replicasets", meta.new_object("ReplicaSet", "fg"))
        for i in range(2):
            client.create("pods", make_owned("Pod", f"fg-{i}",
                                             [(rs, True)]))
        assert wait_for(lambda: gc.graph_size() >= 3)
        client.delete("replicasets", "default", "fg",
                      propagation_policy="Foreground")
        # the owner parks terminating until its blocking dependents go
        cur = store.get("replicasets", "default", "fg")
        assert cur["metadata"]["deletionTimestamp"]
        assert meta.FOREGROUND_FINALIZER in cur["metadata"]["finalizers"]
        for i in range(2):
            assert wait_for(gone(store, "pods", "default", f"fg-{i}"))
        # ... then the GC strips the finalizer and the delete completes
        assert wait_for(gone(store, "replicasets", "default", "fg"))

    def test_nonblocking_dependents_do_not_block(self, gc_cluster):
        store, client, gc = gc_cluster
        rs = client.create("replicasets", meta.new_object("ReplicaSet", "nb"))
        client.create("pods", make_owned("Pod", "nb-0", [(rs, False)]))
        assert wait_for(lambda: gc.graph_size() >= 2)
        client.delete("replicasets", "default", "nb",
                      propagation_policy="Foreground")
        # owner completes without waiting on the non-blocking dependent
        assert wait_for(gone(store, "replicasets", "default", "nb"))
        # and the dependent is then collected as an orphan
        assert wait_for(gone(store, "pods", "default", "nb-0"))


class TestOrphanPropagation:
    def test_orphan_detaches_dependents(self, gc_cluster):
        store, client, gc = gc_cluster
        rs = client.create("replicasets", meta.new_object("ReplicaSet", "op"))
        for i in range(2):
            client.create("pods", make_owned("Pod", f"op-{i}",
                                             [(rs, True)]))
        assert wait_for(lambda: gc.graph_size() >= 3)
        client.delete("replicasets", "default", "op",
                      propagation_policy="Orphan")
        assert wait_for(gone(store, "replicasets", "default", "op"))
        time.sleep(0.5)
        for i in range(2):
            pod = store.get("pods", "default", f"op-{i}")
            assert "ownerReferences" not in pod["metadata"]


class TestHTTPDeleteOptions:
    def test_propagation_policy_over_http(self):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        store = kv.MemoryStore()
        server = APIServer(store).start()
        try:
            c = HTTPClient.from_url(server.url)
            c.create("replicasets", meta.new_object("ReplicaSet", "h"))
            c.delete("replicasets", "default", "h",
                     propagation_policy="Foreground")
            cur = store.get("replicasets", "default", "h")
            assert meta.FOREGROUND_FINALIZER in cur["metadata"]["finalizers"]
        finally:
            server.stop()
