"""High label cardinality: thousands of distinct selector groups vs a
handful of tensor slots (flatten.GroupBucket hash-sharing).

Invariants under test (the correctness contract of bucket sharing):
  1. placements the device allows NEVER violate any real anti-affinity
     (bucket counts are upper bounds — they only over-block);
  2. a no-fit verdict for a pod riding a collided bucket is NOT final:
     it escapes to the per-pod oracle and schedules if truly feasible;
  3. the escape fraction is measured and exposed (backend stats).

Reference anchor: pkg/scheduler/framework/plugins/interpodaffinity
(the exact per-pod semantics the oracle re-proof runs).
"""

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import PODS
from kubernetes_tpu.ops.backend import TPUBatchBackend
from kubernetes_tpu.ops.flatten import (
    Caps, ClusterTensors, SelectorGroup,
)
from kubernetes_tpu.api.labels import selector_from_dict
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod, wait_for


def sg(app: str, topo: str = "kubernetes.io/hostname") -> SelectorGroup:
    return SelectorGroup(topo, selector_from_dict(
        {"matchLabels": {"app": app}}), frozenset(["default"]))


class TestBucketSharing:
    def test_registration_beyond_cap_shares_buckets(self):
        caps = Caps(n_cap=64, sg_cap=4, asg_cap=4)
        t = ClusterTensors(caps)
        idxs = [t.register_asg(sg(f"svc-{i}")) for i in range(40)]
        assert all(i is not None for i in idxs)
        assert len(t.asgs) == 4
        assert any(b.collided for b in t.asgs)
        # deterministic: same groups -> same buckets
        t2 = ClusterTensors(caps)
        idxs2 = [t2.register_asg(sg(f"svc-{i}")) for i in range(40)]
        assert idxs == idxs2

    def test_cross_topology_groups_never_share(self):
        caps = Caps(n_cap=64, sg_cap=2, asg_cap=2)
        t = ClusterTensors(caps)
        a = t.register_sg(sg("a", "kubernetes.io/hostname"))
        b = t.register_sg(sg("b", "topology.kubernetes.io/zone"))
        # caps full with one bucket per topo key; a third key can't land
        c = t.register_sg(sg("c", "other.io/rack"))
        assert a is not None and b is not None
        assert t.sgs[a].topology_key != t.sgs[b].topology_key
        assert c is None  # no same-topology bucket -> escape, as before

    def test_enabler_constraints_never_share(self):
        """Required affinity / DoNotSchedule spread counts ENABLE
        placement — union counts could falsely satisfy them, so those
        registrations must refuse shared slots (old escape behavior)."""
        caps = Caps(n_cap=64, sg_cap=2, asg_cap=2)
        t = ClusterTensors(caps)
        # fill the registry with shareable (anti-style) groups
        a = t.register_sg(sg("svc-a"), shareable=True)
        b = t.register_sg(sg("svc-b"), shareable=True)
        assert a is not None and b is not None
        # overflow shareable joins a bucket; exclusive refuses
        c = t.register_sg(sg("svc-c"), shareable=True)
        assert c is not None and t.sgs[c].collided
        d = t.register_sg(sg("svc-d"))  # enabler: needs exclusive
        assert d is None
        # an enabler request for a group living in a SHARED bucket also
        # refuses (its counts are inflated)
        e = t.register_sg(sg("svc-c"))
        assert e is None

    def test_exclusive_pin_blocks_later_sharing(self):
        """A slot used by an enabler constraint must never accept
        overflow members afterwards."""
        caps = Caps(n_cap=64, sg_cap=1, asg_cap=1)
        t = ClusterTensors(caps)
        a = t.register_sg(sg("svc-a"), shareable=True)
        assert t.sgs[a].allow_share
        assert t.register_sg(sg("svc-a")) == a  # enabler user pins it
        assert not t.sgs[a].allow_share
        assert t.register_sg(sg("svc-b"), shareable=True) is None

    def test_bucket_counts_are_upper_bounds(self):
        caps = Caps(n_cap=8, sg_cap=1, asg_cap=1)
        t = ClusterTensors(caps)
        ia = t.register_asg(sg("svc-a"))
        ib = t.register_asg(sg("svc-b"))
        assert ia == ib  # forced to share
        assert t.asgs[ia].collided


class TestEndToEndCorrectness:
    N_NODES = 12
    N_SVC = 8    # distinct services
    PER_SVC = 3  # pods per service (need 3 distinct nodes each)

    def _cluster(self, caps):
        store = kv.MemoryStore(history=100_000)
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        backend = TPUBatchBackend(caps, batch_size=32)
        fw = new_default_framework(client, factory)
        sched = Scheduler(client, factory, {"default-scheduler": Profile(
            fw, batch_backend=backend, batch_size=32)})
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        return store, client, factory, sched, backend

    def test_no_violations_and_no_false_unschedulable(self):
        """8 services x 3 pods with hostname anti-affinity through 2
        shared asg buckets: every pod schedules (no false
        unschedulable), and no node ever hosts two pods of the same
        service (no violation)."""
        caps = Caps(n_cap=16, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                    s_cap=2, sg_cap=4, asg_cap=2)
        store, client, factory, sched, backend = self._cluster(caps)
        try:
            for i in range(self.N_NODES):
                client.create("nodes", make_node(f"n{i}")
                              .labels(**{"kubernetes.io/hostname": f"n{i}"})
                              .capacity(cpu="16", mem="64Gi").build())
            for s in range(self.N_SVC):
                for j in range(self.PER_SVC):
                    client.create(PODS, make_pod(f"svc{s}-p{j}")
                                  .labels(app=f"svc-{s}")
                                  .req(cpu="100m")
                                  .pod_affinity("kubernetes.io/hostname",
                                                {"app": f"svc-{s}"},
                                                anti=True).build())
            total = self.N_SVC * self.PER_SVC

            def all_bound():
                pods, _ = client.list(PODS, "default")
                return sum(1 for p in pods
                           if meta.pod_node_name(p)) == total
            assert wait_for(all_bound, timeout=60.0), \
                "pods left unscheduled (false unschedulable)"
            pods, _ = client.list(PODS, "default")
            per_node_svc = {}
            for p in pods:
                nodesvc = (meta.pod_node_name(p),
                           p["metadata"]["labels"]["app"])
                assert nodesvc not in per_node_svc, \
                    f"anti-affinity violated: {nodesvc}"
                per_node_svc[nodesvc] = meta.name(p)
            # shared buckets were actually exercised
            assert any(b.collided for b in backend.tensors.asgs)
            assert backend.stats.get("pods", 0) >= total
        finally:
            sched.stop()
            factory.stop()
            client.close()

    def test_fuzz_no_violation_no_false_unschedulable(self):
        """Randomized trials: random service counts, pods per service,
        node counts and (tiny) bucket caps — every workload is feasible
        by construction (pods-per-service <= nodes), so the invariant
        is exact: ALL pods schedule, and no node hosts two pods of one
        service.  Catches kernel/bucket interactions a fixed shape
        misses."""
        import random
        rng = random.Random(7)
        for trial in range(4):
            n_nodes = rng.randrange(6, 14)
            n_svc = rng.randrange(3, 10)
            per_svc = rng.randrange(2, min(5, n_nodes) + 1)
            caps = Caps(n_cap=16, l_cap=64, kl_cap=32, t_cap=8,
                        pt_cap=8, s_cap=2,
                        sg_cap=rng.randrange(1, 5),
                        asg_cap=rng.randrange(1, 4))
            store, client, factory, sched, backend = self._cluster(caps)
            try:
                for i in range(n_nodes):
                    client.create(
                        "nodes", make_node(f"n{i}")
                        .labels(**{"kubernetes.io/hostname": f"n{i}"})
                        .capacity(cpu="64", mem="256Gi").build())
                for s in range(n_svc):
                    for j in range(per_svc):
                        client.create(
                            PODS, make_pod(f"t{trial}-s{s}-p{j}")
                            .labels(app=f"svc-{s}").req(cpu="100m")
                            .pod_affinity("kubernetes.io/hostname",
                                          {"app": f"svc-{s}"},
                                          anti=True).build())
                total = n_svc * per_svc

                def all_bound():
                    pods, _ = client.list(PODS, "default")
                    return sum(1 for p in pods
                               if meta.pod_node_name(p)) == total
                assert wait_for(all_bound, timeout=90.0), (
                    f"trial {trial}: false unschedulable "
                    f"(nodes={n_nodes} svc={n_svc} per={per_svc} "
                    f"sg={caps.sg_cap} asg={caps.asg_cap})")
                pods, _ = client.list(PODS, "default")
                seen = set()
                for p in pods:
                    key = (meta.pod_node_name(p),
                           p["metadata"]["labels"]["app"])
                    assert key not in seen, (
                        f"trial {trial}: violation {key}")
                    seen.add(key)
            finally:
                sched.stop()
                factory.stop()
                client.close()

    def test_escape_stats_exposed(self):
        caps = Caps(n_cap=16, sg_cap=4, asg_cap=2)
        store, client, factory, sched, backend = self._cluster(caps)
        try:
            for i in range(4):
                client.create("nodes", make_node(f"n{i}")
                              .labels(**{"kubernetes.io/hostname": f"n{i}"})
                              .capacity(cpu="8", mem="32Gi").build())
            # more same-bucket pods than nodes: some MUST no-fit on the
            # device and escape to the oracle (which also can't place
            # them all — but the escape path, not UNSCHEDULABLE-forever,
            # must carry them)
            for j in range(6):
                client.create(PODS, make_pod(f"tight-{j}")
                              .labels(app="svc-x").req(cpu="100m")
                              .pod_affinity("kubernetes.io/hostname",
                                            {"app": "svc-x"},
                                            anti=True).build())
            client.create(PODS, make_pod("other")
                          .labels(app="svc-y").req(cpu="100m")
                          .pod_affinity("kubernetes.io/hostname",
                                        {"app": "svc-y"},
                                        anti=True).build())

            def four_bound():
                pods, _ = client.list(PODS, "default")
                return sum(1 for p in pods
                           if meta.pod_node_name(p)) >= 5
            # 4 of svc-x fit (4 nodes) + svc-y's pod
            assert wait_for(four_bound, timeout=60.0)
            assert "pods" in backend.stats
        finally:
            sched.stop()
            factory.stop()
            client.close()
