"""HPA autoscaling/v2 semantics: multi-metric, tolerance, stabilization
windows, behavior policies.

Reference: pkg/controller/podautoscaler/horizontal.go —
computeReplicasForMetrics (max across metrics), tolerance,
stabilizeRecommendationWithBehaviors, normalizeDesiredReplicasWithBehaviors.
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import HPAS, PODS, REPLICASETS
from kubernetes_tpu.controllers.hpa import (
    CUSTOM_PREFIX, MEMORY_ANNOTATION, USAGE_ANNOTATION,
    HorizontalPodAutoscaler,
)
from kubernetes_tpu.store import kv


def wait_for(pred, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def hpa_env():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    ctrl = HorizontalPodAutoscaler(client, factory, tick=3600.0)
    factory.start()
    factory.wait_for_cache_sync()
    yield store, client, ctrl
    factory.stop()


def make_rs(client, replicas=2, cpu_req="500m", mem_req="256Mi"):
    rs = meta.new_object("ReplicaSet", "web", "default")
    rs["spec"] = {"replicas": replicas,
                  "selector": {"matchLabels": {"app": "web"}}}
    client.create(REPLICASETS, rs)
    for i in range(replicas):
        pod = meta.new_object("Pod", f"web-{i}", "default")
        pod["metadata"]["labels"] = {"app": "web"}
        pod["spec"] = {"containers": [{
            "name": "c0", "image": "i",
            "resources": {"requests": {"cpu": cpu_req,
                                       "memory": mem_req}}}]}
        client.create(PODS, pod)


def annotate(client, anns):
    for p in client.list(PODS, "default")[0]:
        def patch(o, anns=anns):
            o["metadata"].setdefault("annotations", {}).update(anns)
            return o
        client.guaranteed_update(PODS, "default", meta.name(p), patch)


def make_hpa(client, spec):
    hpa = meta.new_object("HorizontalPodAutoscaler", "h", "default")
    hpa["spec"] = {"scaleTargetRef": {"kind": "ReplicaSet", "name": "web"},
                   "minReplicas": 1, "maxReplicas": 20, **spec}
    client.create(HPAS, hpa)


def replicas(client):
    return client.get(REPLICASETS, "default", "web")["spec"]["replicas"]


def sync(ctrl, client, now=None):
    assert wait_for(lambda: ctrl.hpa_informer.get("default", "h") is not None)
    assert wait_for(lambda: len(ctrl.pod_informer.list("default")) >= 1)
    ctrl.reconcile_once(now if now is not None else time.time())


class TestMultiMetric:
    def test_max_of_metrics_wins(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        # cpu at target (no scale), memory at 2x target -> memory wins
        annotate(client, {USAGE_ANNOTATION: "500m",
                          MEMORY_ANNOTATION: "512Mi"})
        make_hpa(client, {"metrics": [
            {"type": "Resource", "resource": {
                "name": "cpu", "target": {"type": "Utilization",
                                          "averageUtilization": 100}}},
            {"type": "Resource", "resource": {
                "name": "memory", "target": {"type": "Utilization",
                                             "averageUtilization": 100}}},
        ]})
        sync(ctrl, client)
        assert replicas(client) == 4  # ceil(2 * 200 / 100)

    def test_pods_custom_metric(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {CUSTOM_PREFIX + "qps": "300"})
        make_hpa(client, {"metrics": [
            {"type": "Pods", "pods": {
                "metric": {"name": "qps"},
                "target": {"averageValue": "100"}}}]})
        sync(ctrl, client)
        assert replicas(client) == 6  # ceil(2 * 300/100)

    def test_average_value_resource_target(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "400m"})
        make_hpa(client, {"metrics": [
            {"type": "Resource", "resource": {
                "name": "cpu", "target": {"type": "AverageValue",
                                          "averageValue": "200m"}}}]})
        sync(ctrl, client)
        assert replicas(client) == 4


class TestTolerance:
    def test_within_tolerance_holds(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "525m"})  # 105% of 500m target
        make_hpa(client, {"metrics": [
            {"type": "Resource", "resource": {
                "name": "cpu", "target": {"type": "Utilization",
                                          "averageUtilization": 100}}}]})
        sync(ctrl, client)
        assert replicas(client) == 2  # ratio 1.05 within the 0.1 band


class TestStabilization:
    def test_scale_down_waits_out_window(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=4)
        # first reconcile at target: the window records "4 is right"
        annotate(client, {USAGE_ANNOTATION: "400m"})  # exactly 80% of 500m
        make_hpa(client, {"targetCPUUtilizationPercentage": 80})
        t0 = time.time()
        sync(ctrl, client, now=t0)
        assert replicas(client) == 4
        # load drops: the 300s window still holds the higher recommendation
        annotate(client, {USAGE_ANNOTATION: "50m"})  # 10%
        assert wait_for(lambda: all(
            (p["metadata"].get("annotations") or {}).get(
                USAGE_ANNOTATION) == "50m"
            for p in ctrl.pod_informer.list("default")))
        ctrl.reconcile_once(t0 + 10)
        assert replicas(client) == 4
        # window expired: the low recommendation finally wins
        ctrl.reconcile_once(t0 + 301)
        assert replicas(client) == 1

    def test_scale_up_window_picks_min_recommendation(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "800m"})  # 160%
        make_hpa(client, {
            "targetCPUUtilizationPercentage": 80,
            "behavior": {"scaleUp": {"stabilizationWindowSeconds": 120}}})
        t0 = time.time()
        sync(ctrl, client, now=t0)
        # up-stabilization: the window min includes this first (low)
        # recommendation moment? The first rec IS 4; min over window = 4
        assert replicas(client) == 4


class TestBehaviorPolicies:
    def test_scale_up_pods_policy_limits_step(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "4000m"})  # 800% -> wants 20
        make_hpa(client, {
            "targetCPUUtilizationPercentage": 80,
            "behavior": {"scaleUp": {"policies": [
                {"type": "Pods", "value": 3, "periodSeconds": 60}]}}})
        t0 = time.time()
        sync(ctrl, client, now=t0)
        assert replicas(client) == 5  # 2 + 3 max per period
        # same period: the event history blocks further growth
        ctrl.reconcile_once(t0 + 1)
        assert replicas(client) == 5
        # next period: another step of 3 allowed (relative to current=5)
        ctrl.reconcile_once(t0 + 61)
        assert replicas(client) == 8

    def test_opposite_direction_events_do_not_inflate_budget(self, hpa_env):
        """A recent scale-UP must not grant extra scale-DOWN room (the
        reference keeps separate scaleUpEvents/scaleDownEvents)."""
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "4000m"})  # wants max
        make_hpa(client, {
            "targetCPUUtilizationPercentage": 80,
            "maxReplicas": 8,
            "behavior": {
                "scaleUp": {"policies": [
                    {"type": "Pods", "value": 6, "periodSeconds": 60}]},
                "scaleDown": {
                    "stabilizationWindowSeconds": 0,
                    "policies": [{"type": "Pods", "value": 2,
                                  "periodSeconds": 60}]}}})
        t0 = time.time()
        sync(ctrl, client, now=t0)
        assert replicas(client) == 8  # scaled up +6 (event recorded)
        # load vanishes: scale-down budget is 2/period regardless of the
        # +6 up-event sitting in the same window
        annotate(client, {USAGE_ANNOTATION: "10m"})
        assert wait_for(lambda: all(
            (p["metadata"].get("annotations") or {}).get(
                USAGE_ANNOTATION) == "10m"
            for p in ctrl.pod_informer.list("default")))
        ctrl.reconcile_once(t0 + 1)
        assert replicas(client) == 6  # 8 - 2, NOT 8 - (2 + 6)

    def test_scale_down_percent_policy(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=10)
        annotate(client, {USAGE_ANNOTATION: "10m"})
        make_hpa(client, {
            "targetCPUUtilizationPercentage": 80,
            "behavior": {"scaleDown": {
                "stabilizationWindowSeconds": 0,
                "policies": [{"type": "Percent", "value": 50,
                              "periodSeconds": 60}]}}})
        t0 = time.time()
        sync(ctrl, client, now=t0)
        assert replicas(client) == 5  # at most 50% per period

    def test_scale_down_disabled(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=6)
        annotate(client, {USAGE_ANNOTATION: "10m"})
        make_hpa(client, {
            "targetCPUUtilizationPercentage": 80,
            "behavior": {"scaleDown": {
                "stabilizationWindowSeconds": 0,
                "selectPolicy": "Disabled"}}})
        sync(ctrl, client)
        assert replicas(client) == 6

    def test_v1_status_compat_field(self, hpa_env):
        store, client, ctrl = hpa_env
        make_rs(client, replicas=2)
        annotate(client, {USAGE_ANNOTATION: "500m"})
        make_hpa(client, {"targetCPUUtilizationPercentage": 100})
        sync(ctrl, client)
        hpa = client.get(HPAS, "default", "h")
        assert hpa["status"]["currentCPUUtilizationPercentage"] == 100
        assert hpa["status"]["currentMetrics"]