"""Self-tests for the ktpu-lint engine (tools/ktpulint).

Every rule gets a seeded-violation fixture that must FIRE and a clean
fixture that must stay silent — the lint gate is only trustworthy if the
rules themselves are pinned.  Engine mechanics (suppression comments,
baselines, annotation-block scanning) are covered at the bottom.

Fixture trees are built per-test under tmp_path; project-scope rules
that import fixture packages use unique package names so sys.modules
never aliases two tests together.
"""

from __future__ import annotations

import json
import textwrap

import pytest

from tools.ktpulint.engine import (
    Finding, LintContext, all_rules, load_baseline, run_lint, write_baseline,
)

PKG = "fixpkg"


def make_ctx(tmp_path, files: dict[str, str], package_name: str = PKG,
             **kw) -> LintContext:
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        if rel.endswith(".py"):
            paths.append(p)
    return LintContext(tmp_path, targets=paths, package_name=package_name,
                       **kw)


def run_rule(ctx: LintContext, name: str) -> list[Finding]:
    return run_lint(ctx, rule_names=[name])


def test_registry_has_the_full_catalog():
    rules = all_rules()
    assert len(rules) >= 23
    for name, rule in rules.items():
        assert name == rule.name
        assert rule.doc, f"rule {name} has no doc line"
        assert rule.scope in ("file", "project")


# -- wiring rules ----------------------------------------------------------

def test_module_imports_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {
        "fixpkg_mi_bad/__init__.py": "",
        "fixpkg_mi_bad/boom.py": 'raise RuntimeError("import-time kaboom")\n',
    }, package_name="fixpkg_mi_bad")
    found = run_rule(ctx, "module-imports")
    assert any("boom" in f.message for f in found)

    ctx = make_ctx(tmp_path / "ok", {
        "fixpkg_mi_ok/__init__.py": "",
        "fixpkg_mi_ok/fine.py": "X = 1\n",
    }, package_name="fixpkg_mi_ok")
    assert run_rule(ctx, "module-imports") == []


def test_reference_citation_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {
        f"{PKG}/__init__.py": "",
        f"{PKG}/uncited.py": '"""A module with no reference."""\n',
    })
    found = run_rule(ctx, "reference-citation")
    assert [f.path for f in found] == [f"{PKG}/uncited.py"]

    ctx = make_ctx(tmp_path / "ok", {
        f"{PKG}/__init__.py": "",
        f"{PKG}/cited.py":
            '"""Mirrors pkg/scheduler/framework/plugins/noderesources."""\n',
    })
    assert run_rule(ctx, "reference-citation") == []


_CSS_COMMON = {
    "client/__init__.py": "",
    "client/clientset.py":
        'CLUSTER_SCOPED_RESOURCES = frozenset({"nodes", "namespaces"})\n',
    "client/http_client.py": """\
        from . import clientset

        class HTTPClient:
            def __init__(self,
                         cluster_scoped=clientset.CLUSTER_SCOPED_RESOURCES):
                self.cluster_scoped = cluster_scoped
        """,
    "apiserver/__init__.py": "",
}


def test_cluster_scoped_share_fires_and_clean(tmp_path):
    bad = {f"fixpkg_css_bad/{k}": v for k, v in _CSS_COMMON.items()}
    bad["fixpkg_css_bad/__init__.py"] = ""
    bad["fixpkg_css_bad/apiserver/server.py"] = \
        'CLUSTER_SCOPED = frozenset({"nodes", "namespaces"})  # a FORK\n'
    ctx = make_ctx(tmp_path, bad, package_name="fixpkg_css_bad")
    found = run_rule(ctx, "cluster-scoped-share")
    assert any("fork" in f.message for f in found)

    ok = {f"fixpkg_css_ok/{k}": v for k, v in _CSS_COMMON.items()}
    ok["fixpkg_css_ok/__init__.py"] = ""
    ok["fixpkg_css_ok/apiserver/server.py"] = """\
        from ..client.clientset import CLUSTER_SCOPED_RESOURCES

        CLUSTER_SCOPED = CLUSTER_SCOPED_RESOURCES
        """
    ctx = make_ctx(tmp_path / "ok", ok, package_name="fixpkg_css_ok")
    assert run_rule(ctx, "cluster-scoped-share") == []


def test_pause_independence_fires_and_clean(tmp_path):
    native = tmp_path / "native"
    (native / "pause").mkdir(parents=True)
    (native / "pause" / "pause.c").write_text(
        "static void sigdown(int s) {}\n"
        "int main(void) { struct sigaction sa = {.sa_handler = sigdown}; }\n")
    ctx = make_ctx(tmp_path, {f"{PKG}/__init__.py": ""}, native_dir=native)
    found = run_rule(ctx, "pause-independence")
    assert any("sigwaitinfo" in f.message for f in found)
    assert any("sigaction" in f.message for f in found)

    (native / "pause" / "pause.c").write_text(
        "int main(void) { siginfo_t si; sigwaitinfo(&set, &si); }\n")
    ctx = make_ctx(tmp_path, {f"{PKG}/__init__.py": ""}, native_dir=native)
    assert run_rule(ctx, "pause-independence") == []


_CR_COMMON = {
    "__init__.py": "",
    "controllers/__init__.py": "",
    "controllers/base.py": """\
        class Controller:
            name = "controller"
        """,
    "controllers/endpoints.py": """\
        from .base import Controller

        class EndpointsController(Controller):
            name = "endpoints"
        """,
    "controllers/cloud.py": """\
        from .base import Controller

        class CloudServiceController(Controller):
            name = "cloud-service"

        class CloudRouteController(Controller):
            name = "cloud-route"

        class CloudNodeController(Controller):
            name = "cloud-node"
        """,
    "controllers/orphan.py": """\
        from .base import Controller

        class OrphanController(Controller):
            name = "orphan"
        """,
}


def test_controller_registry_fires_and_clean(tmp_path):
    bad = {f"fixpkg_cr_bad/{k}": v for k, v in _CR_COMMON.items()}
    bad["fixpkg_cr_bad/controllers/manager.py"] = """\
        class ControllerManager:
            CTORS = {}
        """
    ctx = make_ctx(tmp_path, bad, package_name="fixpkg_cr_bad")
    found = run_rule(ctx, "controller-registry")
    assert any("OrphanController" in f.message for f in found)

    ok = {f"fixpkg_cr_ok/{k}": v for k, v in _CR_COMMON.items()}
    ok["fixpkg_cr_ok/controllers/manager.py"] = """\
        from .orphan import OrphanController

        class ControllerManager:
            CTORS = {"orphan": OrphanController}
        """
    ctx = make_ctx(tmp_path / "ok", ok, package_name="fixpkg_cr_ok")
    assert run_rule(ctx, "controller-registry") == []


# -- lifecycle rules -------------------------------------------------------

def test_net_timeout_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {"a.py": """\
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url).read()
        """})
    found = run_rule(ctx, "net-timeout")
    assert len(found) == 1 and found[0].line == 4

    ctx = make_ctx(tmp_path / "ok", {"a.py": """\
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url, timeout=5.0).read()
        """})
    assert run_rule(ctx, "net-timeout") == []


def test_span_lifecycle_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {"a.py": """\
        def leaky(tracer):
            span = tracer.start_span("wave")
            return span
        """})
    found = run_rule(ctx, "span-lifecycle")
    assert len(found) == 1 and "leaky" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {"a.py": """\
        def managed(tracer):
            with tracer.start_span("wave"):
                pass

        def explicit(tracer):
            span = tracer.start_span("wave")
            span.end()
        """})
    assert run_rule(ctx, "span-lifecycle") == []


def test_timeline_stage_paired_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {"a.py": """\
        def leaky(tl):
            tok = tl.begin("h2d")
            return tok

        def leaky_attr(self):
            self._timeline.begin("resolve")
        """})
    found = run_rule(ctx, "timeline-stage-paired")
    assert len(found) == 2
    assert "leaky" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {"a.py": """\
        def managed(tl):
            with tl.begin("h2d"):
                pass

        def explicit(timeline):
            tok = timeline.begin("resolve")
            timeline.end(tok)

        def retroactive(tl, t0, t1):
            tl.record("d2h", t0, t1)

        def unrelated(db):
            db.begin("txn")  # not a timeline receiver
        """})
    assert run_rule(ctx, "timeline-stage-paired") == []


def test_retry_backoff_fires_and_clean(tmp_path):
    bad = """\
        def run(self):
            while True:
                try:
                    self.poll()
                except Exception:
                    continue
        """
    ctx = make_ctx(tmp_path, {"client/informer.py": bad})
    found = run_rule(ctx, "retry-backoff")
    assert len(found) == 1

    # same loop outside the audited module set: silent by design
    ctx = make_ctx(tmp_path / "other", {"client/widget.py": bad})
    assert run_rule(ctx, "retry-backoff") == []

    ctx = make_ctx(tmp_path / "ok", {"client/informer.py": """\
        import time

        def run(self):
            while True:
                try:
                    self.poll()
                except Exception:
                    time.sleep(self.backoff())
        """})
    assert run_rule(ctx, "retry-backoff") == []


# -- pipeline rules --------------------------------------------------------

def test_escape_reason_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {"ops/flatten.py": """\
        class Enc:
            def encode(self, i):
                self.escape.append(i)
        """})
    found = run_rule(ctx, "escape-reason")
    assert len(found) == 1 and "encode" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {"ops/flatten.py": """\
        class Enc:
            def encode(self, i):
                self.escape.append(i)
                self.escape_reasons[i] = ("Plugin", "why")
        """})
    assert run_rule(ctx, "escape-reason") == []


def test_eviction_confinement_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/scheduler/rogue.py": """\
        def drop(self, name):
            self.client.delete(PODS, name)
        """})
    found = run_rule(ctx, "eviction-confinement")
    assert len(found) == 1 and "drop" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/scheduler/preemption.py": """\
        def evict_victims(self, names):
            for n in names:
                self.client.delete(PODS, n)
        """})
    assert run_rule(ctx, "eviction-confinement") == []


def test_overload_metric_reason_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {
        "scheduler/queue.py": """\
            def on_cap(self, why):
                self._shed_over_cap_locked(why)
            """,
        "scheduler/scheduler.py": """\
            def defer(self):
                self.metrics.overload_deferred_total.inc(1)
            """})
    found = run_rule(ctx, "overload-metric-reason")
    assert len(found) == 2
    assert any("string-literal" in f.message for f in found)
    assert any("reason label" in f.message for f in found)

    ctx = make_ctx(tmp_path / "ok", {
        "scheduler/queue.py": """\
            def on_cap(self):
                self._shed_over_cap_locked("backoff_cap")
            """,
        "scheduler/scheduler.py": """\
            def defer(self):
                self.metrics.overload_deferred_total.inc(1, "admission_gate")
            """})
    assert run_rule(ctx, "overload-metric-reason") == []


def test_bind_conflict_handled_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/scheduler/rogue.py": """\
        def commit(self, bindings):
            return self.client.bind_many(bindings)
        """})
    found = run_rule(ctx, "bind-conflict-handled")
    assert len(found) == 1
    assert "bind_many" in found[0].message and "commit" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/scheduler/good.py": """\
        def commit(self, bindings):
            try:
                return self.client.bind_many(bindings)
            except kv.BindConflict:
                raise

        def serve(self, listener):
            listener.bind(("127.0.0.1", 0))
        """})
    assert run_rule(ctx, "bind-conflict-handled") == []


def test_bind_conflict_handled_exempts_bind_layers(tmp_path):
    # the clientset / transport / store layers ARE the bind
    # implementation; the rule only audits callers above them
    ctx = make_ctx(tmp_path, {
        f"{PKG}/client/clientset.py": """\
            def bind(self, pod, node):
                return self.client.bind(pod, node)
            """,
        f"{PKG}/store/replica.py": """\
            def bind_many(self, *a):
                return self.client.bind_many(*a)
            """})
    assert run_rule(ctx, "bind-conflict-handled") == []


_TAXO_README_OK = """\
    # fixture

    ### Escape hatch

    | Plugin/reason | Why |
    |---|---|
    | `NodePorts/port_clash` | host port collision |

    ### Overload protections

    Sheds with reason `backoff_cap`.
    """


def test_taxonomy_sync_code_to_readme(tmp_path):
    ctx = make_ctx(tmp_path, {
        f"{PKG}/ops/flatten.py": """\
            class Enc:
                def f(self, i):
                    self._esc("Ghost", "mystery_reason")
            """,
        "README.md": _TAXO_README_OK,
    }, readme=tmp_path / "README.md")
    found = run_rule(ctx, "taxonomy-sync")
    msgs = " ".join(f.message for f in found)
    assert "'Ghost'" in msgs and "'mystery_reason'" in msgs
    # ... and the README's own row now lacks an emit site too
    assert "'NodePorts'" in msgs


def test_taxonomy_sync_readme_to_code_and_clean(tmp_path):
    code = {f"{PKG}/ops/flatten.py": """\
        class Enc:
            def f(self, i):
                self._esc("NodePorts", "port_clash")
        """,
        f"{PKG}/scheduler/queue.py": """\
        class Q:
            def g(self):
                self._shed_over_cap_locked("backoff_cap")
        """}
    stale = dict(code)
    # NB: _TAXO_README_OK ends with the closing-quote indent, so the
    # appended row must carry none of its own
    stale["README.md"] = _TAXO_README_OK + \
        "| `Stale/old_reason` | gone |\n"
    ctx = make_ctx(tmp_path, stale, readme=tmp_path / "README.md")
    found = run_rule(ctx, "taxonomy-sync")
    msgs = " ".join(f.message for f in found)
    assert "'Stale'" in msgs and "'old_reason'" in msgs

    clean = dict(code)
    clean["README.md"] = _TAXO_README_OK
    ctx = make_ctx(tmp_path / "ok", clean,
                   readme=tmp_path / "ok" / "README.md")
    assert run_rule(ctx, "taxonomy-sync") == []


_TAXO_SCALEOUT_README = _TAXO_README_OK + """\

    ### Horizontal scale-out

    | outcome | meaning |
    |---|---|
    | `requeued` | still unbound, back through backoff |
    | `lost_to_peer` | a peer owns the pod now |
    | `already_bound_same_node` | our own write landed |
    | `fenced` | write fence, wave requeues whole |
    """


def test_taxonomy_sync_covers_bind_conflict_outcomes(tmp_path):
    # the three scale-out emit shapes: outcome = "..." assignments,
    # _conflict_requeue(forced=...), bind_conflict_total.inc literals
    code = {
        f"{PKG}/ops/flatten.py": """\
            class Enc:
                def f(self, i):
                    self._esc("NodePorts", "port_clash")
            """,
        f"{PKG}/scheduler/queue.py": """\
            class Q:
                def g(self):
                    self._shed_over_cap_locked("backoff_cap")
            """,
        f"{PKG}/scheduler/scheduler.py": """\
            class S:
                def resolve(self, fw, entries, bound_elsewhere, fenced):
                    if fenced:
                        self._conflict_requeue(fw, entries, None,
                                               forced="fenced")
                        return
                    outcome = "requeued"
                    if bound_elsewhere:
                        outcome = "lost_to_peer"
                    self.metrics.prom.bind_conflict_total.inc(
                        1.0, "already_bound_same_node")
                    return outcome
            """}
    clean = dict(code)
    clean["README.md"] = _TAXO_SCALEOUT_README
    ctx = make_ctx(tmp_path, clean, readme=tmp_path / "README.md")
    assert run_rule(ctx, "taxonomy-sync") == []

    # drop one outcome row from the README: its emit site is now
    # undocumented and the rule must name it
    stale = dict(code)
    stale["README.md"] = _TAXO_SCALEOUT_README.replace(
        "| `fenced` | write fence, wave requeues whole |\n", "")
    ctx = make_ctx(tmp_path / "stale", stale,
                   readme=tmp_path / "stale" / "README.md")
    found = run_rule(ctx, "taxonomy-sync")
    msgs = " ".join(f.message for f in found)
    assert "'fenced'" in msgs and "'requeued'" not in msgs


# -- observability rules ---------------------------------------------------

_METRICS_README = """\
    # Fix

    ### Metrics

    | metric | kind |
    |---|---|
    | `fix_binds_total` | counter |
    """


def test_metric_documented_fires_both_directions(tmp_path):
    stale = _METRICS_README + "| `fix_stale_total` | counter |\n"
    ctx = make_ctx(tmp_path, {
        f"{PKG}/scheduler/metrics.py": """\
            from ..component_base import metrics as cbm

            BINDS = cbm.Counter("fix_binds_total", "Binds.")
            GHOST = cbm.Gauge("fix_ghost_gauge", "Never documented.")
            """,
        "README.md": stale,
    }, readme=tmp_path / "README.md")
    found = run_rule(ctx, "metric-documented")
    msgs = " ".join(f.message for f in found)
    assert "'fix_ghost_gauge'" in msgs      # constructed, undocumented
    assert "'fix_stale_total'" in msgs      # documented, never constructed
    assert "'fix_binds_total'" not in msgs


def test_metric_documented_clean_and_counter_discriminator(tmp_path):
    ctx = make_ctx(tmp_path, {
        f"{PKG}/scheduler/metrics.py": """\
            from collections import Counter

            from ..component_base import metrics as cbm

            BINDS = cbm.Counter("fix_binds_total", "Binds.",
                                labels=("result",))
            tallies = Counter()              # NOT a metric: no name+help
            hist = Counter(["a", "b"])
            """,
        "README.md": _METRICS_README,
    }, readme=tmp_path / "README.md")
    assert run_rule(ctx, "metric-documented") == []


def test_profiling_gated_fires_on_defaults_and_bare_hooks(tmp_path):
    ctx = make_ctx(tmp_path, {
        f"{PKG}/scheduler/config.py": """\
            class ProfilingPolicy:
                enabled: bool = True
                census: bool = False
            """,
        f"{PKG}/perf/harness.py": """\
            from ..component_base import profiling

            def setup(sched, profiler):
                sched.configure_profiling(profiler)
                sched.run_device_census()
                profiling.default_host_profiler.start()
            """,
    })
    found = run_rule(ctx, "profiling-gated")
    msgs = " ".join(f.message for f in found)
    assert "ProfilingPolicy.enabled" in msgs
    assert "configure_profiling" in msgs
    assert "run_device_census" in msgs
    assert "default_host_profiler.start" in msgs


def test_profiling_gated_clean_when_stanza_guarded(tmp_path):
    ctx = make_ctx(tmp_path, {
        f"{PKG}/scheduler/config.py": """\
            class ProfilingPolicy:
                enabled: bool = False
                census: bool = False
            """,
        f"{PKG}/perf/harness.py": """\
            from ..component_base import profiling

            def setup(cfg, sched, profiler):
                if cfg.profiling.enabled or cfg.profiling.census:
                    profiling.default_host_profiler.start()
                    sched.configure_profiling(profiler)
                    sched.run_device_census()
            """,
    })
    assert run_rule(ctx, "profiling-gated") == []


# -- device rules ----------------------------------------------------------

def test_device_sync_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/hot.py": """\
        import numpy as np

        def pull(scores_dev):
            n = scores_dev.item()
            f = float(scores_dev)
            a = np.asarray(scores_dev)
            return n, f, a
        """})
    found = run_rule(ctx, "device-sync")
    assert len(found) == 3

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/ops/hot.py": """\
        import jax
        import numpy as np

        def pull(scores_dev, host_rows):
            # sync-point: wave resolve pulls the winner row
            n = jax.device_get(scores_dev).item()
            a = np.asarray(host_rows, np.float32)
            return n, a
        """})
    assert run_rule(ctx, "device-sync") == []


def test_device_sync_ignores_cold_path(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/client/cold.py": """\
        def pull(x_dev):
            return x_dev.item()
        """})
    assert run_rule(ctx, "device-sync") == []


def test_recompile_hazard_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/models/hot.py": """\
        import jax

        def build(core):
            return jax.jit(core)

        @jax.jit
        def kernel(x):
            if x.shape[0] > 4:
                return x * 2
            return x
        """})
    found = run_rule(ctx, "recompile-hazard")
    msgs = " ".join(f.message for f in found)
    assert "fresh compile cache" in msgs
    assert "forks the trace" in msgs

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/models/hot.py": """\
        import jax

        def build(core):
            # compile-cached: built once at setup; caller holds the jit
            return jax.jit(core)
        """})
    assert run_rule(ctx, "recompile-hazard") == []


def test_recompile_hazard_unhashable_static_arg(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/models/hot.py": """\
        import jax

        step = jax.jit(_core, static_argnames=("dims",))

        def drive(x):
            return step(x, dims=[1, 2, 3])
        """})
    found = run_rule(ctx, "recompile-hazard")
    assert len(found) == 1 and "unhashable" in found[0].message


def test_replicated_large_tensor_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/parallel/mesh.py": """\
        NODE_PARTITION_RULES = (
            (r"^(alloc|used)$", ("@nodes", None)),
            (r"^big_table$", ()),
        )
        """})
    found = run_rule(ctx, "replicated-large-tensor")
    assert len(found) == 1 and "big_table" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/parallel/mesh.py": """\
        NODE_PARTITION_RULES = (
            (r"^(alloc|used)$", ("@nodes", None)),
            (r"^cd_counts$", ()),  # replicated-ok: kernel keeps it coherent
        )
        """})
    assert run_rule(ctx, "replicated-large-tensor") == []


def test_replicated_large_tensor_ignores_other_dirs(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/tables.py": """\
        MY_PARTITION_RULES = ((r".*", ()),)
        """})
    assert run_rule(ctx, "replicated-large-tensor") == []


def test_tensor_patch_discipline_outside_write_fires(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/backend.py": """\
        def clobber(t, rows):
            t.used[rows] = 0.0
        """})
    found = run_rule(ctx, "tensor-patch-discipline")
    assert len(found) == 1 and "ClusterTensors.used" in found[0].message


def test_tensor_patch_discipline_attr_chain_and_augassign(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/scheduler/hot.py": """\
        def drift(backend, row):
            backend.tensors.npods[row] += 1.0
        """})
    found = run_rule(ctx, "tensor-patch-discipline")
    assert len(found) == 1 and "npods" in found[0].message


def test_tensor_patch_discipline_annotation_and_dict_mirror_quiet(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/backend.py": """\
        def rebuild(t, m, rows):
            # patch-ok: full re-flatten rebuilds every row from scratch
            t.used[rows] = 0.0
            m["used"][rows] = 0.0  # host mirror dict, not ClusterTensors
        """})
    assert run_rule(ctx, "tensor-patch-discipline") == []


def test_tensor_patch_discipline_api_must_bump_gen(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/flatten.py": """\
        class ClusterTensors:
            def patch_node(self, name, ni):
                self.used[0] = 1.0
                return 0

            def patch_remove(self, name):
                row = self._release_row(name)
                self.version += 1
                self.patch_gen += 1
                return row
        """})
    found = run_rule(ctx, "tensor-patch-discipline")
    assert len(found) == 1 and "patch_node" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/ops/flatten.py": """\
        class ClusterTensors:
            def patch_node(self, name, ni):
                self.used[0] = 1.0
                self.patch_gen += 1
                return 0
        """})
    assert run_rule(ctx, "tensor-patch-discipline") == []


def test_tensor_patch_discipline_real_tree_is_clean():
    import pathlib
    repo = pathlib.Path(__file__).resolve().parents[1]
    ctx = LintContext(repo)
    assert run_rule(ctx, "tensor-patch-discipline") == []


def test_donated_buffer_reuse_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/hot.py": """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 2))
        def step(state, static, buf):
            return state, buf * 2

        def drive(state, static, buf):
            state, out = step(state, static, buf)
            return buf.sum(), out
        """})
    found = run_rule(ctx, "donated-buffer-reuse")
    assert len(found) == 1
    assert "buf was donated" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {f"{PKG}/ops/hot.py": """\
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, donate_argnums=(0, 2))
        def step(state, static, buf):
            return state, buf * 2

        def drive(state, static, buf, make_buf):
            # the resident-state idiom: the donated input is rebound
            # from the call's output, so later reads see a live buffer
            state, out = step(state, static, buf)
            buf = make_buf()
            total = buf.sum()
            return state, out, total

        def drive_wrapped(state, static, buf):
            # wrapped arg: the donated buffer is the fresh conversion,
            # not the host array — buf stays readable
            state, out = step(state, static, jnp.asarray(buf))
            return buf.sum(), out

        def drive_annotated(state, static, buf):
            state, out = step(state, static, buf)
            # donate-ok: host staging copy; the seam re-converts it
            return buf.sum(), out
        """})
    assert run_rule(ctx, "donated-buffer-reuse") == []


def test_donated_buffer_reuse_builders_and_closures(tmp_path):
    # builder-bound callables (compile_sharded / build_sharded_step_fn /
    # build_packed_assign_fn) register their donated argnums, the
    # builder CALL itself donates nothing, and a read inside a nested
    # resolve() closure counts — that's the retained-reference hazard
    ctx = make_ctx(tmp_path, {f"{PKG}/parallel/hot.py": """\
        class Backend:
            def setup(self, caps, mesh, weights):
                self._fn = build_sharded_step_fn(caps, mesh, weights)
                self._fn_full, self._spec = build_packed_assign_fn(caps)

            def dispatch(self, pods, prows, pvals):
                self._state, a, w, g = self._fn(
                    self._state, self._static, pods, prows, pvals)

                def resolve():
                    return a, pvals.sum()
                return resolve
        """})
    found = run_rule(ctx, "donated-buffer-reuse")
    assert len(found) == 1
    assert "pvals was donated" in found[0].message

    # _device_step convention: buf feeds the donated packed transport
    ctx = make_ctx(tmp_path / "seam", {f"{PKG}/ops/hot.py": """\
        class Backend:
            def dispatch(self, batch):
                buf = self.pack(batch)
                rd = self._device_step("full", buf)
                self.retained = buf
                return rd
        """})
    found = run_rule(ctx, "donated-buffer-reuse")
    assert len(found) == 1 and "buf was donated" in found[0].message


def test_donated_buffer_reuse_real_tree_is_clean():
    import pathlib
    repo = pathlib.Path(__file__).resolve().parents[1]
    ctx = LintContext(repo)
    assert run_rule(ctx, "donated-buffer-reuse") == []


# -- thread rules ----------------------------------------------------------

def test_lock_discipline_fires_and_clean(tmp_path):
    ctx = make_ctx(tmp_path, {"q.py": """\
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def racy_push(self, x):
                self._items.append(x)
        """})
    found = run_rule(ctx, "lock-discipline")
    assert len(found) == 1 and "racy_push" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {"q.py": """\
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._items = []  # guarded-by: _lock|_cond

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            def notify_push(self, x):
                with self._cond:
                    self._items.append(x)

            def _push_locked(self, x):
                self._items.append(x)
        """})
    assert run_rule(ctx, "lock-discipline") == []


# -- process rules ---------------------------------------------------------

def test_process_safe_state_fires_and_clean(tmp_path):
    # seeded violation: a registry two hops from the child entrypoint,
    # reached through a RELATIVE import (the resolver's hard case)
    ctx = make_ctx(tmp_path, {
        f"{PKG}/__init__.py": "",
        f"{PKG}/scheduler/__init__.py": "",
        f"{PKG}/scheduler/procrun.py": f"""\
            from .config import load
            from {PKG}.client import informer
            """,
        f"{PKG}/scheduler/config.py": """\
            _REGISTRY = {}

            def load():
                return _REGISTRY
            """,
        f"{PKG}/client/__init__.py": "",
        f"{PKG}/client/informer.py": """\
            import collections

            _CACHES = collections.defaultdict(list)
            LOOKUP = {"a": 1}
            _TUPLE = ()
            """,
    })
    found = run_rule(ctx, "process-safe-state")
    assert sorted(f.path for f in found) == [
        f"{PKG}/client/informer.py", f"{PKG}/scheduler/config.py"]
    assert all("process-local" in f.message for f in found)
    # populated literals and immutables are out of scope by design
    assert not any("LOOKUP" in f.message or "_TUPLE" in f.message
                   for f in found)

    # clean: same tree with the annotation claims in place; a module NOT
    # in the entrypoint closure stays invisible however mutable it is
    ctx = make_ctx(tmp_path / "ok", {
        f"{PKG}/__init__.py": "",
        f"{PKG}/scheduler/__init__.py": "",
        f"{PKG}/scheduler/procrun.py": "from .config import load\n",
        f"{PKG}/scheduler/config.py": """\
            # process-local: plugin registry, rebuilt per child on import
            _REGISTRY = {}

            def load():
                return _REGISTRY
            """,
        f"{PKG}/unreached.py": "_GLOBAL_STATE = {}\n",
    })
    assert run_rule(ctx, "process-safe-state") == []


def test_process_safe_state_real_tree_is_annotated():
    """The actual child-process import closure carries its claims."""
    import pathlib
    ctx = LintContext(pathlib.Path(__file__).resolve().parents[1])
    assert run_rule(ctx, "process-safe-state") == []


# -- engine mechanics ------------------------------------------------------

def test_line_suppression_and_file_suppression(tmp_path):
    ctx = make_ctx(tmp_path, {"a.py": """\
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url)  # ktpulint: disable=net-timeout
        """})
    assert run_rule(ctx, "net-timeout") == []

    ctx = make_ctx(tmp_path / "above", {"a.py": """\
        from urllib.request import urlopen

        def fetch(url):
            # ktpulint: disable=net-timeout
            return urlopen(url)
        """})
    assert run_rule(ctx, "net-timeout") == []

    ctx = make_ctx(tmp_path / "file", {"a.py": """\
        # ktpulint: disable-file=net-timeout
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url)

        def fetch2(url):
            return urlopen(url)
        """})
    assert run_rule(ctx, "net-timeout") == []


def test_baseline_round_trip(tmp_path):
    files = {"a.py": """\
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url)
        """}
    ctx = make_ctx(tmp_path, files)
    found = run_rule(ctx, "net-timeout")
    assert found
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, found)
    data = json.loads(bl_path.read_text())
    assert data["findings"][0]["rule"] == "net-timeout"

    ctx = make_ctx(tmp_path, files)
    assert run_lint(ctx, rule_names=["net-timeout"],
                    baseline=load_baseline(bl_path)) == []


def test_fingerprint_excludes_line():
    a = Finding("r", "p.py", 10, "msg")
    b = Finding("r", "p.py", 99, "msg")
    c = Finding("r", "p.py", 10, "other msg")
    assert a.fingerprint() == b.fingerprint()
    assert a.fingerprint() != c.fingerprint()


def test_annotation_scans_wrapped_comment_block(tmp_path):
    ctx = make_ctx(tmp_path, {f"{PKG}/ops/hot.py": """\
        import jax

        def build(core):
            # compile-cached: lazy module-level singleton; one cache
            # serves every call — second line of a wrapped comment
            return jax.jit(core)
        """})
    assert run_rule(ctx, "recompile-hazard") == []


def test_unknown_rule_name_raises(tmp_path):
    ctx = make_ctx(tmp_path, {"a.py": "X = 1\n"})
    with pytest.raises(KeyError):
        run_lint(ctx, rule_names=["no-such-rule"])


def test_checkpoint_versioned_fires_and_clean(tmp_path):
    import zlib
    digest_ab = zlib.crc32(b"a,b")

    ctx = make_ctx(tmp_path / "novers", {"a.py": """\
        CHECKPOINT_FIELDS = ("a", "b")
        """})
    found = run_rule(ctx, "checkpoint-versioned")
    assert len(found) == 1 and "version-gated" in found[0].message

    ctx = make_ctx(tmp_path / "noann", {"a.py": """\
        CHECKPOINT_FIELDS = ("a", "b")
        CHECKPOINT_SCHEMA_VERSION = 1
        """})
    found = run_rule(ctx, "checkpoint-versioned")
    assert len(found) == 1 and "schema-digest" in found[0].message

    # fields edited without a version bump: the digest no longer matches
    ctx = make_ctx(tmp_path / "stale", {"a.py": f"""\
        CHECKPOINT_FIELDS = ("a", "b", "c")
        # schema-digest: {digest_ab}@v1
        CHECKPOINT_SCHEMA_VERSION = 1
        """})
    found = run_rule(ctx, "checkpoint-versioned")
    assert len(found) == 1 and "bump" in found[0].message

    # version constant moved but the annotation wasn't refreshed
    ctx = make_ctx(tmp_path / "vmismatch", {"a.py": f"""\
        CHECKPOINT_FIELDS = ("a", "b")
        # schema-digest: {digest_ab}@v1
        CHECKPOINT_SCHEMA_VERSION = 2
        """})
    found = run_rule(ctx, "checkpoint-versioned")
    assert len(found) == 1 and "refresh" in found[0].message

    ctx = make_ctx(tmp_path / "ok", {"a.py": f"""\
        CHECKPOINT_FIELDS = ("a", "b")
        # schema-digest: {digest_ab}@v1
        CHECKPOINT_SCHEMA_VERSION = 1
        """})
    assert run_rule(ctx, "checkpoint-versioned") == []
