"""kubectl CLI tests (reference: kubectl command tests / test/cmd)."""

import io
import time

import pytest
import yaml

from kubernetes_tpu.api import meta
from kubernetes_tpu.cli.kubectl import run
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import DEPLOYMENTS, NODES, PODS
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    sched = new_scheduler(client, factory)
    mgr = ControllerManager(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    mgr.run()
    yield client
    mgr.stop()
    sched.stop()
    factory.stop()


def kubectl(client, *argv) -> tuple[int, str]:
    out = io.StringIO()
    rc = run(list(argv), client=client, out=out)
    return rc, out.getvalue()


class TestKubectl:
    def test_get_nodes_and_pods(self, cluster):
        client = cluster
        client.create(NODES, make_node("n1").build())
        client.create(PODS, make_pod("p1").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "p1")))
        rc, out = kubectl(client, "get", "nodes")
        assert rc == 0 and "n1" in out and "NAME" in out
        rc, out = kubectl(client, "get", "pods", "-o", "wide")
        assert rc == 0 and "p1" in out and "n1" in out

    def test_get_json_and_yaml(self, cluster):
        client = cluster
        client.create(PODS, make_pod("p1").build())
        rc, out = kubectl(client, "get", "po", "p1", "-o", "json")
        assert rc == 0
        import json
        assert json.loads(out)["metadata"]["name"] == "p1"
        rc, out = kubectl(client, "get", "po", "p1", "-o", "yaml")
        assert yaml.safe_load(out)["metadata"]["name"] == "p1"

    def test_create_apply_delete_manifest(self, cluster, tmp_path):
        client = cluster
        manifest = tmp_path / "dep.yaml"
        manifest.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Deployment",
            "metadata": {"name": "web"},
            "spec": {"replicas": 2,
                     "selector": {"matchLabels": {"app": "web"}},
                     "template": {"metadata": {"labels": {"app": "web"}},
                                  "spec": {"containers": [
                                      {"name": "c0", "image": "img:v1"}]}}},
        }))
        rc, out = kubectl(client, "create", "-f", str(manifest))
        assert rc == 0 and "created" in out
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 2)
        # apply an image change
        doc = yaml.safe_load(manifest.read_text())
        doc["spec"]["template"]["spec"]["containers"][0]["image"] = "img:v2"
        manifest.write_text(yaml.safe_dump(doc))
        rc, out = kubectl(client, "apply", "-f", str(manifest))
        assert rc == 0 and "configured" in out
        rc, out = kubectl(client, "delete", "deploy", "web")
        assert rc == 0

    def test_scale(self, cluster, tmp_path):
        client = cluster
        dep = meta.new_object("Deployment", "api", "default")
        dep["spec"] = {"replicas": 1,
                       "selector": {"matchLabels": {"app": "api"}},
                       "template": {"metadata": {"labels": {"app": "api"}},
                                    "spec": {"containers": [
                                        {"name": "c0", "image": "i"}]}}}
        client.create(DEPLOYMENTS, dep)
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 1)
        rc, out = kubectl(client, "scale", "deploy", "api", "--replicas", "3")
        assert rc == 0
        assert wait_for(lambda: len(client.list(PODS, "default")[0]) == 3)

    def test_cordon_drain(self, cluster):
        client = cluster
        client.create(NODES, make_node("n1").build())
        client.create(NODES, make_node("n2").build())
        client.create(PODS, make_pod("p1").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "p1")))
        victim = meta.pod_node_name(client.get(PODS, "default", "p1"))
        rc, out = kubectl(client, "drain", victim)
        assert rc == 0 and "evicted" in out
        node = client.get(NODES, "", victim)
        assert node["spec"].get("unschedulable") is True
        rc, _ = kubectl(client, "uncordon", victim)
        assert rc == 0
        assert not client.get(NODES, "", victim)["spec"].get("unschedulable")

    def test_top_nodes(self, cluster):
        client = cluster
        client.create(NODES, make_node("n1").capacity(cpu="2", mem="4Gi").build())
        client.create(PODS, make_pod("p1").req(cpu="500m", mem="1Gi").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "p1")))
        rc, out = kubectl(client, "top", "nodes")
        assert rc == 0 and "500m" in out and "25%" in out

    def test_describe_shows_events(self, cluster):
        client = cluster
        client.create(NODES, make_node("n1").build())
        client.create(PODS, make_pod("p1").build())
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "p1")))
        assert wait_for(lambda: kubectl(client, "describe", "po", "p1")[1]
                        .count("Scheduled") >= 1)

    def test_version_and_errors(self, cluster):
        client = cluster
        rc, out = kubectl(client, "version")
        assert rc == 0 and "kubectl-tpu" in out
        rc, out = kubectl(client, "get", "pods", "nope")
        assert rc == 1 and "Error" in out


class TestKubectlOverHTTP:
    def test_against_real_apiserver(self, tmp_path):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.client.http_client import HTTPClient
        store = kv.MemoryStore()
        server = APIServer(store).start()
        try:
            client = HTTPClient("127.0.0.1", server.port)
            client.create(NODES, make_node("n1").build())
            rc, out = kubectl(client, "get", "nodes")
            assert rc == 0 and "n1" in out
        finally:
            server.stop()


class TestKubectlBreadth:
    def _deploy(self, client, name="web", image="img:v1", replicas=2):
        dep = meta.new_object("Deployment", name, "default")
        dep["spec"] = {"replicas": replicas,
                       "selector": {"matchLabels": {"app": name}},
                       "template": {"metadata": {"labels": {"app": name}},
                                    "spec": {"containers": [
                                        {"name": "c0", "image": image}]}}}
        client.create("deployments", dep)
        return dep

    def test_label_annotate_patch(self, cluster):
        client = cluster
        client.create("nodes", make_node("kb-1").build())
        rc, _ = kubectl(client, "label", "node", "kb-1", "env=prod")
        assert rc == 0
        assert meta.labels(client.get("nodes", "", "kb-1"))["env"] == "prod"
        rc, _ = kubectl(client, "label", "node", "kb-1", "env-")
        assert rc == 0
        assert "env" not in meta.labels(client.get("nodes", "", "kb-1"))
        rc, _ = kubectl(client, "annotate", "node", "kb-1", "team=infra")
        assert rc == 0
        rc, _ = kubectl(client, "patch", "node", "kb-1",
                        "-p", '{"spec":{"unschedulable":true}}')
        assert rc == 0
        assert client.get("nodes", "", "kb-1")["spec"]["unschedulable"]

    def test_rollout_status_restart_undo(self, cluster):
        client = cluster
        client.create("nodes", make_node("kb-2").capacity(cpu="64").build())
        self._deploy(client, "roll", image="img:v1")
        assert wait_for(lambda: len([
            p for p in client.list("pods", "default")[0]
            if meta.deletion_timestamp(p) is None]) == 2)
        for p in client.list("pods", "default")[0]:
            client.update_status("pods", {**p, "status": {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}]}})
        rc, out = kubectl(client, "rollout", "status", "deployment", "roll")
        assert rc == 0 and "successfully rolled out" in out

        # template change -> second RS; undo -> back to v1 template
        def set_v2(o):
            o["spec"]["template"]["spec"]["containers"][0]["image"] = "img:v2"
            o["metadata"]["generation"] = 2
            return o
        client.guaranteed_update("deployments", "default", "roll", set_v2)
        assert wait_for(lambda: len([
            rs for rs in client.list("replicasets", "default")[0]]) >= 2)
        rc, out = kubectl(client, "rollout", "undo", "deployment", "roll")
        assert rc == 0 and "rolled back" in out
        img = client.get("deployments", "default", "roll")[
            "spec"]["template"]["spec"]["containers"][0]["image"]
        assert img == "img:v1"

        rc, out = kubectl(client, "rollout", "restart", "deployment", "roll")
        assert rc == 0
        ann = client.get("deployments", "default", "roll")[
            "spec"]["template"]["metadata"]["annotations"]
        assert "kubectl.kubernetes.io/restartedAt" in ann

    def test_wait_for_condition_and_delete(self, cluster):
        client = cluster
        pod = make_pod("waity").node("kb-3").build()
        client.create("pods", pod)
        rc, out = kubectl(client, "wait", "pod", "waity",
                          "--for", "condition=Ready", "--timeout", "0.4")
        assert rc == 1  # not ready yet
        client.update_status("pods", {**client.get("pods", "default", "waity"),
                                      "status": {"phase": "Running",
                                                 "conditions": [
                                                     {"type": "Ready",
                                                      "status": "True"}]}})
        rc, out = kubectl(client, "wait", "pod", "waity",
                          "--for", "condition=Ready", "--timeout", "5")
        assert rc == 0
        client.delete("pods", "default", "waity")
        rc, out = kubectl(client, "wait", "pod", "waity",
                          "--for", "delete", "--timeout", "5")
        assert rc == 0


class TestEditDebug:
    def test_edit_applies_changes(self, cluster, tmp_path):
        """EDITOR is a script that rewrites a label; the PUT must land."""
        client = cluster
        cm = meta.new_object("ConfigMap", "editable", "default")
        cm["data"] = {"k": "v"}
        client.create("configmaps", cm)
        editor = tmp_path / "ed.sh"
        editor.write_text("#!/bin/sh\n"
                          "python3 - \"$1\" <<'PY'\n"
                          "import sys, yaml\n"
                          "doc = yaml.safe_load(open(sys.argv[1]))\n"
                          "doc['metadata'].setdefault('labels', {})"
                          "['edited'] = 'yes'\n"
                          "yaml.safe_dump(doc, open(sys.argv[1], 'w'))\n"
                          "PY\n")
        editor.chmod(0o755)
        import io

        from kubernetes_tpu.cli.kubectl import Kubectl
        out = io.StringIO()
        k = Kubectl(client, out)
        assert k.edit("cm", "editable", "default",
                      editor=str(editor)) == 0, out.getvalue()
        assert "edited" in out.getvalue()
        assert meta.labels(client.get("configmaps", "default",
                                      "editable"))["edited"] == "yes"

    def test_edit_no_change_is_noop(self, cluster, tmp_path):
        client = cluster
        cm = meta.new_object("ConfigMap", "steady", "default")
        client.create("configmaps", cm)
        rv_before = meta.resource_version(
            client.get("configmaps", "default", "steady"))
        import io

        from kubernetes_tpu.cli.kubectl import Kubectl
        out = io.StringIO()
        k = Kubectl(client, out)
        assert k.edit("cm", "steady", "default", editor="true") == 0
        assert "unchanged" in out.getvalue()
        assert meta.resource_version(
            client.get("configmaps", "default", "steady")) == rv_before

    def test_debug_creates_pod_copy(self, cluster):
        client = cluster
        client.create(NODES, make_node("dbg-node").build())
        client.create(PODS, make_pod("prod-pod").req(cpu="100m").build())
        rc, out = kubectl(client, "debug", "prod-pod",
                          "--image", "tools:v1")
        assert rc == 0, out
        copy = client.get(PODS, "default", "prod-pod-debug")
        names = [c["name"] for c in copy["spec"]["containers"]]
        assert "debugger" in names
        dbg = next(c for c in copy["spec"]["containers"]
                   if c["name"] == "debugger")
        assert dbg["image"] == "tools:v1"
        assert meta.labels(copy)["debug.kubernetes.io/source"] == \
            "prod-pod"
        # the copy reschedules on its own
        assert wait_for(lambda: meta.pod_node_name(
            client.get(PODS, "default", "prod-pod-debug")))


class TestGetSelectors:
    def test_label_selector(self, cluster):
        client = cluster
        client.create(PODS, make_pod("web-1").labels(app="web").build())
        client.create(PODS, make_pod("web-2").labels(app="web",
                                                     tier="x").build())
        client.create(PODS, make_pod("db-1").labels(app="db").build())
        rc, out = kubectl(client, "get", "pods", "-l", "app=web")
        assert rc == 0
        assert "web-1" in out and "web-2" in out and "db-1" not in out
        rc, out = kubectl(client, "get", "pods", "-l", "app=web,tier")
        assert "web-2" in out and "web-1" not in out
        rc, out = kubectl(client, "get", "pods", "-l", "app!=web")
        assert "db-1" in out and "web-1" not in out

    def test_all_namespaces(self, cluster):
        client = cluster
        ns = meta.new_object("Namespace", "other", None)
        client.create("namespaces", ns)
        client.create(PODS, make_pod("here").build())
        client.create(PODS, make_pod("there", namespace="other").build())
        rc, out = kubectl(client, "get", "pods", "-A")
        assert rc == 0
        assert "here" in out and "there" in out
        rc, out = kubectl(client, "get", "pods")
        assert "here" in out and "there" not in out


class TestSelectorParsing:
    def test_set_expressions_and_guards(self, cluster):
        client = cluster
        client.create(PODS, make_pod("in-a").labels(env="a").build())
        client.create(PODS, make_pod("in-b").labels(env="b").build())
        client.create(PODS, make_pod("in-c").labels(env="c").build())
        rc, out = kubectl(client, "get", "pods", "-l", "env in (a, b)")
        assert rc == 0
        assert "in-a" in out and "in-b" in out and "in-c" not in out
        rc, out = kubectl(client, "get", "pods", "-l", "env notin (a)")
        assert "in-a" not in out and "in-b" in out
        # name + -l is a usage error, not a silent filter
        rc, out = kubectl(client, "get", "pods", "in-a", "-l", "env=a")
        assert rc == 1 and "cannot" in out
        rc, out = kubectl(client, "get", "pods", "in-a", "-A")
        assert rc == 1

    def test_all_namespaces_column(self, cluster):
        client = cluster
        ns = meta.new_object("Namespace", "col-ns", None)
        client.create("namespaces", ns)
        client.create(PODS, make_pod("same-name").build())
        client.create(PODS, make_pod("same-name",
                                     namespace="col-ns").build())
        rc, out = kubectl(client, "get", "pods", "-A")
        assert rc == 0
        assert "NAMESPACE" in out.splitlines()[0]
        rows = [ln for ln in out.splitlines() if "same-name" in ln]
        assert len(rows) == 2
        assert any(ln.startswith("col-ns") for ln in rows)
        assert any(ln.startswith("default") for ln in rows)
