"""kubectl breadth: api-resources / api-versions / explain / expose /
autoscale / set / cp / proxy — every command round-trips against the
real HTTP apiserver (and the kubelet tunnel where the verb needs it).

Reference commands being matched: staging/src/k8s.io/kubectl/pkg/cmd/
{apiresources,explain,expose,autoscale,set,cp,proxy}.
"""

import io
import json
import os
import threading
import urllib.request

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.cli.kubectl import Kubectl
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import PODS
from kubernetes_tpu.client.http_client import HTTPClient
from kubernetes_tpu.kubelet import KubeletServer, start_hollow_nodes
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import wait_for


@pytest.fixture(scope="module")
def cluster():
    store = kv.MemoryStore(history=100_000)
    server = APIServer(store).start()
    local = LocalClient(store)
    factory = SharedInformerFactory(local)
    factory.start()
    factory.wait_for_cache_sync()
    kubelet_server = KubeletServer().start()
    kubelets = start_hollow_nodes(local, factory, 1,
                                  kubelet_server=kubelet_server)
    http = HTTPClient.from_url(server.url)
    yield http, local
    for k in kubelets:
        k.stop()
    kubelet_server.stop()
    factory.stop()
    server.stop()
    local.close()


def kubectl(http) -> tuple[Kubectl, io.StringIO]:
    out = io.StringIO()
    return Kubectl(http, out), out


def run_pod(local, name):
    pod = meta.new_object("Pod", name, "default")
    pod["spec"] = {"nodeName": "hollow-0",
                   "containers": [{"name": "c0", "image": "img"}]}
    local.create(PODS, pod)
    assert wait_for(lambda: (local.get(PODS, "default", name)
                             .get("status") or {}).get("phase") == "Running")
    return pod


class TestDiscoveryCommands:
    def test_api_versions(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.api_versions() == 0
        lines = out.getvalue().splitlines()
        assert "v1" in lines
        assert any(l.startswith("apps/") for l in lines)
        assert lines == sorted(lines)

    def test_api_resources(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.api_resources() == 0
        text = out.getvalue()
        assert "pods" in text and "deployments" in text
        assert "NAMESPACED" in text
        # nodes are cluster-scoped; a namespaced=true filter drops them
        k2, out2 = kubectl(http)
        assert k2.api_resources(namespaced=True) == 0
        rows = [l.split()[0] for l in out2.getvalue().splitlines()[1:]]
        assert "pods" in rows and "nodes" not in rows

    def test_explain_pod(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.explain("pods") == 0
        text = out.getvalue()
        assert "KIND:     Pod" in text
        assert "spec" in text and "status" in text

    def test_explain_field_path(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.explain("pod.spec.containers.resources") == 0
        text = out.getvalue()
        assert "requests" in text and "limits" in text
        # array hop: containers is []Container and still explains
        k2, out2 = kubectl(http)
        assert k2.explain("pods.spec.containers") == 0
        assert "image" in out2.getvalue()

    def test_explain_unknown_field(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.explain("pods.spec.nosuchfield") == 1
        assert "does not exist" in out.getvalue()


class TestExposeAutoscaleSet:
    def _mkdeploy(self, http, name):
        dep = meta.new_object("Deployment", name, "default")
        dep["spec"] = {
            "replicas": 1,
            "selector": {"matchLabels": {"app": name}},
            "template": {"metadata": {"labels": {"app": name}},
                         "spec": {"containers": [
                             {"name": "web", "image": "img:1"}]}},
        }
        http.create("deployments", dep)
        return dep

    def test_expose_deployment(self, cluster):
        http, _ = cluster
        self._mkdeploy(http, "web-exp")
        k, out = kubectl(http)
        rc = k.expose("deployment", "web-exp", "default", port=80,
                      target_port=8080)
        assert rc == 0, out.getvalue()
        svc = http.get("services", "default", "web-exp")
        assert svc["spec"]["selector"] == {"app": "web-exp"}
        assert svc["spec"]["ports"][0] == {
            "port": 80, "protocol": "TCP", "targetPort": 8080}

    def test_expose_pod_by_labels(self, cluster):
        http, local = cluster
        pod = meta.new_object("Pod", "exp-pod", "default")
        pod["metadata"]["labels"] = {"run": "exp-pod"}
        pod["spec"] = {"containers": [{"name": "c", "image": "i"}]}
        http.create(PODS, pod)
        k, out = kubectl(http)
        assert k.expose("pod", "exp-pod", "default", port=9,
                        svc_name="exp-pod-svc") == 0
        svc = http.get("services", "default", "exp-pod-svc")
        assert svc["spec"]["selector"] == {"run": "exp-pod"}

    def test_expose_no_selector_fails(self, cluster):
        http, _ = cluster
        cm = meta.new_object("ConfigMap", "exp-cm", "default")
        http.create("configmaps", cm)
        k, out = kubectl(http)
        assert k.expose("configmaps", "exp-cm", "default", port=1) == 1
        assert "selector" in out.getvalue()

    def test_autoscale(self, cluster):
        http, _ = cluster
        self._mkdeploy(http, "web-hpa")
        k, out = kubectl(http)
        rc = k.autoscale("deployment", "web-hpa", "default",
                         min_replicas=2, max_replicas=7, cpu_percent=60)
        assert rc == 0, out.getvalue()
        hpa = http.get("horizontalpodautoscalers", "default", "web-hpa")
        assert hpa["spec"]["minReplicas"] == 2
        assert hpa["spec"]["maxReplicas"] == 7
        assert hpa["spec"]["scaleTargetRef"]["name"] == "web-hpa"
        mt = hpa["spec"]["metrics"][0]["resource"]
        assert mt["target"]["averageUtilization"] == 60

    def test_set_image(self, cluster):
        http, _ = cluster
        self._mkdeploy(http, "web-set")
        k, out = kubectl(http)
        assert k.set_cmd("image", "deployment", "web-set", "default",
                         ["web=img:2"]) == 0
        dep = http.get("deployments", "default", "web-set")
        assert dep["spec"]["template"]["spec"]["containers"][0][
            "image"] == "img:2"
        # unknown container name is an error, not a silent no-op
        k2, out2 = kubectl(http)
        assert k2.set_cmd("image", "deployment", "web-set", "default",
                          ["nope=img:3"]) == 1
        assert "not found" in out2.getvalue()

    def test_set_env(self, cluster):
        http, _ = cluster
        self._mkdeploy(http, "web-env")
        k, _ = kubectl(http)
        assert k.set_cmd("env", "deployment", "web-env", "default",
                         ["MODE=fast", "DEBUG=1"]) == 0
        # re-set overwrites, not duplicates
        assert k.set_cmd("env", "deployment", "web-env", "default",
                         ["MODE=slow"]) == 0
        dep = http.get("deployments", "default", "web-env")
        env = dep["spec"]["template"]["spec"]["containers"][0]["env"]
        assert {"name": "MODE", "value": "slow"} in env
        assert {"name": "DEBUG", "value": "1"} in env
        assert sum(1 for e in env if e["name"] == "MODE") == 1


class TestCp:
    def test_round_trip(self, cluster, tmp_path):
        http, local = cluster
        run_pod(local, "cp-pod")
        src = tmp_path / "payload.bin"
        data = bytes(range(256)) * 17  # binary-safe
        src.write_bytes(data)
        k, out = kubectl(http)
        rc = k.cp(str(src), "cp-pod:/data/payload.bin", "default")
        assert rc == 0, out.getvalue()
        # in-container visibility through exec
        k2, out2 = kubectl(http)
        assert k2.exec("cp-pod", "default", ["ls", "/data"]) == 0
        assert "/data/payload.bin" in out2.getvalue()
        # download back and compare
        dst = tmp_path / "back.bin"
        k3, out3 = kubectl(http)
        rc = k3.cp("cp-pod:/data/payload.bin", str(dst), "default")
        assert rc == 0, out3.getvalue()
        assert dst.read_bytes() == data

    def test_large_file_crosses_frame_cap(self, cluster, tmp_path):
        """Payloads larger than the 4 MiB stream frame cap must chunk
        (streams.MAX_FRAME); a single jumbo frame kills the stream."""
        http, local = cluster
        run_pod(local, "cp-big")
        src = tmp_path / "big.bin"
        data = os.urandom(5 << 20)  # > MAX_FRAME
        src.write_bytes(data)
        k, out = kubectl(http)
        assert k.cp(str(src), "cp-big:/big.bin", "default") == 0, \
            out.getvalue()
        dst = tmp_path / "big-back.bin"
        k2, out2 = kubectl(http)
        assert k2.cp("cp-big:/big.bin", str(dst), "default") == 0, \
            out2.getvalue()
        assert dst.read_bytes() == data

    def test_trailing_slash_dest_keeps_source_name(self, cluster,
                                                   tmp_path):
        http, local = cluster
        run_pod(local, "cp-slash")
        src = tmp_path / "named.txt"
        src.write_bytes(b"hi")
        k, out = kubectl(http)
        assert k.cp(str(src), "cp-slash:/tmp/", "default") == 0
        k2, out2 = kubectl(http)
        assert k2.exec("cp-slash", "default", ["cat", "/tmp/named.txt"]) \
            == 0
        assert out2.getvalue() == "hi"

    def test_download_missing_file(self, cluster, tmp_path):
        http, local = cluster
        run_pod(local, "cp-miss")
        k, out = kubectl(http)
        rc = k.cp("cp-miss:/no/such", str(tmp_path / "x"), "default")
        assert rc == 1
        assert "No such file" in out.getvalue()

    def test_both_local_rejected(self, cluster, tmp_path):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.cp(str(tmp_path / "a"), str(tmp_path / "b"),
                    "default") == 1


class TestJsonPath:
    def test_jsonpath_outputs(self, cluster):
        """Runs in its own namespace — the module-scoped cluster holds
        other tests' configmaps."""
        from kubernetes_tpu.cli.kubectl import run
        http, _ = cluster
        try:
            http.create("namespaces",
                        meta.new_object("Namespace", "jp", ""))
        except kv.AlreadyExistsError:
            pass
        for i in range(3):
            cm = meta.new_object("ConfigMap", f"jp-{i}", "jp")
            cm["data"] = {"n": str(i)}
            http.create("configmaps", cm)
        out = io.StringIO()
        assert run(["-n", "jp", "get", "configmaps", "-o",
                    "jsonpath={.items[*].metadata.name}"],
                   client=http, out=out) == 0
        assert out.getvalue().strip() == "jp-0 jp-1 jp-2"
        # range/end with literal newline
        out = io.StringIO()
        assert run(["-n", "jp", "get", "configmaps", "-o",
                    'jsonpath={range .items[*]}{.metadata.name}'
                    '{"\\n"}{end}'], client=http, out=out) == 0
        lines = [l for l in out.getvalue().splitlines() if l]
        assert lines == ["jp-0", "jp-1", "jp-2"]
        # single object + index
        out = io.StringIO()
        assert run(["-n", "jp", "get", "configmaps", "jp-1", "-o",
                    "jsonpath={.data.n}"], client=http, out=out) == 0
        assert out.getvalue().strip() == "1"
        # malformed template errors (never silently empty)
        out = io.StringIO()
        assert run(["-n", "jp", "get", "configmaps", "-o",
                    "jsonpath={range .items[*]}{.x}"],
                   client=http, out=out) == 1
        assert "range" in out.getvalue()
        # unknown -o rejected
        out = io.StringIO()
        assert run(["-n", "jp", "get", "configmaps", "-o", "banana"],
                   client=http, out=out) == 1


class TestDeleteVariants:
    def test_delete_by_file_and_selector_and_o_name(self, cluster,
                                                    tmp_path):
        from kubernetes_tpu.cli.kubectl import run
        http, _ = cluster
        mf = tmp_path / "objs.yaml"
        mf.write_text(
            "apiVersion: v1\nkind: ConfigMap\n"
            "metadata: {name: del-a, labels: {grp: del}}\n---\n"
            "apiVersion: v1\nkind: ConfigMap\n"
            "metadata: {name: del-b, labels: {grp: del}}\n")
        out = io.StringIO()
        assert run(["apply", "-f", str(mf)], client=http, out=out) == 0
        # -o name output
        out = io.StringIO()
        assert run(["get", "configmaps", "-o", "name"],
                   client=http, out=out) == 0
        assert "configmaps/del-a" in out.getvalue()
        # delete -l
        out = io.StringIO()
        assert run(["delete", "configmaps", "-l", "grp=del"],
                   client=http, out=out) == 0
        assert "del-a" in out.getvalue() and "del-b" in out.getvalue()
        with pytest.raises(kv.NotFoundError):
            http.get("configmaps", "default", "del-a")
        # delete -f round trip
        out = io.StringIO()
        assert run(["apply", "-f", str(mf)], client=http, out=out) == 0
        out = io.StringIO()
        assert run(["delete", "-f", str(mf)], client=http, out=out) == 0
        with pytest.raises(kv.NotFoundError):
            http.get("configmaps", "default", "del-b")
        # bad invocation
        out = io.StringIO()
        assert run(["delete"], client=http, out=out) == 1


class TestTopPods:
    def test_top_pods_lists_requests(self, cluster):
        http, local = cluster
        pod = meta.new_object("Pod", "top-a", "default")
        pod["spec"] = {"containers": [{"name": "c", "resources": {
            "requests": {"cpu": "250m", "memory": "256Mi"}}}]}
        http.create(PODS, pod)
        from kubernetes_tpu.cli.kubectl import run
        out = io.StringIO()
        assert run(["top", "pods"], client=http, out=out) == 0
        text = out.getvalue()
        assert "top-a" in text and "250m" in text and "256Mi" in text


class TestCreateGenerators:
    def test_create_deployment(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        rc = k.create_generated(
            "deployment", ["genweb", "--image=img:3", "--replicas=2"],
            "default")
        assert rc == 0, out.getvalue()
        dep = http.get("deployments", "default", "genweb")
        assert dep["spec"]["replicas"] == 2
        assert dep["spec"]["template"]["spec"]["containers"][0][
            "image"] == "img:3"
        assert dep["spec"]["selector"]["matchLabels"] == {"app": "genweb"}

    def test_create_configmap_and_secret(self, cluster):
        http, _ = cluster
        k, _ = kubectl(http)
        assert k.create_generated(
            "configmap", ["gencm", "--from-literal=a=1",
                          "--from-literal=b=2"], "default") == 0
        cm = http.get("configmaps", "default", "gencm")
        assert cm["data"] == {"a": "1", "b": "2"}
        assert k.create_generated(
            "secret", ["generic", "gensec", "--from-literal=pw=x"],
            "default") == 0
        import base64
        sec = http.get("secrets", "default", "gensec")
        assert base64.b64decode(sec["data"]["pw"]).decode() == "x"

    def test_create_namespace_and_service(self, cluster):
        http, _ = cluster
        k, _ = kubectl(http)
        assert k.create_generated("namespace", ["genns"], "default") == 0
        assert http.get("namespaces", "", "genns")
        assert k.create_generated(
            "service", ["clusterip", "gensvc", "--tcp=80:8080"],
            "default") == 0
        svc = http.get("services", "default", "gensvc")
        assert svc["spec"]["ports"][0] == {
            "port": 80, "protocol": "TCP", "targetPort": 8080}

    def test_create_job_with_command(self, cluster):
        """Canonical CLI form: create job NAME --image=I -- CMD ARGS
        (the `--` split happens in run())."""
        from kubernetes_tpu.cli.kubectl import run
        http, _ = cluster
        out = io.StringIO()
        rc = run(["create", "job", "genjob", "--image=busybox",
                  "--", "echo", "hi"], client=http, out=out)
        assert rc == 0, out.getvalue()
        job = http.get("jobs", "default", "genjob")
        c = job["spec"]["template"]["spec"]["containers"][0]
        assert c["command"] == ["echo", "hi"]

    def test_trailing_namespace_flag_honored(self, cluster):
        """kubectl's canonical `-n NS` after the generator args must not
        be swallowed by REMAINDER parsing."""
        from kubernetes_tpu.cli.kubectl import run
        http, _ = cluster
        http.create("namespaces", meta.new_object("Namespace",
                                                  "genprod", ""))
        out = io.StringIO()
        rc = run(["create", "configmap", "nscm", "--from-literal=a=1",
                  "-n", "genprod"], client=http, out=out)
        assert rc == 0, out.getvalue()
        assert http.get("configmaps", "genprod", "nscm")

    def test_bad_flags_are_errors_not_silent(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        # typo'd flag
        assert k.create_generated(
            "deployment", ["d1", "--image=x", "--replica=3"],
            "default") == 1
        assert "unknown flag" in out.getvalue()
        # non-integer replicas
        k2, out2 = kubectl(http)
        assert k2.create_generated(
            "deployment", ["d2", "--image=x", "--replicas=two"],
            "default") == 1
        assert "integer" in out2.getvalue()
        # stray positional
        k3, out3 = kubectl(http)
        assert k3.create_generated(
            "configmap", ["cmx", "a=1"], "default") == 1
        assert "unexpected argument" in out3.getvalue()
        # portless service
        k4, out4 = kubectl(http)
        assert k4.create_generated(
            "service", ["clusterip", "s1"], "default") == 1
        assert "--tcp" in out4.getvalue()
        # nothing was created by any of the failed commands
        with pytest.raises(kv.NotFoundError):
            http.get("deployments", "default", "d1")

    def test_unknown_generator_errors(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        assert k.create_generated("cronjob", ["x"], "default") == 1
        assert "unsupported" in out.getvalue()


class TestKustomize:
    def _overlay(self, tmp_path):
        """base (deployment+service) + overlay (prefix, namespace,
        labels, image rewrite, replica patch) — the canonical kustomize
        layout."""
        base = tmp_path / "base"
        base.mkdir()
        (base / "app.yaml").write_text("""\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: web
spec:
  replicas: 1
  selector:
    matchLabels: {app: web}
  template:
    metadata:
      labels: {app: web}
    spec:
      containers:
      - name: web
        image: registry/web:1.0
---
apiVersion: v1
kind: Service
metadata:
  name: web
spec:
  selector: {app: web}
  ports:
  - port: 80
""")
        (base / "kustomization.yaml").write_text(
            "resources:\n- app.yaml\n")
        overlay = tmp_path / "prod"
        overlay.mkdir()
        (overlay / "replicas.yaml").write_text("""\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: web
spec:
  replicas: 5
""")
        (overlay / "kustomization.yaml").write_text("""\
resources:
- ../base
namePrefix: prod-
namespace: production
commonLabels: {env: prod}
commonAnnotations: {team: core}
images:
- name: registry/web
  newTag: "2.0"
patchesStrategicMerge:
- replicas.yaml
""")
        return overlay

    def test_build_applies_all_transforms(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import build
        objs = build(str(self._overlay(tmp_path)))
        dep = next(o for o in objs if o["kind"] == "Deployment")
        svc = next(o for o in objs if o["kind"] == "Service")
        assert dep["metadata"]["name"] == "prod-web"
        assert dep["metadata"]["namespace"] == "production"
        assert dep["spec"]["replicas"] == 5  # patch applied
        assert dep["spec"]["template"]["spec"]["containers"][0][
            "image"] == "registry/web:2.0"
        assert dep["metadata"]["labels"]["env"] == "prod"
        assert dep["spec"]["selector"]["matchLabels"]["env"] == "prod"
        assert dep["spec"]["template"]["metadata"]["labels"][
            "env"] == "prod"
        assert svc["spec"]["selector"]["env"] == "prod"
        assert dep["metadata"]["annotations"]["team"] == "core"

    def test_apply_k_round_trips_server(self, cluster, tmp_path):
        http, _ = cluster
        ns = meta.new_object("Namespace", "production", "")
        try:
            http.create("namespaces", ns)
        except kv.AlreadyExistsError:
            pass
        k, out = kubectl(http)
        rc = k.apply_kustomize(str(self._overlay(tmp_path)), "default")
        assert rc == 0, out.getvalue()
        dep = http.get("deployments", "production", "prod-web")
        assert dep["spec"]["replicas"] == 5
        assert dep["spec"]["template"]["spec"]["containers"][0][
            "image"] == "registry/web:2.0"

    def test_unknown_field_rejected(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import (
            KustomizeError, build,
        )
        d = tmp_path / "bad"
        d.mkdir()
        (d / "kustomization.yaml").write_text(
            "resources: []\nconfigMapGenerator: []\n")
        with pytest.raises(KustomizeError):
            build(str(d))

    def test_missing_patch_file_is_clean_error(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import KustomizeError, build
        d = tmp_path / "mp"
        d.mkdir()
        (d / "kustomization.yaml").write_text(
            "resources: []\npatchesStrategicMerge:\n- typo.yaml\n")
        with pytest.raises(KustomizeError):
            build(str(d))

    def test_registry_port_image_rewrite(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import build
        d = tmp_path / "img"
        d.mkdir()
        (d / "p.yaml").write_text(
            "apiVersion: v1\nkind: Pod\nmetadata: {name: p}\n"
            "spec:\n  containers:\n  - name: c\n"
            "    image: myreg.io:5000/web:1.0\n")
        (d / "kustomization.yaml").write_text(
            "resources: [p.yaml]\nimages:\n"
            "- name: myreg.io:5000/web\n  newTag: \"2.0\"\n")
        pod = build(str(d))[0]
        assert pod["spec"]["containers"][0]["image"] \
            == "myreg.io:5000/web:2.0"

    def test_cycle_detected(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import KustomizeError, build
        a = tmp_path / "a"; b = tmp_path / "b"
        a.mkdir(); b.mkdir()
        (a / "kustomization.yaml").write_text("resources: [../b]\n")
        (b / "kustomization.yaml").write_text("resources: [../a]\n")
        with pytest.raises(KustomizeError, match="cycle"):
            build(str(a))

    def test_unmatched_patch_rejected(self, cluster, tmp_path):
        from kubernetes_tpu.cli.kustomize import (
            KustomizeError, build,
        )
        d = tmp_path / "orphan"
        d.mkdir()
        (d / "p.yaml").write_text(
            "kind: Deployment\nmetadata: {name: nope}\n")
        (d / "kustomization.yaml").write_text(
            "resources: []\npatchesStrategicMerge:\n- p.yaml\n")
        with pytest.raises(KustomizeError):
            build(str(d))


class TestProxy:
    def test_forwards_with_credentials(self, cluster):
        http, _ = cluster
        k, out = kubectl(http)
        ready = threading.Event()
        bound = []

        def go():
            k.proxy(port=0, ready=lambda p: (bound.append(p),
                                             ready.set()), once=True)

        t = threading.Thread(target=go, daemon=True)
        t.start()
        assert ready.wait(5)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{bound[0]}/api/v1/namespaces/default/"
                f"pods", timeout=5) as resp:
            body = json.load(resp)
        assert body.get("kind") in ("PodList", "List")
        t.join(timeout=5)

    def test_streams_watch_events_live(self, cluster):
        """A watch through the proxy must deliver events as they
        happen, not after the upstream closes (chunked pass-through)."""
        http, local = cluster
        k, _ = kubectl(http)
        ready = threading.Event()
        bound = []

        def go():
            k.proxy(port=0, ready=lambda p: (bound.append(p),
                                             ready.set()), once=True)

        t = threading.Thread(target=go, daemon=True)
        t.start()
        assert ready.wait(5)
        got = threading.Event()
        lines = []

        def watch():
            req = urllib.request.urlopen(
                f"http://127.0.0.1:{bound[0]}/api/v1/namespaces/default/"
                f"configmaps?watch=true", timeout=10)
            line = req.readline()  # HTTPResponse dechunks
            if line.strip():
                lines.append(json.loads(line))
                got.set()
            req.close()

        wt = threading.Thread(target=watch, daemon=True)
        wt.start()
        import time
        time.sleep(0.3)  # let the watch register upstream
        cm = meta.new_object("ConfigMap", "proxy-live", "default")
        http.create("configmaps", cm)
        assert got.wait(5), "watch event did not stream through proxy"
        assert lines[0]["object"]["metadata"]["name"] == "proxy-live"
        wt.join(timeout=5)
        t.join(timeout=5)
