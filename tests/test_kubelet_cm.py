"""Container-manager layer: cpu/memory/device managers + topology manager.

Behavioral contracts from pkg/kubelet/cm/{cpumanager,memorymanager,
devicemanager,topologymanager}.
"""

import pytest

from kubernetes_tpu.kubelet.cm import (
    POLICY_STATIC, TOPOLOGY_RESTRICTED, TOPOLOGY_SINGLE_NUMA, AdmissionError,
    ContainerManager, CPUManager, DeviceManager, DevicePlugin, MemoryManager,
    TopologyHint, TopologyManager, merge_hints,
)


def guaranteed_pod(uid, cpu="2", memory="2Gi", extra_requests=None):
    req = {"cpu": cpu, "memory": memory, **(extra_requests or {})}
    return {"metadata": {"uid": uid, "name": uid},
            "spec": {"containers": [{"name": "c0", "image": "img",
                                     "resources": {"requests": dict(req),
                                                   "limits": dict(req)}}]}}


def burstable_pod(uid, cpu="2"):
    return {"metadata": {"uid": uid, "name": uid},
            "spec": {"containers": [{"name": "c0", "image": "img",
                                     "resources": {"requests": {"cpu": cpu}}}]}}


class TestCPUManager:
    def test_exclusive_cores_for_guaranteed_integer(self):
        m = CPUManager(num_cpus=8, reserved=1)
        cores = m.allocate(guaranteed_pod("p1", cpu="2"))
        assert len(cores) == 2 and all(c >= 1 for c in cores)
        # second pod gets disjoint cores
        cores2 = m.allocate(guaranteed_pod("p2", cpu="3"))
        assert not set(cores) & set(cores2)
        m.release("p1")
        assert set(m.allocate(guaranteed_pod("p3", cpu="2"))) == set(cores)

    def test_non_integer_or_burstable_stay_shared(self):
        m = CPUManager(num_cpus=8)
        assert m.allocate(guaranteed_pod("p1", cpu="1500m")) == []
        assert m.allocate(burstable_pod("p2")) == []

    def test_exhaustion_raises(self):
        m = CPUManager(num_cpus=4, reserved=1)
        m.allocate(guaranteed_pod("p1", cpu="3"))
        with pytest.raises(AdmissionError):
            m.allocate(guaranteed_pod("p2", cpu="1"))

    def test_checkpoint_restore(self, tmp_path):
        from kubernetes_tpu.kubelet.checkpoint import CheckpointManager
        ck = CheckpointManager(str(tmp_path))
        m = CPUManager(num_cpus=8, checkpoints=ck)
        cores = m.allocate(guaranteed_pod("p1", cpu="2"))
        m2 = CPUManager(num_cpus=8, checkpoints=ck)
        assert m2.assignments["p1"] == cores


class TestMemoryManager:
    def test_numa_bank_allocation(self):
        m = MemoryManager(numa_banks=[4 << 30, 4 << 30])
        alloc = m.allocate(guaranteed_pod("p1", memory="3Gi"),
                           TopologyHint(0b01, True))
        assert alloc == {0: 3 << 30}
        # spills across banks when one can't hold it
        alloc2 = m.allocate(guaranteed_pod("p2", memory="4Gi"))
        assert sum(alloc2.values()) == 4 << 30 and len(alloc2) == 2

    def test_exhaustion(self):
        m = MemoryManager(numa_banks=[2 << 30])
        with pytest.raises(AdmissionError):
            m.allocate(guaranteed_pod("p1", memory="3Gi"))


class TestDeviceManager:
    def _mgr(self):
        m = DeviceManager()
        m.register(DevicePlugin("google.com/tpu",
                                {"tpu0": 0, "tpu1": 0, "tpu2": 1, "tpu3": 1}))
        return m

    def test_allocatable_and_allocate(self):
        m = self._mgr()
        assert m.allocatable() == {"google.com/tpu": 4}
        pod = guaranteed_pod("p1", extra_requests={"google.com/tpu": "2"})
        alloc = m.allocate(pod, TopologyHint(0b10, True))
        assert alloc["google.com/tpu"] == ["tpu2", "tpu3"]  # NUMA-1 first
        pod2 = guaranteed_pod("p2", extra_requests={"google.com/tpu": "3"})
        with pytest.raises(AdmissionError):
            m.allocate(pod2)

    def test_hints_prefer_single_numa(self):
        m = self._mgr()
        pod = guaranteed_pod("p1", extra_requests={"google.com/tpu": "2"})
        hints = m.hints(pod)
        assert TopologyHint(0b01, True) in hints
        assert TopologyHint(0b10, True) in hints
        # 3 devices cannot come from one NUMA node: only the wide fallback
        pod3 = guaranteed_pod("p3", extra_requests={"google.com/tpu": "3"})
        hints3 = m.hints(pod3)
        assert hints3 == [TopologyHint(0b11, False)]


class TestTopologyManager:
    def test_merge_prefers_narrow_preferred(self):
        merged = merge_hints([[TopologyHint(0b01, True),
                               TopologyHint(0b11, False)],
                              [TopologyHint(0b01, True)]], 2)
        assert merged == TopologyHint(0b01, True)

    def test_restricted_rejects_unpreferred(self):
        tm = TopologyManager(TOPOLOGY_RESTRICTED, num_numa=2)
        with pytest.raises(AdmissionError):
            tm.admit("p1", [[TopologyHint(0b11, False)]])

    def test_single_numa_rejects_wide(self):
        tm = TopologyManager(TOPOLOGY_SINGLE_NUMA, num_numa=2)
        with pytest.raises(AdmissionError):
            tm.admit("p1", [[TopologyHint(0b11, True)]])
        assert tm.admit("p2", [[TopologyHint(0b10, True)]]).numa_mask == 0b10


class TestContainerManager:
    def test_admit_and_release_roundtrip(self, tmp_path):
        cm = ContainerManager(num_cpus=8, memory_bytes=8 << 30, num_numa=2,
                              topology_policy=TOPOLOGY_SINGLE_NUMA,
                              checkpoint_dir=str(tmp_path))
        cm.devices.register(DevicePlugin("google.com/tpu",
                                         {"tpu0": 0, "tpu1": 1}))
        pod = guaranteed_pod("p1", cpu="2", memory="2Gi",
                             extra_requests={"google.com/tpu": "1"})
        cm.admit_pod(pod)
        assert cm.cpu.assignments["p1"]
        assert cm.devices.allocations["p1"]["google.com/tpu"]
        # everything the pod got sits on ONE numa node
        numa = cm.topology.pod_hints["p1"].numa_mask
        assert bin(numa).count("1") == 1
        cm.release_pod("p1")
        assert "p1" not in cm.cpu.assignments
        assert "p1" not in cm.devices.allocations

    def test_admission_failure_rolls_back(self):
        cm = ContainerManager(num_cpus=4, memory_bytes=2 << 30)
        # memory is the blocker; CPU allocation must be rolled back
        pod = guaranteed_pod("p1", cpu="2", memory="4Gi")
        with pytest.raises(AdmissionError):
            cm.admit_pod(pod)
        assert "p1" not in cm.cpu.assignments
        assert "p1" not in cm.memory.assignments


class TestKubeletAdmissionIntegration:
    def test_hollow_kubelet_admits_and_fails_pods(self):
        import time as _t

        from kubernetes_tpu.api import meta
        from kubernetes_tpu.client import LocalClient, SharedInformerFactory
        from kubernetes_tpu.client.clientset import PODS
        from kubernetes_tpu.kubelet.hollow import HollowKubelet
        from kubernetes_tpu.store import kv as kvs

        def wait_for(pred, timeout=10.0):
            deadline = _t.time() + timeout
            while _t.time() < deadline:
                if pred():
                    return True
                _t.sleep(0.02)
            return False

        store = kvs.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        cm = ContainerManager(num_cpus=4, memory_bytes=8 << 30)
        cm.devices.register(DevicePlugin("google.com/tpu", {"tpu0": 0}))
        kubelet = HollowKubelet(client, factory, "cm-node",
                                container_manager=cm)
        factory.start()
        factory.wait_for_cache_sync()
        kubelet.start()
        try:
            # device allocatable surfaced on the node
            node = client.get("nodes", "", "cm-node")
            assert node["status"]["allocatable"]["google.com/tpu"] == "1"
            ok = guaranteed_pod("ok-pod", cpu="2", memory="1Gi",
                                extra_requests={"google.com/tpu": "1"})
            ok["metadata"]["namespace"] = "default"
            ok["spec"]["nodeName"] = "cm-node"
            client.create(PODS, ok)
            assert wait_for(lambda: (client.get(PODS, "default", "ok-pod")
                                     .get("status") or {}).get("phase")
                            == "Running")
            assert cm.devices.allocations  # admitted through the cm
            # second TPU pod must fail admission (only one chip)
            bad = guaranteed_pod("bad-pod", cpu="1", memory="1Gi",
                                 extra_requests={"google.com/tpu": "1"})
            bad["metadata"]["namespace"] = "default"
            bad["spec"]["nodeName"] = "cm-node"
            client.create(PODS, bad)
            assert wait_for(lambda: (client.get(PODS, "default", "bad-pod")
                                     .get("status") or {}).get("reason")
                            == "UnexpectedAdmissionError")
            # deleting the good pod releases its devices
            client.delete(PODS, "default", "ok-pod")
            assert wait_for(lambda: not cm.devices.allocations)
        finally:
            kubelet.stop()
            factory.stop()


class TestTerminalReclaimAndReconcile:
    def test_terminal_pod_releases_devices(self):
        import time as _t

        from kubernetes_tpu.client import LocalClient, SharedInformerFactory
        from kubernetes_tpu.client.clientset import PODS
        from kubernetes_tpu.kubelet.hollow import HollowKubelet
        from kubernetes_tpu.store import kv as kvs

        store = kvs.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        cm = ContainerManager(num_cpus=8, memory_bytes=8 << 30)
        cm.devices.register(DevicePlugin("google.com/tpu", {"tpu0": 0}))
        kubelet = HollowKubelet(client, factory, "t-node",
                                container_manager=cm)
        factory.start()
        factory.wait_for_cache_sync()
        kubelet.start()
        try:
            pod = guaranteed_pod("term-pod", cpu="1", memory="1Gi",
                                 extra_requests={"google.com/tpu": "1"})
            pod["metadata"]["namespace"] = "default"
            pod["spec"]["nodeName"] = "t-node"
            client.create(PODS, pod)
            deadline = _t.time() + 10
            while _t.time() < deadline and not cm.devices.allocations:
                _t.sleep(0.02)
            assert cm.devices.allocations
            # pod turns terminal (NOT deleted): devices must come back
            client.update_status(PODS, {**client.get(PODS, "default",
                                                     "term-pod"),
                                        "status": {"phase": "Succeeded"}})
            deadline = _t.time() + 10
            while _t.time() < deadline and cm.devices.allocations:
                _t.sleep(0.02)
            assert not cm.devices.allocations
        finally:
            kubelet.stop()
            factory.stop()

    def test_restart_reconciles_stale_checkpoint(self, tmp_path):
        cm = ContainerManager(num_cpus=8, memory_bytes=8 << 30,
                              checkpoint_dir=str(tmp_path))
        cm.devices.register(DevicePlugin("google.com/tpu", {"tpu0": 0}))
        cm.admit_pod(guaranteed_pod("ghost", cpu="1", memory="1Gi",
                                    extra_requests={"google.com/tpu": "1"}))
        # simulated restart: fresh managers restore the checkpoint...
        cm2 = ContainerManager(num_cpus=8, memory_bytes=8 << 30,
                               checkpoint_dir=str(tmp_path))
        cm2.devices.register(DevicePlugin("google.com/tpu", {"tpu0": 0}))
        assert "ghost" in cm2.devices.allocations
        # ...and reconcile against live pods (ghost vanished meanwhile)
        cm2.reconcile(set())
        assert "ghost" not in cm2.devices.allocations
        assert "ghost" not in cm2.cpu.assignments
