"""Kubelet depth: checkpoint manager, QoS, probes, eviction, status
manager, pod workers, image GC, restart policy + crash backoff.

Behavioral contracts from pkg/kubelet/{checkpointmanager,prober,eviction,
status,pod_workers.go,images,kuberuntime}.
"""

import json
import threading
import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.kubelet.checkpoint import (
    CheckpointManager, CorruptCheckpointError,
)
from kubernetes_tpu.kubelet.cri import EXITED, RUNNING, FakeRuntimeService
from kubernetes_tpu.kubelet.eviction import EvictionManager
from kubernetes_tpu.kubelet.images import ImageGCManager
from kubernetes_tpu.kubelet.kubelet import Kubelet
from kubernetes_tpu.kubelet.pod_workers import PodWorkers
from kubernetes_tpu.kubelet.probes import LIVENESS, READINESS, ProbeManager
from kubernetes_tpu.kubelet.qos import (
    BEST_EFFORT, BURSTABLE, GUARANTEED, pod_qos,
)
from kubernetes_tpu.kubelet.status_manager import StatusManager
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def make_pod(name, node="n1", requests=None, limits=None, **spec_extra):
    pod = meta.new_object("Pod", name, "default")
    pod["metadata"]["uid"] = f"uid-{name}"
    res = {}
    if requests:
        res["requests"] = requests
    if limits:
        res["limits"] = limits
    pod["spec"] = {"nodeName": node,
                   "containers": [{"name": "c0", "image": "img:v1",
                                   "resources": res}],
                   **spec_extra}
    return pod


# -- checkpoint manager ----------------------------------------------------

class TestCheckpointManager:
    def test_roundtrip(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("state", {"a": [1, 2], "b": "x"})
        assert cm.get_checkpoint("state") == {"a": [1, 2], "b": "x"}
        assert cm.list_checkpoints() == ["state"]
        cm.remove_checkpoint("state")
        assert cm.list_checkpoints() == []

    def test_missing_raises_keyerror(self, tmp_path):
        with pytest.raises(KeyError):
            CheckpointManager(str(tmp_path)).get_checkpoint("nope")

    def test_corrupt_checksum_detected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        cm.create_checkpoint("state", {"v": 1})
        path = tmp_path / "state"
        doc = json.loads(path.read_text())
        doc["data"] = json.dumps({"v": 2})  # tampered, checksum stale
        path.write_text(json.dumps(doc))
        with pytest.raises(CorruptCheckpointError):
            cm.get_checkpoint("state")

    def test_torn_write_detected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        (tmp_path / "torn").write_text('{"data": "{\\"v\\"')
        with pytest.raises(CorruptCheckpointError):
            cm.get_checkpoint("torn")

    def test_invalid_names_rejected(self, tmp_path):
        cm = CheckpointManager(str(tmp_path))
        with pytest.raises(ValueError):
            cm.create_checkpoint("../escape", {})


# -- QoS -------------------------------------------------------------------

class TestQoS:
    def test_guaranteed(self):
        p = make_pod("g", requests={"cpu": "1", "memory": "1Gi"},
                     limits={"cpu": "1", "memory": "1Gi"})
        assert pod_qos(p) == GUARANTEED

    def test_limits_only_is_guaranteed(self):
        p = make_pod("g2", limits={"cpu": "1", "memory": "1Gi"})
        assert pod_qos(p) == GUARANTEED

    def test_burstable(self):
        p = make_pod("b", requests={"cpu": "1"})
        assert pod_qos(p) == BURSTABLE

    def test_best_effort(self):
        assert pod_qos(make_pod("be")) == BEST_EFFORT

    def test_mismatched_request_limit_burstable(self):
        p = make_pod("m", requests={"cpu": "1", "memory": "1Gi"},
                     limits={"cpu": "2", "memory": "1Gi"})
        assert pod_qos(p) == BURSTABLE


# -- probes ----------------------------------------------------------------

class TestProbes:
    def test_readiness_gates_until_success(self):
        results = []
        pm = ProbeManager(
            handler=lambda pod, c, t, running: True,
            on_readiness_change=lambda p, c, ok: results.append(ok))
        pod = make_pod("r")
        pod["spec"]["containers"][0]["readinessProbe"] = {
            "initialDelaySeconds": 0.3, "periodSeconds": 0.05}
        pm.add_pod(pod)
        assert pm.pod_ready(pod) is False  # gated until first success
        assert wait_for(lambda: pm.pod_ready(pod), timeout=5)
        assert results == [True]
        pm.stop()

    def test_failure_threshold_before_liveness_restart(self):
        restarts = []
        pm = ProbeManager(
            handler=lambda pod, c, t, running: False,
            on_liveness_failure=lambda p, c: restarts.append(c))
        pod = make_pod("l")
        pod["spec"]["containers"][0]["livenessProbe"] = {
            "periodSeconds": 0.05, "failureThreshold": 3}
        pm.add_pod(pod)
        assert wait_for(lambda: restarts, timeout=5)
        assert restarts[0] == "c0"
        pm.stop()

    def test_remove_pod_stops_workers(self):
        pm = ProbeManager(handler=lambda *a: True)
        pod = make_pod("gone")
        pod["spec"]["containers"][0]["readinessProbe"] = {"periodSeconds": 1}
        pm.add_pod(pod)
        pm.remove_pod(pod)
        assert pm.readiness == {}
        assert pm._workers == {}

    def test_annotation_handler_fails_probe(self):
        from kubernetes_tpu.kubelet.probes import default_handler
        pod = make_pod("ann")
        pod["metadata"]["annotations"] = {"hollow/fail-readiness": "true"}
        assert default_handler(pod, {"name": "c0"}, READINESS, True) is False
        assert default_handler(pod, {"name": "c0"}, LIVENESS, True) is True


# -- eviction --------------------------------------------------------------

class TestEviction:
    def _cluster(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        return store, client

    def test_evicts_best_effort_first_under_pressure(self):
        store, client = self._cluster()
        node = meta.new_object("Node", "n1", "")
        node["status"] = {"conditions": []}
        client.create(NODES, node)
        be = make_pod("besteffort")
        bu = make_pod("burstable", requests={"memory": "900Mi"})
        client.create(PODS, be)
        client.create(PODS, bu)
        pods = [client.get(PODS, "default", "besteffort"),
                client.get(PODS, "default", "burstable")]
        em = EvictionManager(
            client, "n1", memory_capacity=1 << 30,  # 1Gi, ~900Mi used
            memory_available_threshold=0.15,  # 12% free < 15% -> pressure
            stats_provider=lambda ps: sum(
                0 if meta.name(p) == "besteffort" else 943718400
                for p in ps),
            list_pods=lambda: [client.get(PODS, "default", meta.name(p))
                               for p in pods
                               if meta.name(p) in {
                                   meta.name(x)
                                   for x in client.list(PODS, "default")[0]}])
        evicted = em.synchronize()
        # BestEffort dies first even though Burstable is the hog
        assert evicted[0] == "besteffort"
        assert (client.get(PODS, "default", "besteffort")["status"]["reason"]
                == "Evicted")
        node = client.get(NODES, "", "n1")
        assert any(c["type"] == "MemoryPressure"
                   for c in node["status"]["conditions"])

    def test_no_pressure_no_eviction(self):
        store, client = self._cluster()
        node = meta.new_object("Node", "n1", "")
        client.create(NODES, node)
        p = make_pod("calm", requests={"memory": "64Mi"})
        client.create(PODS, p)
        em = EvictionManager(client, "n1", memory_capacity=1 << 30,
                             list_pods=lambda: [p])
        assert em.synchronize() == []
        assert em.under_pressure is False


# -- status manager --------------------------------------------------------

class TestStatusManager:
    def test_dedupes_identical_statuses(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        pod = make_pod("s")
        client.create(PODS, pod)
        sm = StatusManager(client)
        for _ in range(5):
            sm.set_pod_status(pod, {"phase": "Running"})
        assert sm.api_writes == 1
        sm.set_pod_status(pod, {"phase": "Succeeded"})
        assert sm.api_writes == 2
        assert client.get(PODS, "default", "s")["status"]["phase"] == "Succeeded"

    def test_missing_pod_dropped(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        sm = StatusManager(client)
        pod = make_pod("ghost")
        sm.set_pod_status(pod, {"phase": "Running"})  # pod never created
        assert sm.get_pod_status("uid-ghost") is None


# -- pod workers -----------------------------------------------------------

class TestPodWorkers:
    def test_serialized_per_pod_and_coalesced(self):
        seen = []
        gate = threading.Event()

        def sync(update_type, pod):
            if not seen:
                gate.wait(5)
            seen.append((update_type, pod["metadata"]["labels"]["v"]))

        pw = PodWorkers(sync)
        pod = make_pod("w")
        for v in ("1", "2", "3"):  # arrive while sync #1 blocks
            pod = meta.deep_copy(pod)
            pod["metadata"]["labels"] = {"v": v}
            pw.update_pod("SYNC", pod)
        gate.set()
        assert wait_for(lambda: len(seen) == 2, timeout=5)
        time.sleep(0.1)
        # v=2 was coalesced away: only the first and the latest ran
        assert [v for _, v in seen] == ["1", "3"]
        pw.stop()

    def test_worker_exception_does_not_kill_pool(self):
        calls = []

        def sync(update_type, pod):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("boom")

        pw = PodWorkers(sync)
        pod = make_pod("e")
        pw.update_pod("SYNC", pod)
        assert wait_for(lambda: len(calls) == 1)
        pw.update_pod("SYNC", pod)
        assert wait_for(lambda: len(calls) == 2)
        pw.stop()


# -- image GC --------------------------------------------------------------

class TestImageGC:
    def test_gc_when_over_threshold(self):
        rt = FakeRuntimeService()
        gc = ImageGCManager(rt, disk_capacity=10, image_size=1,
                            high_threshold_percent=85,
                            low_threshold_percent=50)
        for i in range(9):  # 90% > 85%
            rt.pull_image(f"img:{i}")
            gc.image_used(f"img:{i}")
        deleted = gc.garbage_collect(in_use={"img:8"})
        assert "img:8" not in deleted
        assert gc.usage_percent() <= 50
        # oldest-used deleted first
        assert deleted[0] == "img:0"

    def test_no_gc_below_threshold(self):
        rt = FakeRuntimeService()
        gc = ImageGCManager(rt, disk_capacity=10, image_size=1)
        rt.pull_image("img:a")
        assert gc.garbage_collect(in_use=set()) == []


# -- full kubelet: restart policy + crash backoff + probes ----------------

@pytest.fixture
def kubelet_cluster(tmp_path):
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    kl = Kubelet(client, factory, "n1", root_dir=str(tmp_path),
                 heartbeat_interval=3600)
    factory.start()
    factory.wait_for_cache_sync()
    kl.start()
    yield store, client, kl
    kl.stop()
    factory.stop()


class TestKubeletFull:
    def test_pod_runs_and_reports_qos(self, kubelet_cluster):
        store, client, kl = kubelet_cluster
        client.create(PODS, make_pod("app", requests={"cpu": "1"}))
        assert wait_for(lambda: (client.get(PODS, "default", "app")
                                 .get("status") or {}).get("phase") == "Running")
        assert client.get(PODS, "default", "app")["status"]["qosClass"] == \
            BURSTABLE

    def test_restart_policy_always_restarts_exited(self, kubelet_cluster):
        store, client, kl = kubelet_cluster
        pod = make_pod("crashy")
        pod["metadata"]["annotations"] = {"hollow/run-seconds": "0.05",
                                          "hollow/exit-code": "1"}
        client.create(PODS, pod)
        assert wait_for(lambda: kl._container_running(pod, "c0"), timeout=10)
        # wait for the planned exit, then the restart
        assert wait_for(
            lambda: (kl.runtime.list_containers()
                     and any(c["state"] == EXITED
                             for c in kl.runtime.list_containers()))
            or kl._backoff, timeout=10)
        kl.workers.update_pod("SYNC", client.get(PODS, "default", "crashy"))
        assert wait_for(lambda: ("uid-crashy", "c0") in kl._backoff,
                        timeout=10)

    def test_restart_policy_never_stays_dead(self, kubelet_cluster):
        store, client, kl = kubelet_cluster
        pod = make_pod("oneshot", restartPolicy="Never")
        pod["metadata"]["annotations"] = {"hollow/run-seconds": "0.05",
                                          "hollow/exit-code": "0"}
        client.create(PODS, pod)
        assert wait_for(lambda: (client.get(PODS, "default", "oneshot")
                                 .get("status") or {}).get("phase")
                        == "Succeeded", timeout=10)

    def test_readiness_probe_gates_ready_condition(self, kubelet_cluster):
        store, client, kl = kubelet_cluster
        pod = make_pod("gated")
        pod["metadata"]["annotations"] = {"hollow/fail-readiness": "true"}
        pod["spec"]["containers"][0]["readinessProbe"] = {
            "periodSeconds": 0.05}
        client.create(PODS, pod)
        assert wait_for(lambda: (client.get(PODS, "default", "gated")
                                 .get("status") or {}).get("phase")
                        == "Running", timeout=10)
        time.sleep(0.3)
        conds = client.get(PODS, "default", "gated")["status"]["conditions"]
        ready = next(c for c in conds if c["type"] == "Ready")
        assert ready["status"] == "False"

    def test_checkpoint_and_restore(self, kubelet_cluster, tmp_path):
        store, client, kl = kubelet_cluster
        client.create(PODS, make_pod("persist"))
        assert wait_for(lambda: kl._pod_state, timeout=10)
        kl._checkpoint_state()
        # a fresh kubelet over the same root restores allocation state
        factory2 = SharedInformerFactory(client)
        kl2 = Kubelet(client, factory2, "n1", root_dir=str(tmp_path),
                      heartbeat_interval=3600)
        assert kl2.restore_state() is True
        assert "uid-persist" in kl2._pod_state
