"""Node authorizer: a kubelet certificate is scoped to ITS node.

Reference: plugin/pkg/auth/authorizer/node/ (the graph-based node
authorizer) + the NodeRestriction write pinning, running as the Node
half of --authorization-mode=Node,RBAC.  Everything here crosses the
real TLS wire with real client certs.
"""

import pytest

pytest.importorskip("cryptography",
                    reason="ClusterCA/TLS need the cryptography package")

from kubernetes_tpu.api import meta
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver import authn as authnlib
from kubernetes_tpu.client.http_client import HTTPClient, HTTPError
from kubernetes_tpu.controllers.certificates import ClusterCA
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    d = tmp_path_factory.mktemp("node-pki")
    ca = ClusterCA()
    tls = authnlib.write_serving_bundle(ca, str(d))
    store = kv.MemoryStore()
    server = APIServer(store, tls=tls, enable_rbac=True).start()

    def client_for(cn, orgs=()):
        cert_pem, key_pem = authnlib.issue_cert(ca, cn, tuple(orgs))
        slug = cn.replace(":", "_")
        (d / f"{slug}.crt").write_text(cert_pem)
        (d / f"{slug}.key").write_text(key_pem)
        return HTTPClient(server.httpd.server_address[0], server.port,
                          tls={"ca_file": tls["client_ca_file"],
                               "cert_file": str(d / f"{slug}.crt"),
                               "key_file": str(d / f"{slug}.key")})

    admin = client_for("kubernetes-admin", ["system:masters"])
    kubelet_a = client_for("system:node:node-a", ["system:nodes"])
    kubelet_b = client_for("system:node:node-b", ["system:nodes"])
    for n in ("node-a", "node-b"):
        admin.create("nodes", make_node(n).build())
    yield admin, kubelet_a, kubelet_b, store
    server.stop()


class TestNodeScoping:
    def test_own_node_writes_allowed(self, cluster):
        admin, ka, kb, store = cluster
        ka.guaranteed_update(
            "nodes", "", "node-a",
            lambda n: {**n, "status": {**(n.get("status") or {}),
                                       "lastHeartbeatTime": 1.0}})

    def test_other_node_writes_denied(self, cluster):
        admin, ka, kb, store = cluster
        with pytest.raises(HTTPError) as exc:
            ka.guaranteed_update(
                "nodes", "", "node-b",
                lambda n: {**n, "status": {"hacked": True}})
        assert exc.value.code == 403

    def test_lease_scoping(self, cluster):
        admin, ka, kb, store = cluster
        for owner, node in ((ka, "node-a"), (kb, "node-b")):
            lease = meta.new_object("Lease", node, "kube-node-lease")
            lease["spec"] = {"holderIdentity": node}
            owner.create("leases", lease)
        ka.guaranteed_update(
            "leases", "kube-node-lease", "node-a",
            lambda l: {**l, "spec": {**l["spec"], "renewTime": 2.0}})
        with pytest.raises(HTTPError) as exc:
            ka.guaranteed_update(
                "leases", "kube-node-lease", "node-b",
                lambda l: {**l, "spec": {**l["spec"], "renewTime": 2.0}})
        assert exc.value.code == 403

    def test_pod_status_only_for_bound_pods(self, cluster):
        admin, ka, kb, store = cluster
        for name, node in (("pa", "node-a"), ("pb", "node-b")):
            pod = make_pod(name).node(node).build()
            admin.create("pods", pod)
        ka.guaranteed_update(
            "pods", "default", "pa",
            lambda p: {**p, "status": {"phase": "Running"}})
        with pytest.raises(HTTPError) as exc:
            ka.guaranteed_update(
                "pods", "default", "pb",
                lambda p: {**p, "status": {"phase": "Failed"}})
        assert exc.value.code == 403

    def test_pod_create_denied(self, cluster):
        admin, ka, kb, store = cluster
        with pytest.raises(HTTPError) as exc:
            ka.create("pods", make_pod("rogue").build())
        assert exc.value.code == 403

    def test_reads_allowed(self, cluster):
        admin, ka, kb, store = cluster
        ka.list("pods", "default")
        ka.list("nodes")
        ka.get("nodes", "", "node-b")  # reads are not name-scoped


class TestSecretGraph:
    def test_secret_gated_on_pod_reference(self, cluster):
        admin, ka, kb, store = cluster
        for name in ("app-secret", "unrelated-secret"):
            sec = meta.new_object("Secret", name, "default")
            sec["data"] = {"k": "djNsdWU="}
            admin.create("secrets", sec)
        pod = make_pod("secret-user").node("node-a").build()
        pod["spec"]["volumes"] = [{"name": "v", "secret":
                                   {"secretName": "app-secret"}}]
        admin.create("pods", pod)
        assert ka.get("secrets", "default", "app-secret")
        with pytest.raises(HTTPError) as exc:
            ka.get("secrets", "default", "unrelated-secret")
        assert exc.value.code == 403
        # the pod is on node-a, so node-b's kubelet gets nothing
        with pytest.raises(HTTPError) as exc:
            kb.get("secrets", "default", "app-secret")
        assert exc.value.code == 403
        # and list/watch of secrets is never granted to kubelets
        with pytest.raises(HTTPError) as exc:
            ka.list("secrets", "default")
        assert exc.value.code == 403

    def test_lease_outside_node_lease_ns_denied(self, cluster):
        admin, ka, kb, store = cluster
        lease = meta.new_object("Lease", "apiserver-x", "kube-system")
        lease["spec"] = {"holderIdentity": "forged"}
        with pytest.raises(HTTPError) as exc:
            ka.create("leases", lease)
        assert exc.value.code == 403
        # even a name collision with the node name stays out of reach
        lease2 = meta.new_object("Lease", "node-a", "kube-system")
        lease2["spec"] = {"holderIdentity": "forged"}
        with pytest.raises(HTTPError) as exc:
            ka.create("leases", lease2)
        assert exc.value.code == 403

    def test_envfrom_and_pull_secrets_count(self, cluster):
        admin, ka, kb, store = cluster
        for name in ("envfrom-secret", "pull-secret"):
            sec = meta.new_object("Secret", name, "default")
            sec["data"] = {"k": "eA=="}
            admin.create("secrets", sec)
        pod = make_pod("wide-ref").node("node-a").build()
        pod["spec"]["imagePullSecrets"] = [{"name": "pull-secret"}]
        pod["spec"]["containers"][0]["envFrom"] = [
            {"secretRef": {"name": "envfrom-secret"}}]
        admin.create("pods", pod)
        assert ka.get("secrets", "default", "envfrom-secret")
        assert ka.get("secrets", "default", "pull-secret")

    def test_env_ref_also_counts(self, cluster):
        admin, ka, kb, store = cluster
        sec = meta.new_object("Secret", "env-secret", "default")
        sec["data"] = {"k": "eA=="}
        admin.create("secrets", sec)
        pod = make_pod("env-user").node("node-a").build()
        pod["spec"]["containers"][0]["env"] = [
            {"name": "TOKEN", "valueFrom": {"secretKeyRef":
                                            {"name": "env-secret",
                                             "key": "k"}}}]
        admin.create("pods", pod)
        assert ka.get("secrets", "default", "env-secret")
