"""Node-plane tests: hollow kubelet lifecycle, full Deployment->Running
chain, Job completion via the fake runtime, endpoints + kube-proxy, node
failure eviction. This is the closest analog of the reference's kubemark
simulated-cluster tier (SURVEY.md §4.4)."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import (
    DEPLOYMENTS, ENDPOINTS, JOBS, NODES, PODS, SERVICES,
)
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.controllers.nodelifecycle import NodeLifecycleController
from kubernetes_tpu.kubelet import HollowKubelet, start_hollow_nodes
from kubernetes_tpu.proxy import ServiceProxy
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.store import kv


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def full_cluster():
    """Control plane + scheduler + controllers + 2 hollow nodes."""
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    sched = new_scheduler(client, factory)
    mgr = ControllerManager(client, factory)
    ep = EndpointsController(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    mgr.run()
    ep.run()
    kubelets = start_hollow_nodes(client, factory, 2, heartbeat_interval=0.5)
    yield store, client, factory, kubelets
    for k in kubelets:
        k.stop()
    ep.stop()
    mgr.stop()
    sched.stop()
    factory.stop()


def phase(client, ns, name):
    try:
        return (client.get(PODS, ns, name).get("status") or {}).get("phase")
    except kv.NotFoundError:
        return None


class TestHollowKubelet:
    def test_node_registers_with_capacity(self, full_cluster):
        store, client, factory, kubelets = full_cluster
        node = client.get(NODES, "", "hollow-0")
        assert node["status"]["allocatable"]["cpu"] == "32000m"
        assert any(c["type"] == "Ready" and c["status"] == "True"
                   for c in node["status"]["conditions"])

    def test_pod_runs_after_binding(self, full_cluster):
        store, client, factory, kubelets = full_cluster
        from kubernetes_tpu.testing import make_pod
        client.create(PODS, make_pod("web").req(cpu="100m").build())
        assert wait_for(lambda: phase(client, "default", "web") == "Running")
        pod = client.get(PODS, "default", "web")
        assert pod["status"].get("podIP")
        assert any(c["type"] == "Ready" and c["status"] == "True"
                   for c in pod["status"]["conditions"])

    def test_deployment_to_running_chain(self, full_cluster):
        """Deployment -> RS -> pods -> scheduled -> Running -> RS Ready."""
        store, client, factory, kubelets = full_cluster
        dep = meta.new_object("Deployment", "api", "default")
        dep["spec"] = {"replicas": 3,
                       "selector": {"matchLabels": {"app": "api"}},
                       "template": {"metadata": {"labels": {"app": "api"}},
                                    "spec": {"containers": [
                                        {"name": "c0", "image": "img"}]}}}
        client.create(DEPLOYMENTS, dep)

        def ready():
            d = client.get(DEPLOYMENTS, "default", "api")
            return (d.get("status") or {}).get("readyReplicas") == 3
        assert wait_for(ready, timeout=30)

    def test_job_completes_via_runtime_exit(self, full_cluster):
        store, client, factory, kubelets = full_cluster
        job = meta.new_object("Job", "calc", "default")
        job["spec"] = {
            "completions": 1, "parallelism": 1,
            "template": {
                "metadata": {"annotations": {"hollow/run-seconds": "0.2"}},
                "spec": {"containers": [{"name": "c0", "image": "worker"}]}}}
        client.create(JOBS, job)
        assert wait_for(lambda: any(
            c.get("type") == "Complete"
            for c in (client.get(JOBS, "default", "calc")
                      .get("status") or {}).get("conditions", [])), timeout=30)

    def test_pod_deletion_tears_down_sandbox(self, full_cluster):
        store, client, factory, kubelets = full_cluster
        from kubernetes_tpu.testing import make_pod
        client.create(PODS, make_pod("gone").build())
        assert wait_for(lambda: phase(client, "default", "gone") == "Running")
        owner = next(k for k in kubelets
                     if k.node_name == meta.pod_node_name(
                         client.get(PODS, "default", "gone")))
        client.delete(PODS, "default", "gone")
        assert wait_for(lambda: not owner._pod_state)


class TestServiceDataplane:
    def test_endpoints_and_proxy(self, full_cluster):
        store, client, factory, kubelets = full_cluster
        from kubernetes_tpu.testing import make_pod
        for i in range(2):
            client.create(PODS, make_pod(f"be{i}").labels(app="svc").build())
        assert wait_for(lambda: all(
            phase(client, "default", f"be{i}") == "Running" for i in range(2)))
        svc = meta.new_object("Service", "mysvc", "default")
        svc["spec"] = {"clusterIP": "10.96.0.10", "selector": {"app": "svc"},
                       "ports": [{"port": 80, "protocol": "TCP"}]}
        client.create(SERVICES, svc)
        def two_endpoints():
            try:
                ep = client.get(ENDPOINTS, "default", "mysvc")
            except kv.NotFoundError:
                return False
            subsets = ep.get("subsets") or []
            return bool(subsets) and len(subsets[0].get("addresses") or []) == 2

        assert wait_for(two_endpoints, timeout=20)

        proxy = ServiceProxy(client, factory, "hollow-0").start()
        try:
            assert wait_for(lambda: proxy.route("10.96.0.10", 80) is not None)
            backend = proxy.route("10.96.0.10", 80)
            ips = {a["ip"] for a in
                   client.get(ENDPOINTS, "default", "mysvc")["subsets"][0]["addresses"]}
            assert backend[0] in ips
            assert proxy.route("10.96.0.99", 80) is None
        finally:
            proxy.stop()


class TestNodeFailure:
    def test_dead_node_pods_evicted_and_rescheduled(self):
        store = kv.MemoryStore()
        client = LocalClient(store)
        factory = SharedInformerFactory(client)
        sched = new_scheduler(client, factory)
        nlc = NodeLifecycleController(client, factory, grace_period=1.0,
                                      tick=0.3)
        factory.start()
        factory.wait_for_cache_sync()
        sched.run()
        nlc.run()
        kubelets = start_hollow_nodes(client, factory, 2,
                                      heartbeat_interval=0.2)
        try:
            from kubernetes_tpu.testing import make_pod
            client.create(PODS, make_pod("worker").build())
            assert wait_for(lambda: phase(client, "default", "worker") == "Running")
            victim_node = meta.pod_node_name(client.get(PODS, "default", "worker"))
            victim = next(k for k in kubelets if k.node_name == victim_node)
            victim.stop()  # heartbeats cease -> NotReady -> eviction
            assert wait_for(lambda: phase(client, "default", "worker") is None,
                            timeout=20)
            node = client.get(NODES, "", victim_node)
            assert any(c["type"] == "Ready" and c["status"] == "False"
                       for c in node["status"]["conditions"])
        finally:
            for k in kubelets:
                k.stop()
            nlc.stop()
            sched.stop()
            factory.stop()
