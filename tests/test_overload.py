"""Overload-resilience suite for the batch pipeline (ISSUE 5 tentpole).

Four closed-loop protections, each proven against a seeded, reproducible
overload schedule (ops/faults.OverloadSchedule — same determinism rules
as the transport-level FaultSchedule):

  * bounded admission   — the active queue is capped; over the cap the
                          LOWEST-priority, youngest pods shed into the
                          backoff tier (never dropped); system/high
                          priority and aged pods are shed-exempt.
  * AIMD wave sizing    — _WaveTuner shrinks the dispatch wave
                          multiplicatively on SLO breach, grows it
                          additively while under.
  * escape-storm breaker— a batch whose SKIP rate crosses the threshold
                          trips _OverloadBreaker: the escape class waits
                          out a backoff instead of flooding the per-pod
                          oracle; a calm probe batch re-closes it.
  * stuck-wave watchdog — a wave whose resolve outlives waveDeadline is
                          cancelled and the pods re-enter backoff via the
                          BackendUnavailableError requeue path.

Plus the satellite seams: per-binding failure classification under a bulk
bind error, and the overload: config stanza.
"""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.ops.faults import (
    ALL_ESCAPE, SLOW, ChaosBatchBackend, OverloadSchedule)
from kubernetes_tpu.scheduler import Profile, Scheduler, new_default_framework
from kubernetes_tpu.scheduler.config import (
    ConfigError, OverloadPolicy, load_config)
from kubernetes_tpu.scheduler.queue import (
    SYSTEM_PRIORITY_BAND, SchedulingQueue)
from kubernetes_tpu.scheduler.scheduler import (
    BackendUnavailableError, BatchBackend, _OverloadBreaker, _WaveTuner)
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod

pytestmark = pytest.mark.chaos


def wait_for(pred, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def new_queue(cap=0, protect_prio=1000, protect_age=30.0,
              initial=0.05, maximum=0.2):
    return SchedulingQueue(pod_initial_backoff=initial,
                           pod_max_backoff=maximum,
                           queue_cap=cap,
                           shed_protect_priority=protect_prio,
                           shed_protect_age=protect_age)


def prio_pod(name, priority):
    return make_pod(name).priority(priority).req(cpu="100m").build()


# -- tentpole (1): bounded priority-aware admission ----------------------


class TestBoundedAdmission:
    def test_flood_capped_and_shed_to_backoff(self):
        """20 pods into a cap-8 queue: active holds exactly the cap, the
        12 shed pods land in BACKOFF (not dropped), and the shed tally
        carries the reason + priority band."""
        q = new_queue(cap=8)
        for i in range(20):
            q.add(make_pod(f"p{i}").build())  # default priority 0
        st = q.stats()
        assert st["active"] == 8
        assert st["backoff"] == 12
        assert q.drain_shed_total() == {("admission", "best_effort"): 12}
        assert q.drain_shed_total() == {}  # drain is destructive

    def test_lowest_priority_shed_first(self):
        """Whichever arrival order, the pods that survive at the cap are
        the higher-priority ones."""
        for first, second in [(500, 10), (10, 500)]:
            q = new_queue(cap=4)
            for i in range(4):
                q.add(prio_pod(f"a{i}", first))
            for i in range(4):
                q.add(prio_pod(f"b{i}", second))
            survivors = q.pop_batch(8, timeout=0.1)
            assert len(survivors) == 4
            assert all(s.pod_info.priority == 500 for s in survivors), \
                f"arrival order {first},{second}"

    def test_system_and_high_priority_never_shed(self):
        """Shed-exempt pods may take active past the cap — bounded
        admission must NEVER cost a system or high-priority pod."""
        q = new_queue(cap=2, protect_prio=1000)
        for i in range(3):
            q.add(prio_pod(f"be{i}", 0))
        for i in range(3):
            q.add(prio_pod(f"sys{i}", SYSTEM_PRIORITY_BAND + i))
        for i in range(3):
            q.add(prio_pod(f"hi{i}", 1500))
        sheds = q.drain_shed_total()
        assert all(band == "best_effort" for _, band in sheds)
        popped = q.pop_batch(16, timeout=0.1)
        names = {p.pod_info.pod["metadata"]["name"] for p in popped}
        assert {f"sys{i}" for i in range(3)} <= names
        assert {f"hi{i}" for i in range(3)} <= names

    def test_aged_pod_is_shed_exempt(self):
        """A pod past shedProtectAgeSeconds is exempt even when it is the
        lowest-priority victim candidate — starvation protection: a pod
        cannot be shed over and over forever."""
        q = new_queue(cap=1, protect_age=0.05)
        q.add(prio_pod("old", -1))  # lowest priority: first victim pick
        time.sleep(0.1)            # ...but now aged past the threshold
        q.add(prio_pod("fresh", 0))
        st = q.stats()
        assert st["active"] == 2   # both kept: the only victim was exempt
        assert q.drain_shed_total() == {}

    def test_shed_keeps_initial_attempt_timestamp_and_reenters(self):
        """Shed = move to backoff with attempts+1: the original
        initial_attempt_timestamp survives (so age-based protections keep
        working) and the pod re-enters active after its backoff."""
        q = new_queue(cap=1, initial=0.05, maximum=0.2)
        q.run()
        try:
            q.add(make_pod("p0").build())
            q.add(make_pod("p1").build())  # over cap: p1 (youngest) shed
            [first] = q.pop_batch(2, timeout=0.5)
            assert first.key == "default/p0"
            again = []
            deadline = time.time() + 5.0
            while time.time() < deadline and not again:
                again.extend(q.pop_batch(2, timeout=0.1))
            [p1] = again
            assert p1.key == "default/p1"
            assert p1.attempts == 2  # 1 shed + 1 pop
            # backoff clock restarted at shed; admission clock did not
            assert p1.initial_attempt_timestamp < p1.timestamp
        finally:
            q.close()

    def test_no_infinite_shed_loop(self):
        """shed -> backoff -> promote -> shed must converge: with age
        protection every pod is eventually admitted and popped."""
        q = new_queue(cap=2, protect_age=0.1, initial=0.02, maximum=0.05)
        q.run()
        try:
            for i in range(6):
                q.add(make_pod(f"p{i}").build())
            seen = set()
            deadline = time.time() + 5.0
            while time.time() < deadline and len(seen) < 6:
                for p in q.pop_batch(2, timeout=0.1):
                    seen.add(p.key)
            assert len(seen) == 6
        finally:
            q.close()


# -- tentpole (2): AIMD wave sizing --------------------------------------


class TestWaveTuner:
    def test_breach_shrinks_multiplicatively_to_floor(self):
        t = _WaveTuner(256, 0.2, 16, 32, 0.5)
        assert t.current() == 256
        t.observe(0.5, 1000)
        assert t.current() == 128
        for _ in range(10):
            t.observe(0.9, 1000)
        assert t.current() == 16  # never below wave_min

    def test_under_slo_grows_additively(self):
        t = _WaveTuner(256, 0.2, 16, 32, 0.5)
        for _ in range(10):
            t.observe(0.9, 0)
        assert t.current() == 16
        t.observe(0.05, 1000)       # under SLO with a backlog
        assert t.current() == 48    # +increase
        t.observe(0.05, 0)          # under SLO, queue idle
        assert t.current() == 56    # +increase//4 (cautious growth)

    def test_never_exceeds_cap(self):
        t = _WaveTuner(64, 0.2, 16, 32, 0.5)
        for _ in range(50):
            t.observe(0.01, 10_000)
        assert t.current() == 64


# -- tentpole (3): escape-storm breaker ----------------------------------


class TestOverloadBreaker:
    def test_opens_on_threshold_probes_and_recloses(self):
        clock = [0.0]
        br = _OverloadBreaker(threshold=2, probe_interval=5.0,
                              now_fn=lambda: clock[0])
        assert br.record_storm() is False  # 1 of 2
        assert br.record_storm() is True   # opens (edge)
        assert br.is_open and not br.probe_due()
        clock[0] = 5.0
        assert br.probe_due()
        assert br.record_storm() is False  # failed probe: re-arm
        assert not br.probe_due()          # window restarted
        clock[0] = 10.0
        assert br.probe_due()
        assert br.record_calm() is True    # calm probe re-closes (edge)
        assert not br.is_open
        assert br.record_calm() is False   # already closed: no edge

    def test_calm_resets_consecutive_count(self):
        br = _OverloadBreaker(threshold=2, probe_interval=5.0,
                              now_fn=lambda: 0.0)
        assert br.record_storm() is False
        br.record_calm()
        assert br.record_storm() is False  # count restarted, not 2 of 2
        assert not br.is_open


# -- e2e harness ---------------------------------------------------------


class _StubRung(BatchBackend):
    """Assigns every pod to a fixed node (test_chaos_seam idiom)."""

    def __init__(self, node="ov-0"):
        self.node = node
        self.stats = {"batches": 0}

    def dispatch(self, pod_infos, snapshot):
        results = [(self.node, None) for _ in pod_infos]
        self.stats["batches"] += 1
        return lambda: results


def build_harness(backend, policy=None, batch_size=8):
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory)
    sched = Scheduler(client, factory, {"default-scheduler": Profile(
        fw, batch_backend=backend, batch_size=batch_size)})
    sched.queue._initial_backoff = 0.05
    sched.queue._max_backoff = 0.2
    if policy is not None:
        sched.configure_overload(policy)
    factory.start()
    factory.wait_for_cache_sync()
    return client, factory, sched


def all_bound(client):
    pods, _ = client.list(PODS, "default")
    return pods and all(meta.pod_node_name(p) for p in pods)


class TestEscapeStormBreakerE2E:
    def test_storm_defers_to_backoff_then_recloses_and_binds(self):
        """Wave 0 is an injected all-escape storm: the breaker opens and
        the whole wave waits out a backoff instead of hitting the per-pod
        oracle.  The chaos schedule then goes calm, so the probe re-closes
        the breaker and every pod binds."""
        chaos = ChaosBatchBackend(_StubRung(), OverloadSchedule(
            script={0: ALL_ESCAPE}))
        policy = OverloadPolicy(engagement="always",
                                escape_rate_threshold=0.5,
                                escape_min_batch=1,
                                breaker_threshold=1,
                                breaker_probe_interval=0.05)
        client, factory, sched = build_harness(chaos, policy)
        try:
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(6):
                client.create(PODS, make_pod(f"esc{i}")
                              .req(cpu="100m").build())
            # pods reach the queue via the (already wired) informer before
            # the run loop starts: wave 0 carries all six
            assert wait_for(lambda: sched.queue.stats()["active"] == 6,
                            timeout=10)
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            sched.expose_metrics()
            prom = sched.metrics.prom
            assert prom.overload_deferred_total.value(
                "injected_all_escape") == 6.0
            assert chaos.injected[ALL_ESCAPE] == 1
            assert prom.overload_breaker_open.value() == 0.0  # re-closed
        finally:
            sched.stop()
            factory.stop()


class TestStuckWaveWatchdogE2E:
    def test_slow_wave_cancelled_and_pods_rebound(self):
        """Wave 0 resolves 1.0s late against a 0.15s deadline: the
        watchdog cancels it, the pods re-enter backoff via the
        BackendUnavailableError path, and the (calm) next wave binds
        them — well before the slow resolve would have returned."""
        chaos = ChaosBatchBackend(_StubRung(), OverloadSchedule(
            script={0: SLOW}, slow_s=1.0))
        policy = OverloadPolicy(engagement="always", wave_deadline=0.15)
        client, factory, sched = build_harness(chaos, policy)
        try:
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(4):
                client.create(PODS, make_pod(f"slow{i}")
                              .req(cpu="100m").build())
            assert wait_for(lambda: sched.queue.stats()["active"] == 4,
                            timeout=10)
            t0 = time.time()
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            prom = sched.metrics.prom
            assert prom.overload_wave_cancel_total.value("deadline") == 1.0
            assert prom.tpu_seam_events.value("requeued_pods") >= 4
            # rebound happened on the cancel path, not by waiting out the
            # 1.0s slow resolve plus a backoff
            assert time.time() - t0 < 1.0
        finally:
            sched.stop()
            factory.stop()


@pytest.mark.pipeline
class TestPipelinedWatchdogE2E:
    """Per-wave watchdog semantics under depth-2 pipelining: the deadline
    clock for wave N+1 starts when wave N retires (head-of-queue), not at
    dispatch — so a slow-but-within-deadline wave never falsely cancels
    the healthy wave pipelined behind it, while a genuinely stuck wave
    cancels its successors too (their resident-state chain is gone)."""

    def test_slow_waves_within_deadline_no_false_cancel(self):
        """Waves 0 and 1 each resolve 0.6s late against a 0.9s deadline.
        Budgeted per wave both pass; budgeted from dispatch, pipelined
        wave 1 would have only ~0.3s left when it reached the head and
        would be falsely cancelled.  Assert zero cancels and that both
        slow resolves really ran back-to-back (elapsed > 1.1s)."""
        chaos = ChaosBatchBackend(_StubRung(), OverloadSchedule(
            script={0: SLOW, 1: SLOW}, slow_s=0.6))
        policy = OverloadPolicy(engagement="always", wave_deadline=0.9)
        client, factory, sched = build_harness(chaos, policy, batch_size=2)
        sched.pipeline_depth = 2
        try:
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(4):
                client.create(PODS, make_pod(f"pipedl{i}")
                              .req(cpu="100m").build())
            assert wait_for(lambda: sched.queue.stats()["active"] == 4,
                            timeout=10)
            t0 = time.time()
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            prom = sched.metrics.prom
            assert prom.overload_wave_cancel_total.value("deadline") == 0.0
            assert prom.tpu_seam_events.value("requeued_pods") == 0.0
            assert chaos.injected[SLOW] == 2
            # both 0.6s resolves actually happened (serial at the device
            # head): proof the waves were live-but-late, not fast
            assert time.time() - t0 > 1.1
        finally:
            sched.stop()
            factory.stop()

    def test_stuck_wave_cancels_pipelined_successor(self):
        """Wave 0 is stuck (2.0s against a 0.2s deadline) with healthy
        wave 1 pipelined behind it.  The watchdog cancels wave 0 AND
        requeues wave 1 — its dispatch rode a resident-state chain that
        abandon_wave just dropped — then the calm retry waves bind all
        four pods well before the stuck resolve would have returned."""
        chaos = ChaosBatchBackend(_StubRung(), OverloadSchedule(
            script={0: SLOW}, slow_s=2.0))
        policy = OverloadPolicy(engagement="always", wave_deadline=0.2)
        client, factory, sched = build_harness(chaos, policy, batch_size=2)
        sched.pipeline_depth = 2
        try:
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(4):
                client.create(PODS, make_pod(f"pipstk{i}")
                              .req(cpu="100m").build())
            assert wait_for(lambda: sched.queue.stats()["active"] == 4,
                            timeout=10)
            t0 = time.time()
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            prom = sched.metrics.prom
            # exactly ONE deadline cancel: the successor is torn down via
            # the requeue path, not double-counted as its own cancel
            assert prom.overload_wave_cancel_total.value("deadline") == 1.0
            assert prom.tpu_seam_events.value("requeued_pods") >= 4
            # the cancel path returned immediately; nothing waited out
            # the 2.0s stuck resolve
            assert time.time() - t0 < 1.5
        finally:
            sched.stop()
            factory.stop()


class TestSeededOverloadChaos:
    def test_flooded_pipeline_stays_live_and_protects_priority(self):
        """The acceptance scenario: a pod flood against a cap-32 queue
        with seeded slow-wave and escape-storm injection.  The pipeline
        must keep scheduling (every pod binds), keep the active queue
        bounded, and never shed a system/high-priority pod."""
        chaos = ChaosBatchBackend(_StubRung(), OverloadSchedule(
            seed=7, slow_rate=0.1, slow_s=0.03, all_escape_rate=0.2))
        policy = OverloadPolicy(engagement="always",
                                queue_cap=32,
                                shed_protect_priority=1000,
                                shed_protect_age=30.0,
                                slo_p99_ms=200.0,
                                escape_rate_threshold=0.5,
                                escape_min_batch=4,
                                breaker_threshold=1,
                                breaker_probe_interval=0.05,
                                wave_deadline=5.0)
        client, factory, sched = build_harness(chaos, policy,
                                               batch_size=16)
        try:
            for i in range(2):
                client.create(NODES, make_node(f"ov-{i}")
                              .capacity(cpu="8", mem="32Gi").build())
            for i in range(120):
                client.create(PODS, prio_pod(f"be{i}", 0))
            for i in range(5):
                client.create(PODS, prio_pod(f"hi{i}", 1500))
            for i in range(5):
                client.create(PODS,
                              prio_pod(f"sys{i}", SYSTEM_PRIORITY_BAND))
            sched.run()
            max_active = 0
            deadline = time.time() + 60.0
            while time.time() < deadline:
                max_active = max(max_active,
                                 sched.queue.stats()["active"])
                if all_bound(client):
                    break
                time.sleep(0.02)
            assert all_bound(client), "pipeline lost liveness under flood"
            # bounded memory: active never exceeds cap + the shed-exempt
            # pods (10 protected-priority pods in the flood)
            assert max_active <= 32 + 10
            sched.expose_metrics()
            sheds = sched.metrics.prom.queue_shed_total.values()
            assert sum(sheds.values()) > 0  # the flood did overflow
            for (reason, band), n in sheds.items():
                assert band not in ("system", "high"), \
                    f"shed {n} {band} pods (reason={reason})"
        finally:
            sched.stop()
            factory.stop()


# -- satellite: per-binding failure classification -----------------------


class TestBulkBindClassification:
    def test_bulk_failure_classified_per_binding(self):
        """A whole-call bulk bind failure where ONE pod was deleted
        mid-flight: the classification pass re-drives each binding, the
        deleted pod is dropped quietly (NotFound), and every other pod in
        the batch still binds — no all-or-nothing requeue."""
        client, factory, sched = build_harness(_StubRung())
        real_bind_many = client.bind_many
        fired = []

        def sabotaged_bind_many(bindings):
            if not fired:
                fired.append(True)
                client.delete(PODS, "default", "bind1")
                raise RuntimeError("injected bulk transport failure")
            return real_bind_many(bindings)

        client.bind_many = sabotaged_bind_many
        try:
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(4):
                client.create(PODS, make_pod(f"bind{i}")
                              .req(cpu="100m").build())
            assert wait_for(lambda: sched.queue.stats()["active"] == 4,
                            timeout=10)
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            pods, _ = client.list(PODS, "default")
            names = {p["metadata"]["name"] for p in pods}
            assert names == {"bind0", "bind2", "bind3"}
            assert fired  # the sabotage actually ran
        finally:
            sched.stop()
            factory.stop()


# -- satellite: overload config stanza -----------------------------------


class TestOverloadConfig:
    def test_stanza_parses(self):
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "overload": {
                "queueCap": 16384,
                "shedProtectPriority": 2000,
                "shedProtectAgeSeconds": 60,
                "sloP99Ms": 250,
                "waveMin": 8,
                "waveIncrease": 16,
                "waveDecrease": 0.25,
                "escapeRateThreshold": 0.5,
                "escapeMinBatch": 4,
                "breakerThreshold": 2,
                "breakerProbeIntervalSeconds": 1.5,
                "waveDeadlineSeconds": 30,
            },
        })
        ov = cfg.overload
        assert ov.enabled
        assert ov.queue_cap == 16384
        assert ov.shed_protect_priority == 2000
        assert ov.shed_protect_age == 60.0
        assert ov.slo_p99_ms == 250.0
        assert ov.wave_min == 8
        assert ov.wave_increase == 16
        assert ov.wave_decrease == 0.25
        assert ov.escape_rate_threshold == 0.5
        assert ov.escape_min_batch == 4
        assert ov.breaker_threshold == 2
        assert ov.breaker_probe_interval == 1.5
        assert ov.wave_deadline == 30.0

    def test_absent_stanza_is_on_by_default(self):
        """No overload: stanza no longer means unprotected — the policy
        ships enabled with engagement: auto, so the machinery exists but
        only bites when the hysteresis controller engages."""
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
        })
        ov = cfg.overload
        assert ov.enabled
        assert ov.engagement == "auto"
        assert ov.queue_cap > 0
        assert ov.slo_p99_ms > 0
        assert ov.wave_deadline > 0

    def test_engagement_off_disables_everything(self):
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "overload": {"engagement": "off"},
        })
        assert not cfg.overload.enabled

    def test_engagement_knobs_parse(self):
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
            "overload": {
                "engagement": "always",
                "armSamples": 3,
                "engageDwellSeconds": 7.5,
                "coolDwellSeconds": 20,
                "queueGrowthFactor": 4,
            },
        })
        ov = cfg.overload
        assert ov.engagement == "always"
        assert ov.arm_samples == 3
        assert ov.engage_dwell == 7.5
        assert ov.cool_dwell == 20.0
        assert ov.queue_growth_factor == 4.0

    @pytest.mark.parametrize("stanza", [
        {"queueCap": -1},
        {"sloP99Ms": -5},
        {"waveDecrease": 1.5},
        {"waveDecrease": 0},
        {"escapeRateThreshold": 2},
        {"waveMin": 0},
        {"breakerThreshold": 0},
        {"shedProtectAgeSeconds": 0},
        {"nope": 1},
        {"engagement": "sometimes"},
        {"armSamples": 0},
        {"engageDwellSeconds": -1},
        {"queueGrowthFactor": 0},
    ])
    def test_bad_stanza_rejected(self, stanza):
        with pytest.raises(ConfigError):
            load_config({
                "apiVersion": "kubescheduler.config.k8s.io/v1",
                "kind": "KubeSchedulerConfiguration",
                "overload": stanza,
            })


# -- tentpole (ISSUE 20): engagement controller ---------------------------


from kubernetes_tpu.component_base.profiling import SLOTracker  # noqa: E402
from kubernetes_tpu.scheduler.config import BackendPolicy  # noqa: E402
from kubernetes_tpu.scheduler.scheduler import (  # noqa: E402
    _ENGAGEMENT_REASONS, _ENGAGEMENT_STATES, _EngagementController)


def make_controller(clock, **kw):
    kw.setdefault("engagement", "auto")
    kw.setdefault("arm_samples", 2)
    kw.setdefault("engage_dwell", 5.0)
    kw.setdefault("cool_dwell", 10.0)
    policy = OverloadPolicy(**kw)
    slo = SLOTracker(target_ms=policy.slo_p99_ms, objective=0.99,
                     windows=(10.0, 30.0), time_fn=lambda: clock[0])
    return _EngagementController(policy, slo, now_fn=lambda: clock[0])


def burn(eng, clock, n=10):
    """Feed latencies far over target so both burn windows breach."""
    eng.note_latencies([eng.slo.target_s * 4] * n, now=clock[0])


class TestEngagementController:
    def test_starts_disengaged_and_stays_quiescent(self):
        clock = [0.0]
        eng = make_controller(clock)
        assert eng.state == "disengaged" and not eng.engaged
        for _ in range(50):
            clock[0] += 1.0
            assert eng.on_wave(0, 256) == []
        assert eng.state == "disengaged"

    def test_slo_burn_arms_then_engages(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=2)
        burn(eng, clock)
        assert eng.on_wave(0, 256) == [("disengaged", "arming", "slo_burn")]
        assert not eng.engaged  # arming is not engaged
        clock[0] += 1.0
        burn(eng, clock)
        assert eng.on_wave(0, 256) == [("arming", "engaged", "slo_burn")]
        assert eng.engaged

    def test_arm_samples_one_engages_in_a_single_wave(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1)
        burn(eng, clock)
        assert eng.on_wave(0, 256) == [
            ("disengaged", "arming", "slo_burn"),
            ("arming", "engaged", "slo_burn")]

    def test_blip_disarms_without_engaging(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=3)
        burn(eng, clock)
        eng.on_wave(0, 256)
        assert eng.state == "arming"
        # pressure gone before arm_samples confirmed: back to disengaged
        clock[0] += 60.0  # burn samples age out of both windows
        assert eng.on_wave(0, 256) == [("arming", "disengaged", "blip")]

    def test_queue_growth_secondary_trigger(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=2, queue_growth_factor=2.0)
        # no SLO samples at all: backlog over 2x nominal AND growing
        assert eng.on_wave(600, 256) == [
            ("disengaged", "arming", "queue_growth")]
        clock[0] += 1.0
        assert eng.on_wave(700, 256) == [
            ("arming", "engaged", "queue_growth")]

    def test_queue_deep_but_draining_is_not_pressure(self):
        clock = [0.0]
        eng = make_controller(clock)
        eng.on_wave(900, 256)   # growing from 0: pressure
        assert eng.state == "arming"
        clock[0] += 60.0        # pressure samples gone
        eng.on_wave(0, 256)     # blip back down; _last_depth now 0... 
        assert eng.state == "disengaged"
        # re-prime the depth watermark high, then present a DRAINING deep
        # queue: depth over the factor but shrinking wave over wave
        eng._last_depth = 2000
        for depth in (1500, 1200, 900):
            clock[0] += 1.0
            assert eng.on_wave(depth, 256) == []
        assert eng.state == "disengaged"

    def test_engage_dwell_then_cooling_then_cooled(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1,
                              engage_dwell=5.0, cool_dwell=10.0)
        # engage via queue growth (no SLO samples: calm is then purely
        # clock-driven, which is what this test times)
        assert eng.on_wave(600, 256) == [
            ("disengaged", "arming", "queue_growth"),
            ("arming", "engaged", "queue_growth")]
        # calm but inside engage_dwell: still engaged
        clock[0] = 2.0
        assert eng.on_wave(0, 256) == []
        assert eng.state == "engaged"
        # past the dwell since last pressure: cooling (still shielded)
        clock[0] = 6.0
        assert eng.on_wave(0, 256) == [("engaged", "cooling", "calm")]
        assert eng.engaged  # cooling keeps the protections on
        # inside cool_dwell: still cooling
        clock[0] = 15.0
        assert eng.on_wave(0, 256) == []
        assert eng.state == "cooling"
        # cool_dwell of calm: stand down
        clock[0] = 16.5
        assert eng.on_wave(0, 256) == [("cooling", "disengaged", "cooled")]
        assert not eng.engaged

    def test_cooling_reengages_on_pressure(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1, engage_dwell=1.0)
        burn(eng, clock)
        eng.on_wave(0, 256)
        clock[0] += 60.0
        eng.on_wave(0, 256)  # calm past dwell -> cooling
        assert eng.state == "cooling"
        burn(eng, clock)
        assert eng.on_wave(0, 256) == [("cooling", "engaged", "re_pressure")]

    def test_oscillating_pressure_bounded_transitions(self):
        """The flapping-storm guarantee: pressure toggling every wave
        must NOT toggle engagement every wave — after the first engage
        the machine rides engaged/cooling (dwell hysteresis), so the
        transition count stays far below the wave count."""
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1,
                              engage_dwell=5.0, cool_dwell=10.0)
        edges = []
        for i in range(200):
            clock[0] += 0.5
            if i % 2 == 0:
                burn(eng, clock, n=3)
            edges += eng.on_wave(0, 256)
        # 200 waves of 1Hz-flapping load: engage once, never stand down
        assert len(edges) <= 4, edges
        assert eng.engaged

    def test_reconfigure_keeps_state(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1)
        burn(eng, clock)
        eng.on_wave(0, 256)
        assert eng.state == "engaged"
        eng.reconfigure(OverloadPolicy(engagement="auto", slo_p99_ms=500.0))
        assert eng.state == "engaged"          # reload keeps the shield
        assert eng.slo.target_s == pytest.approx(0.5)

    def test_detach_counts_config_edge(self):
        clock = [0.0]
        eng = make_controller(clock, arm_samples=1)
        assert eng.detach() == []              # disengaged: no edge
        burn(eng, clock)
        eng.on_wave(0, 256)
        assert eng.detach() == [("engaged", "disengaged", "config")]

    def test_taxonomy_closed(self):
        """Every emittable edge uses tokens from the pinned taxonomy
        (the README table + ktpu-lint sync rule ride on these)."""
        assert set(_ENGAGEMENT_STATES) == {
            "disengaged", "arming", "engaged", "cooling"}
        assert set(_ENGAGEMENT_REASONS) == {
            "slo_burn", "queue_growth", "blip", "calm", "re_pressure",
            "cooled", "config"}


class TestEngagementE2E:
    def test_default_policy_healthy_run_stays_disengaged(self):
        """The on-by-default acceptance shape: an unconfigured scheduler
        now carries the full overload policy, yet a healthy run never
        engages — no sheds, no wave shrink, every pod binds."""
        cfg = load_config({
            "apiVersion": "kubescheduler.config.k8s.io/v1",
            "kind": "KubeSchedulerConfiguration",
        })
        client, factory, sched = build_harness(_StubRung(),
                                               cfg.overload)
        try:
            assert sched._engagement is not None
            assert sched.overload_engagement == "disengaged"
            client.create(NODES, make_node("ov-0")
                          .capacity(cpu="8", mem="32Gi").build())
            for i in range(12):
                client.create(PODS, make_pod(f"calm{i}")
                              .req(cpu="100m").build())
            sched.run()
            assert wait_for(lambda: all_bound(client), timeout=30)
            sched.expose_metrics()
            prom = sched.metrics.prom
            assert prom.overload_engaged.value() == 0.0
            assert prom.overload_transition_total.values() == {}
            assert sched.queue.drain_shed_total() == {}
            assert sched.overload_engagement == "disengaged"
        finally:
            sched.stop()
            factory.stop()

    def test_engage_edge_enforces_cap_and_sheds_engaged(self):
        """Flip a live scheduler's controller to engaged: the queue cap
        starts biting immediately (backlog over the cap sheds with
        reason 'engaged') and the transition counter carries the edge."""
        policy = OverloadPolicy(queue_cap=4, arm_samples=1)
        client, factory, sched = build_harness(_StubRung(), policy)
        try:
            for i in range(10):
                sched.queue.add(prio_pod(f"q{i}", 0))
            assert sched.queue.stats()["active"] == 10  # disengaged: no cap
            eng = sched._engagement
            # feed breaching latencies on the controller's own (real
            # monotonic) clock, then advance one wave
            eng.note_latencies([eng.slo.target_s * 4] * 10)
            sched._apply_engagement_edges(eng.on_wave(0, 8))
            assert eng.engaged
            st = sched.queue.stats()
            assert st["active"] == 4                    # cap bites now
            sheds = sched.queue.drain_shed_total()
            assert sheds == {("engaged", "best_effort"): 6}
            sched.expose_metrics()
            prom = sched.metrics.prom
            assert prom.overload_engaged.value() == 1.0
            totals = prom.overload_transition_total.values()
            assert totals[("disengaged", "arming", "slo_burn")] == 1.0
            assert totals[("arming", "engaged", "slo_burn")] == 1.0
        finally:
            sched.stop()
            factory.stop()


# -- satellite: SIGHUP reload re-clamps the live wave tuner ----------------


class TestReloadReclampsTuner:
    def _reload(self, sched, batch_size, overload=None):
        cfg = {"apiVersion": "kubescheduler.config.k8s.io/v1",
               "kind": "KubeSchedulerConfiguration",
               "backend": {"kind": "null", "batchSize": batch_size}}
        if overload is not None:
            cfg["overload"] = overload
        return sched.reload_config(cfg)

    def test_shrinking_batch_size_reclamps_ceiling(self):
        """The satellite bug: before the reorder, reload rebuilt the
        tuner from the OLD profile batch size, leaving the AIMD ceiling
        above the new one until restart."""
        policy = OverloadPolicy(engagement="always", slo_p99_ms=100.0)
        client, factory, sched = build_harness(_StubRung(), policy,
                                               batch_size=256)
        sched.backend_policy = BackendPolicy(kind="null")
        try:
            assert sched._wave_tuner.current() == 256
            out = self._reload(sched, 64)
            assert "backend.batchSize" in out["applied"]
            assert sched._wave_tuner.current() <= 64
            assert sched._wave_tuner._cap == 64
            for _ in range(50):  # AIMD growth can never exceed the new cap
                sched._wave_tuner.observe(0.001, 10_000)
            assert sched._wave_tuner.current() == 64
        finally:
            sched.stop()
            factory.stop()

    def test_reload_keeps_ratcheted_wave_position(self):
        """A reload mid-incident must not blow a ratcheted-down wave
        back to the full cap."""
        policy = OverloadPolicy(engagement="always", slo_p99_ms=100.0)
        client, factory, sched = build_harness(_StubRung(), policy,
                                               batch_size=256)
        sched.backend_policy = BackendPolicy(kind="null")
        try:
            for _ in range(3):
                sched._wave_tuner.observe(0.5, 1000)  # breach: halve
            ratcheted = sched._wave_tuner.current()
            assert ratcheted < 256
            self._reload(sched, 256)
            assert sched._wave_tuner.current() == ratcheted
        finally:
            sched.stop()
            factory.stop()

    def test_reload_overload_off_detaches(self):
        policy = OverloadPolicy(engagement="always", slo_p99_ms=100.0)
        client, factory, sched = build_harness(_StubRung(), policy)
        sched.backend_policy = BackendPolicy(kind="null")
        try:
            assert sched._wave_tuner is not None
            self._reload(sched, 8, overload={"engagement": "off"})
            assert sched._wave_tuner is None
            assert sched.overload_engagement == "off"
        finally:
            sched.stop()
            factory.stop()


# -- satellite: monotonic clock contract -----------------------------------


class TestMonotonicClockContract:
    def test_breaker_probe_survives_wall_clock_jump(self, monkeypatch):
        """configure_overload builds the breaker on time.monotonic: an
        NTP step on the wall clock must neither hold the breaker's probe
        window open forever nor fire it early."""
        policy = OverloadPolicy(engagement="always",
                                escape_rate_threshold=0.5,
                                breaker_threshold=1,
                                breaker_probe_interval=30.0)
        client, factory, sched = build_harness(_StubRung(), policy)
        try:
            br = sched._escape_breaker
            assert br._now is time.monotonic
            assert br.record_storm() is True  # opens
            real_time = time.time
            monkeypatch.setattr(time, "time",
                                lambda: real_time() + 3600.0)
            assert not br.probe_due()  # wall jump did not elapse the window
        finally:
            sched.stop()
            factory.stop()

    def test_shed_age_exemption_survives_wall_clock_jump(self, monkeypatch):
        """The queue's shed-age exemption ages pods on the monotonic
        clock: a +1h wall step must not age-exempt a fresh pod (which
        would make the cap unenforceable for the storm's duration)."""
        q = new_queue(cap=1, protect_age=30.0)
        q.set_overload_engaged(True)
        q.add(prio_pod("victim", -1))   # lowest priority: the victim pick
        real_time = time.time
        monkeypatch.setattr(time, "time", lambda: real_time() + 3600.0)
        q.add(prio_pod("fresh", 0))
        # the wall jump must NOT have exempted the victim from shedding
        assert q.stats()["active"] == 1
        assert q.drain_shed_total() == {("admission", "best_effort"): 1}
