"""Pallas claims kernel vs the scalar oracle.

The fused mask+score+argmax tile kernel (ops/pallas_kernels.py) must be
bit-identical to the wave solver's XLA path (models/assign.py) — same
feasibility rules, same LeastAllocated+BalancedAllocation scores, same
tie-break noise.  Runs in interpret mode on CPU (tests/conftest.py).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from kubernetes_tpu.ops.pallas_kernels import (  # noqa: E402
    NEG, TIE_NOISE, claims, prepare_static,
)


def oracle(req, req_nz, active, alloc, used, used_nz, npods, maxpods, smask):
    P, R = req.shape
    N = alloc.shape[0]
    fit = (npods + 1 <= maxpods)[None, :]
    for r in range(R):
        fit = fit & (req[:, r][:, None] <= (alloc[:, r] - used[:, r])[None, :])
    mask = smask & fit & active[:, None]
    utils = []
    for r in range(2):
        a = alloc[:, r][None, :]
        u = used_nz[:, r][None, :] + req_nz[:, r][:, None]
        utils.append(np.where(a > 0, np.minimum(u / np.maximum(a, 1.0), 1.0),
                              1.0))
    ucpu, umem = utils
    score = (2 - ucpu - umem) * 50 + (1 - np.abs(ucpu - umem) * 0.5) * 100
    gp = np.arange(P, dtype=np.float32)[:, None]
    gn = np.arange(N, dtype=np.float32)[None, :]
    h = np.sin(gp * 12.9898 + gn * 78.233, dtype=np.float32) * 43758.5453
    noise = (h - np.floor(h)) * TIE_NOISE
    masked = np.where(mask, (score + noise).astype(np.float32), NEG)
    return np.where(mask.any(1), masked.argmax(1), -1)


def run_kernel(req, req_nz, active, alloc, used, used_nz, npods, maxpods,
               smask):
    static = prepare_static(jnp.asarray(req), jnp.asarray(req_nz),
                            jnp.asarray(alloc), jnp.asarray(maxpods),
                            jnp.asarray(smask))
    idx, best = claims(static, jnp.asarray(active), jnp.asarray(used),
                       jnp.asarray(used_nz), jnp.asarray(npods))
    return np.asarray(idx)


@pytest.mark.parametrize("P,N", [(8, 64), (20, 700), (130, 520)])
def test_matches_oracle(P, N):
    R = 6
    rng = np.random.default_rng(P * 1000 + N)
    req = rng.uniform(0, 4, (P, R)).astype(np.float32)
    req[:, 3:] = 0
    req_nz = req.copy()
    active = rng.random(P) > 0.1
    alloc = rng.uniform(2, 16, (N, R)).astype(np.float32)
    alloc[:, 3:] = 0
    used = rng.uniform(0, 4, (N, R)).astype(np.float32)
    used[:, 3:] = 0
    used_nz = used.copy()
    npods = rng.integers(0, 5, N).astype(np.float32)
    maxpods = np.full(N, 110, np.float32)
    smask = rng.random((P, N)) > 0.25

    args = (req, req_nz, active, alloc, used, used_nz, npods, maxpods, smask)
    got = run_kernel(*args)
    want = oracle(*args)
    np.testing.assert_array_equal(got, want)


def test_no_feasible_node_returns_minus_one():
    P, N, R = 9, 130, 6
    req = np.full((P, R), 100.0, np.float32)  # nothing fits
    alloc = np.ones((N, R), np.float32)
    args = (req, req, np.ones(P, bool), alloc, np.zeros((N, R), np.float32),
            np.zeros((N, R), np.float32), np.zeros(N, np.float32),
            np.full(N, 10, np.float32), np.ones((P, N), bool))
    got = run_kernel(*args)
    assert (got == -1).all()


def test_scalar_resource_gates_fit():
    # pod wants 1 unit of scalar resource r=3; only node 1 has it
    P, N, R = 1, 200, 6
    req = np.zeros((P, R), np.float32)
    req[0, 3] = 1.0
    alloc = np.zeros((N, R), np.float32)
    alloc[:, :2] = 8.0
    alloc[1, 3] = 2.0
    args = (req, req, np.ones(P, bool), alloc, np.zeros((N, R), np.float32),
            np.zeros((N, R), np.float32), np.zeros(N, np.float32),
            np.full(N, 10, np.float32), np.ones((P, N), bool))
    got = run_kernel(*args)
    assert got[0] == 1
