"""Round-5 perf workload configs run end to end at toy scale.

Each workload from perf/config/performance-config.yaml must complete
(barrier_ok) through the REAL pipeline — store -> informers -> queue ->
scheduler -> bind — with counts shrunk so the whole parametrized suite
stays fast on CPU.  The per-pod oracle path is used (tpu=False): these
tests prove the workload DEFINITIONS and harness opcodes
(createNamespaces, skipWaitToCompletion, churn recreate mode), not the
device kernel (bench.py measures that on hardware).

Reference: test/integration/scheduler_perf/scheduler_perf_test.go
(the integration test driver over performance-config.yaml).
"""

from __future__ import annotations

import copy

import pytest

from kubernetes_tpu.perf import load_workloads
from kubernetes_tpu.perf.scheduler_perf import (
    ThroughputCollector, run_workload, setup_cluster,
)

# (workload, node_shrink, pod_shrink): counts divided by the shrink
# factor (min 1 node / a few pods) so ratios that give the workload its
# meaning survive — PreemptionBasic keeps ~4 low-prio pods per node so
# high-priority pods still must evict.
CASES = [
    ("SchedulingSecrets", 100, 100),
    ("SchedulingPodAffinity", 100, 100),
    ("SchedulingPreferredPodAffinity", 100, 100),
    ("SchedulingPreferredPodAntiAffinity", 100, 100),
    ("SchedulingNodeAffinity", 100, 100),
    ("PreferredTopologySpreading", 100, 100),
    ("MixedSchedulingBasePod", 100, 100),
    ("PreemptionBasic", 25, 25),
    ("PreemptionDense", 25, 25),
    ("Unschedulable", 100, 100),
    ("SchedulingWithMixedChurn", 100, 100),
    ("SchedulingRequiredPodAntiAffinityWithNSSelector", 100, 100),
    ("SchedulingPreferredAffinityWithNSSelector", 100, 100),
    ("SchedulingNSSelectorDense", 100, 100),
]


def shrink(cfg: dict, node_div: int, pod_div: int) -> dict:
    cfg = copy.deepcopy(cfg)
    for op in cfg["workloadTemplate"]:
        if op["opcode"] == "createNodes":
            op["count"] = max(2, op["count"] // node_div)
        elif op["opcode"] == "createPods":
            op["count"] = max(4, op["count"] // pod_div)
        elif op["opcode"] == "createNamespaces":
            pass  # namespace counts are semantic, keep them
        elif op["opcode"] == "barrier":
            op["timeout"] = 120.0
        elif op["opcode"] == "churn":
            op["intervalMilliseconds"] = 100
    return cfg


@pytest.mark.parametrize("name,ndiv,pdiv", CASES,
                         ids=[c[0] for c in CASES])
def test_workload_completes(name, ndiv, pdiv):
    cfg = shrink(load_workloads()[name], ndiv, pdiv)
    cluster = setup_cluster(tpu=False)
    collector = ThroughputCollector(cluster.store, interval=0.2)
    try:
        stats = run_workload(cluster, cfg["workloadTemplate"], collector)
        assert stats.get("barrier_ok", False), stats
    finally:
        collector.stop()
        cluster.shutdown()


def test_unschedulable_pods_stay_parked():
    """The skipWaitToCompletion pods must end WITHOUT nodeName while
    every measured pod binds (the workload's entire point)."""
    from kubernetes_tpu.api import meta
    from kubernetes_tpu.client.clientset import PODS
    cfg = shrink(load_workloads()["Unschedulable"], 100, 100)
    cluster = setup_cluster(tpu=False)
    collector = ThroughputCollector(cluster.store, interval=0.2)
    try:
        stats = run_workload(cluster, cfg["workloadTemplate"], collector)
        assert stats.get("barrier_ok", False), stats
        items, _ = cluster.store.list(PODS, None)
        bound = sum(1 for p in items if meta.pod_node_name(p))
        unbound = sum(1 for p in items if not meta.pod_node_name(p))
        skip_count = next(
            op["count"] for op in cfg["workloadTemplate"]
            if op.get("skipWaitToCompletion"))
        assert unbound == skip_count, (bound, unbound)
    finally:
        collector.stop()
        cluster.shutdown()


def test_preemption_evicts_victims():
    """High-priority pods must displace low-priority ones: every
    high-priority pod binds, and at least one low-priority pod was
    evicted (deleted or rescheduled later)."""
    from kubernetes_tpu.api import meta
    from kubernetes_tpu.client.clientset import PODS
    cfg = shrink(load_workloads()["PreemptionBasic"], 25, 25)
    cluster = setup_cluster(tpu=False)
    collector = ThroughputCollector(cluster.store, interval=0.2)
    try:
        stats = run_workload(cluster, cfg["workloadTemplate"], collector)
        assert stats.get("barrier_ok", False), stats
        assert cluster.scheduler.metrics.preemption_attempts > 0
    finally:
        collector.stop()
        cluster.shutdown()


@pytest.mark.parametrize("name", [
    "SchedulingRequiredPodAntiAffinityWithNSSelector",
    "SchedulingPreferredAffinityWithNSSelector",
])
def test_ns_selector_workloads_run_device_path(name):
    """Regression guard: namespaceSelector terms are tensor-encoded
    (resolved against the namespace-label cache), so the two NS-selector
    workloads must report escape_rate == 0.0 on the in-process device
    backend — the oracle fallback must not silently come back."""
    from kubernetes_tpu.ops.flatten import Caps
    from kubernetes_tpu.perf.scheduler_perf import run_named_workload
    cfg = shrink(load_workloads()[name], 100, 100)
    caps = Caps(n_cap=64, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8, c_cap=2, ns_cap=128)
    summary, stats = run_named_workload(cfg, tpu=True, caps=caps,
                                        batch_size=64)
    assert stats.get("barrier_ok"), stats
    assert stats.get("backend_stats", {}).get("pods", 0) > 0, stats
    assert stats.get("escape_rate", 1.0) == 0.0, stats


def test_overload_flood_runs_with_policy_and_chaos():
    """SchedulingOverloadFlood shrunk through the bench --overload
    plumbing: seeded escape-storm chaos + the full overload policy.
    Liveness (barrier_ok) must hold and the protected high-priority
    class (the workload's hipri- pods) must never be shed."""
    from kubernetes_tpu.ops.faults import OverloadSchedule
    from kubernetes_tpu.perf import caps_for_nodes
    from kubernetes_tpu.perf.scheduler_perf import run_named_workload
    from kubernetes_tpu.scheduler.config import OverloadPolicy
    cfg = shrink(load_workloads()["SchedulingOverloadFlood"], 100, 100)
    policy = OverloadPolicy(queue_cap=64, shed_protect_priority=1000,
                            slo_p99_ms=250.0, escape_rate_threshold=0.5,
                            escape_min_batch=8, breaker_threshold=1,
                            breaker_probe_interval=0.05,
                            wave_deadline=60.0)
    chaos = OverloadSchedule(seed=3, all_escape_rate=0.2)
    summary, stats = run_named_workload(
        cfg, tpu=True, caps=caps_for_nodes(20), batch_size=64,
        null_device=True, overload=policy, chaos_schedule=chaos)
    assert stats.get("barrier_ok"), stats
    ov = stats.get("overload")
    assert ov is not None, stats
    assert not any(k.endswith(("/system", "/high")) for k in ov["shed"]), ov
    assert stats.get("chaos_injected", {}).get("all_escape", 0) >= 0


def test_mixed_escapes_reports_nonzero_escape_rate():
    """SchedulingMixedEscapes: the Gt node-affinity pods must escape to
    the per-pod oracle (non-zero escape_rate) AND still schedule onto
    rack>9 nodes only."""
    from kubernetes_tpu.api import meta
    from kubernetes_tpu.client.clientset import PODS
    from kubernetes_tpu.ops.nullbackend import NullBatchBackend  # noqa: F401
    from kubernetes_tpu.perf import caps_for_nodes
    from kubernetes_tpu.perf.scheduler_perf import run_named_workload
    cfg = shrink(load_workloads()["SchedulingMixedEscapes"], 10, 20)
    summary, stats = run_named_workload(
        cfg, tpu=True, caps=caps_for_nodes(500), batch_size=512,
        null_device=True)
    assert stats.get("barrier_ok"), stats
    assert stats.get("escape_rate", 0) > 0


def test_flight_delay_backend_pins_wave_wall_and_credits_overlap():
    """FlightDelayBackend (bench --pipeline-ab's off-host-device arm):
    the flight clock starts at DISPATCH, so host work done between
    dispatch and resolve is credited against the flight — the property
    that lets the A/B measure pipeline overlap on a box whose
    CPU-simulated device shares cores with the host."""
    import time

    from kubernetes_tpu.ops.nullbackend import FlightDelayBackend

    class _Stub:
        supports_pipelining = True
        stats = {"batches": 0}

        def dispatch(self, pods, snapshot):
            self.stats["batches"] += 1
            return lambda: ["ok"] * len(pods)

        def warmup(self):
            self.warmed = True

    stub = _Stub()
    fb = FlightDelayBackend(stub, flight_s=0.2)
    # attribute forwarding (scheduler reads these off the backend)
    assert fb.supports_pipelining is True
    fb.warmup()
    assert stub.warmed

    # cold resolve pays the full flight
    t0 = time.monotonic()
    resolve = fb.dispatch([1, 2], None)
    out = resolve()
    full = time.monotonic() - t0
    assert out == ["ok", "ok"]
    assert full >= 0.2

    # host work between dispatch and resolve is credited: sleeping
    # 150ms of a 200ms flight leaves <~50ms of blocking in resolve
    resolve = fb.dispatch([1], None)
    time.sleep(0.15)
    t0 = time.monotonic()
    resolve()
    blocked = time.monotonic() - t0
    assert blocked < 0.15, blocked

    # non-callable dispatch returns (flush sentinel / inline results)
    # pass through untouched
    class _Inline:
        def dispatch(self, pods, snapshot):
            return [("n0", None)]

    assert FlightDelayBackend(_Inline(), 0.2).dispatch([1], None) == [
        ("n0", None)]
