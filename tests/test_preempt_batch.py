"""Oracle parity for the batched device-side DryRunPreemption.

The tentpole acceptance: for every pod the device keeps (no escape),
ops/backend.preempt_batch must return BIT-IDENTICAL answers to the host
Evaluator run SEQUENTIALLY over the wave — pod by pod along the wave's
finalization order (backend.last_wave_order), folding each winner's
nomination before the next pod, exactly as a one-pod-at-a-time
scheduler would.  That covers the selected node (including
pickOneNodeForPreemption tie-breaks), the exact victim set (reprieve
semantics), the PDB violation count, AND the wave's conflict
resolution (two winners may legally share one node's capacity).
Randomized clusters drive the comparison; a seeded failure reproduces
exactly.

Also covers the grpc/http seam: RemoteTPUBatchBackend ships the victim
tensors inside /static and the dry run via /preempt, so the remote
answers must match the in-process backend bit-for-bit, including after
a worker kill + resync.
"""

import random

import pytest

from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import PDBS
from kubernetes_tpu.ops.backend import TPUBatchBackend
from kubernetes_tpu.ops.flatten import Caps
from kubernetes_tpu.scheduler import new_default_framework
from kubernetes_tpu.scheduler.cache import Cache, Snapshot
from kubernetes_tpu.scheduler.framework import CycleState
from kubernetes_tpu.scheduler.preemption import Evaluator
from kubernetes_tpu.scheduler.types import PodInfo
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


def small_caps():
    return Caps(n_cap=16, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                s_cap=2, sg_cap=8, asg_cap=8, v_cap=8)


def make_env():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    fw = new_default_framework(client, factory)
    return client, fw


def snapshot_from(nodes, bound_pods=()):
    cache = Cache()
    for n in nodes:
        cache.add_node(n)
    for p in bound_pods:
        cache.add_pod(p)
    return cache.update_snapshot(Snapshot())


def oracle(fw, client, snapshot, pod_info):
    """The per-pod reference answer: full host DryRunPreemption +
    SelectCandidate, no eviction side effects."""
    ev = Evaluator(fw, client)
    cands = ev.find_candidates(CycleState(), pod_info, {}, snapshot)
    if not cands:
        return None
    best = ev.select_candidate(cands)
    return (best.node_name, sorted(v.key for v in best.victims),
            best.num_pdb_violations)


def sequential_oracle(fw, client, snapshot, pod_infos, order,
                      nominated=()):
    """The wave's reference answers: the per-pod Evaluator run pod by
    pod along `order` (the wave's finalization order), each winner's
    nomination folded before the next pod — what a sequential
    scheduler would have decided."""
    noms = list(nominated)

    class _Nom:
        def nominated_pods_for_node(self, name):
            return [pi for pi, n in noms if n == name]

    fw.handle.nominator = _Nom()
    want: list = [None] * len(pod_infos)
    for i in order:
        r = oracle(fw, client, snapshot, pod_infos[i])
        want[i] = r
        if r is not None:
            noms.append((pod_infos[i], r[0]))
    return want


def device(backend, snapshot, pod_infos, nominated=()):
    node_ord_of = {ni.name: i for i, ni in enumerate(snapshot.list())}
    res, esc = backend.preempt_batch(pod_infos, node_ord_of, nominated)
    out = []
    for r in res:
        out.append(None if r is None
                   else (r[0], sorted(r[1]), r[2]))
    return out, esc


def synced_backend(snapshot, caps=None):
    backend = TPUBatchBackend(caps or small_caps(), batch_size=8)
    backend.assign([], snapshot)
    return backend


class TestOracleParityRandomized:
    """Seeded random clusters: device == Evaluator, bit for bit."""

    @pytest.mark.parametrize("seed", range(6))
    def test_victim_sets_and_tiebreaks_match(self, seed):
        rng = random.Random(seed)
        client, fw = make_env()
        n_nodes = rng.randint(3, 10)
        nodes = [make_node(f"n{i}")
                 .capacity(cpu=str(rng.choice([1, 2, 4])), mem="32Gi")
                 .build() for i in range(n_nodes)]
        victims = []
        for i in range(rng.randint(4, 24)):
            victims.append(
                make_pod(f"v{i}").priority(rng.randint(0, 4))
                .req(cpu=f"{rng.choice([100, 200, 400, 800])}m")
                .node(f"n{rng.randrange(n_nodes)}").build())
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        preemptors = [
            PodInfo(make_pod(f"p{j}").priority(rng.choice([5, 10, 20]))
                    .req(cpu=f"{rng.choice([500, 1000, 2000, 3500])}m")
                    .build())
            for j in range(rng.randint(2, 8))]
        got, esc = device(backend, snap, preemptors)
        assert esc == {}
        order = backend.last_wave_order
        assert sorted(order) == list(range(len(preemptors)))
        want = sequential_oracle(fw, client, snap, preemptors, order)
        assert got == want

    @pytest.mark.parametrize("seed", range(3))
    def test_parity_with_nominated_claims(self, seed):
        """Nominated >=-priority pods claim capacity on the device
        exactly as RunFilterPluginsWithNominatedPods does on the host."""
        rng = random.Random(100 + seed)
        client, fw = make_env()
        nodes = [make_node(f"n{i}").capacity(cpu="2", mem="8Gi").build()
                 for i in range(4)]
        victims = [make_pod(f"v{i}").priority(1)
                   .req(cpu="600m").node(f"n{i % 4}").build()
                   for i in range(8)]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        nom = PodInfo(make_pod("nom").priority(50).req(cpu="1500m").build())
        nominated = [(nom, f"n{rng.randrange(4)}")]
        preemptors = [PodInfo(make_pod(f"p{j}").priority(10)
                              .req(cpu="1500m").build())
                      for j in range(3)]
        got, esc = device(backend, snap, preemptors, nominated)
        assert esc == {}
        want = sequential_oracle(fw, client, snap, preemptors,
                                 backend.last_wave_order, nominated)
        assert got == want


class TestOracleParityTargeted:
    def test_taints_gate_candidates_identically(self):
        client, fw = make_env()
        nodes = [
            make_node("clean").capacity(cpu="1", mem="4Gi").build(),
            make_node("tainted").capacity(cpu="1", mem="4Gi")
            .taint("dedicated", "gpu", "NoSchedule").build()]
        victims = [make_pod("vc").priority(1).req(cpu="800m")
                   .node("clean").build(),
                   make_pod("vt").priority(1).req(cpu="800m")
                   .node("tainted").build()]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        intolerant = PodInfo(make_pod("p0").priority(10)
                             .req(cpu="800m").build())
        tolerant_pod = (make_pod("p1").priority(10).req(cpu="800m")
                        .toleration("dedicated", "gpu", "NoSchedule")
                        .build())
        tolerant = PodInfo(tolerant_pod)
        got, esc = device(backend, snap, [intolerant, tolerant])
        assert esc == {}
        want = sequential_oracle(fw, client, snap,
                                 [intolerant, tolerant],
                                 backend.last_wave_order)
        assert got == want
        assert got[0][0] == "clean"  # intolerant pod never picks tainted
        # "clean" is claimed and provably closed (1-cpu node): the
        # tolerant pod's wave answer lands on the tainted node
        assert got[1][0] == "tainted"

    def test_pdb_violations_counted_identically(self):
        client, fw = make_env()
        pdb = {"metadata": {"name": "db-pdb", "namespace": "default"},
               "spec": {"selector": {"matchLabels": {"app": "db"}}},
               "status": {"disruptionsAllowed": 0}}
        client.create(PDBS, pdb)
        nodes = [make_node("a").capacity(cpu="1", mem="4Gi").build(),
                 make_node("b").capacity(cpu="1", mem="4Gi").build()]
        victims = [
            make_pod("covered").priority(1).labels(app="db")
            .req(cpu="800m").node("a").build(),
            make_pod("free").priority(1).labels(app="web")
            .req(cpu="800m").node("b").build()]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        backend.note_pdb_event("ADDED", pdb)
        pre = PodInfo(make_pod("p").priority(10).req(cpu="800m").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {}
        want = [oracle(fw, client, snap, pre)]
        assert got == want
        # fewest-PDB-violations dominates: node b (uncovered victim) wins
        assert got[0][0] == "b"
        assert got[0][2] == 0

    def test_reprieve_spares_what_the_oracle_spares(self):
        """Minimal victim prefix: removing both victims fits, but the
        greedy re-add (highest priority first) must spare one — same one
        the Evaluator spares."""
        client, fw = make_env()
        nodes = [make_node("n").capacity(cpu="2", mem="8Gi").build()]
        victims = [make_pod("hi-v").priority(3).req(cpu="700m")
                   .node("n").build(),
                   make_pod("lo-v").priority(1).req(cpu="700m")
                   .node("n").build()]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        pre = PodInfo(make_pod("p").priority(10).req(cpu="700m").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {}
        want = [oracle(fw, client, snap, pre)]
        assert got == want
        # one victim suffices; the higher-priority resident is reprieved
        assert got[0][1] == ["default/lo-v"]

    def test_zero_victim_nodes_are_not_candidates(self):
        client, fw = make_env()
        nodes = [make_node("empty").capacity(cpu="4", mem="8Gi").build()]
        snap = snapshot_from(nodes)
        backend = synced_backend(snap)
        pre = PodInfo(make_pod("p").priority(10).req(cpu="1").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {}
        assert got == [None]  # fits without victims -> plain FitError
        assert oracle(fw, client, snap, pre) is None


class TestEscapeGates:
    def test_victim_overflow_escapes_with_reason(self):
        """More residents than v_cap on a reachable node: the device
        refuses to answer from a truncated victim set."""
        caps = Caps(n_cap=16, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8,
                    s_cap=2, sg_cap=8, asg_cap=8, v_cap=2)
        nodes = [make_node("full").capacity(cpu="2", mem="8Gi").build()]
        victims = [make_pod(f"v{i}").priority(1).req(cpu="300m")
                   .node("full").build() for i in range(4)]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap, caps)
        pre = PodInfo(make_pod("p").priority(10).req(cpu="1500m").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {0: "victim_overflow"}
        assert got == [None]

    def test_foreign_namespace_pdb_escapes(self):
        """A blocking PDB outside the preemptor's namespace: the device
        bit covers it, the Evaluator's namespace-scoped listing does not
        — the pod must re-prove host-side instead of diverging."""
        nodes = [make_node("a").capacity(cpu="1", mem="4Gi").build()]
        victims = [make_pod("v").priority(1).req(cpu="800m")
                   .node("a").build()]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        backend.note_pdb_event("ADDED", {
            "metadata": {"name": "other", "namespace": "kube-system"},
            "spec": {"selector": {"matchLabels": {"app": "x"}}},
            "status": {"disruptionsAllowed": 0}})
        pre = PodInfo(make_pod("p").priority(10).req(cpu="800m").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {0: "pdb_scope"}

    def test_pdb_with_budget_does_not_gate(self):
        """disruptionsAllowed > 0 is not blocking: no escape, and the
        victim counts zero violations on both halves."""
        client, fw = make_env()
        pdb = {"metadata": {"name": "roomy", "namespace": "default"},
               "spec": {"selector": {"matchLabels": {"app": "db"}}},
               "status": {"disruptionsAllowed": 2}}
        client.create(PDBS, pdb)
        nodes = [make_node("a").capacity(cpu="1", mem="4Gi").build()]
        victims = [make_pod("v").priority(1).labels(app="db")
                   .req(cpu="800m").node("a").build()]
        snap = snapshot_from(nodes, victims)
        backend = synced_backend(snap)
        backend.note_pdb_event("ADDED", pdb)
        pre = PodInfo(make_pod("p").priority(10).req(cpu="800m").build())
        got, esc = device(backend, snap, [pre])
        assert esc == {}
        assert got == [oracle(fw, client, snap, pre)]
        assert got[0][2] == 0


@pytest.fixture(params=["http", "grpc"])
def worker(request):
    from kubernetes_tpu.ops.remote import DeviceWorker, GrpcDeviceWorker
    w = (GrpcDeviceWorker() if request.param == "grpc"
         else DeviceWorker()).start()
    yield w
    w.stop()


class TestRemoteSeamParity:
    """The dry run over the wire: victim tensors ride /static, the
    kernel runs worker-side via /preempt, answers bit-identical."""

    def _cluster(self):
        nodes = [make_node(f"n{i}").capacity(cpu="2", mem="8Gi").build()
                 for i in range(4)]
        victims = [make_pod(f"v{i}").priority(1 + i % 3)
                   .req(cpu=f"{400 + 200 * (i % 3)}m")
                   .node(f"n{i % 4}").build() for i in range(10)]
        return snapshot_from(nodes, victims)

    def test_remote_matches_local_bit_identical(self, worker):
        from kubernetes_tpu.ops.remote import RemoteTPUBatchBackend
        snap = self._cluster()
        local = synced_backend(snap)
        remote = RemoteTPUBatchBackend(worker.url, small_caps(),
                                       batch_size=8)
        remote.assign([], snap)
        preemptors = [PodInfo(make_pod(f"p{j}").priority(10)
                              .req(cpu="1800m").build())
                      for j in range(4)]
        got_l, esc_l = device(local, snap, preemptors)
        got_r, esc_r = device(remote, snap, preemptors)
        assert esc_l == esc_r == {}
        assert got_l == got_r

    def test_kill_resync_replays_victim_tensors(self, worker):
        """Chaos acceptance: a worker restart between preemption waves
        loses the resident victim tensors; the client's resync replays
        the victim-carrying /static checkpoint and the post-resync
        answers stay bit-identical."""
        from kubernetes_tpu.ops.remote import RemoteTPUBatchBackend
        snap = self._cluster()
        local = synced_backend(snap)
        remote = RemoteTPUBatchBackend(worker.url, small_caps(),
                                       batch_size=8)
        remote.assign([], snap)
        preemptors = [PodInfo(make_pod(f"p{j}").priority(10)
                              .req(cpu="1800m").build())
                      for j in range(4)]
        first, _ = device(remote, snap, preemptors)
        worker.simulate_restart()
        second, esc = device(remote, snap, preemptors)
        assert esc == {}
        assert remote.seam_stats["resyncs"] >= 1
        assert second == first
        want, _ = device(local, snap, preemptors)
        assert second == want


class TestCandidateRanking:
    def test_headroom_normalized_per_resource(self):
        """Heterogeneous-memory fleets: the headroom tiebreak must be the
        per-resource free FRACTION, not raw units.  Node A frees 256Gi of
        memory but needs TWO victims; node B frees 1Gi with ONE victim.
        Fewest-victims must win — under raw-unit headroom, 1e-9 * free
        memory BYTES (~274 for 256Gi) dwarfed both the victim-count term
        and the decorrelation noise, so big-memory nodes always won."""
        import numpy as np

        from kubernetes_tpu.models.preempt import preempt_candidates

        GI = float(1 << 30)
        alloc = np.array([[64.0, 512 * GI],    # node A
                          [64.0, 64 * GI]],    # node B
                         np.float32)
        used = alloc.copy()                    # both full pre-reclaim
        reclaim = np.array([[[2.0, 256 * GI],  # A: two victims, huge mem
                             [2.0, 1 * GI]]],  # B: one victim, small mem
                           np.float32)         # [G=1, N=2, R=2]
        reclaim_np = np.array([[2.0, 1.0]], np.float32)
        rows, count = preempt_candidates(
            alloc, used, np.array([5.0, 5.0], np.float32),
            np.array([10.0, 10.0], np.float32), np.array([True, True]),
            reclaim, reclaim_np, np.array([0], np.int32),
            np.array([[1.0, GI / 2]], np.float32), np.array([True]), k=2)
        assert count[0] == 2                   # both nodes feasible
        assert rows[0, 0] == 1                 # fewest victims first: B
