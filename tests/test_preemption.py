"""Preemption tests (reference: defaultpreemption/default_preemption_test.go
+ test/integration/scheduler preemption suites, reduced)."""

import time

import pytest

from kubernetes_tpu.api import meta
from kubernetes_tpu.client import LocalClient, SharedInformerFactory
from kubernetes_tpu.client.clientset import NODES, PDBS, PODS
from kubernetes_tpu.scheduler import new_scheduler
from kubernetes_tpu.store import kv
from kubernetes_tpu.testing import make_node, make_pod


@pytest.fixture
def cluster():
    store = kv.MemoryStore()
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    sched = new_scheduler(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    yield store, client, sched
    sched.stop()
    factory.stop()


def wait_for(predicate, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def node_of(client, name):
    try:
        return meta.pod_node_name(client.get(PODS, "default", name)) or None
    except kv.NotFoundError:
        return None


class TestPreemption:
    def test_high_priority_preempts_low(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS, make_pod("low").priority(1).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "low") == "n1")
        client.create(PODS, make_pod("high").priority(100).req(cpu="800m").build())
        # low gets evicted, high lands on n1
        assert wait_for(lambda: node_of(client, "high") == "n1", timeout=20)
        assert node_of(client, "low") is None

    def test_no_preemption_of_equal_priority(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS, make_pod("a").priority(50).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "a") == "n1")
        client.create(PODS, make_pod("b").priority(50).req(cpu="800m").build())
        time.sleep(0.5)
        assert node_of(client, "a") == "n1"   # not evicted
        assert node_of(client, "b") is None

    def test_minimal_victim_set(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="2", mem="4Gi").build())
        client.create(PODS, make_pod("small").priority(1).req(cpu="500m").build())
        client.create(PODS, make_pod("big").priority(1).req(cpu="1200m").build())
        assert wait_for(lambda: node_of(client, "small") == "n1"
                        and node_of(client, "big") == "n1")
        # needs 1 cpu; evicting just "big" suffices (reprieve spares "small")
        client.create(PODS, make_pod("high").priority(100).req(cpu="1").build())
        assert wait_for(lambda: node_of(client, "high") == "n1", timeout=20)
        assert node_of(client, "small") == "n1"
        assert node_of(client, "big") is None

    def test_preemption_policy_never(self, cluster):
        store, client, sched = cluster
        client.create(NODES, make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(PODS, make_pod("low").priority(1).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "low") == "n1")
        p = make_pod("polite").priority(100).req(cpu="800m").build()
        p["spec"]["preemptionPolicy"] = "Never"
        client.create(PODS, p)
        time.sleep(0.5)
        assert node_of(client, "low") == "n1"
        assert node_of(client, "polite") is None

    def test_pdb_respected_in_candidate_ranking(self, cluster):
        store, client, sched = cluster
        # two nodes, each with one victim; n1's victim is PDB-protected
        client.create(NODES, make_node("n1").capacity(cpu="1", mem="2Gi").build())
        client.create(NODES, make_node("n2").capacity(cpu="1", mem="2Gi").build())
        v1 = make_pod("v1").priority(1).req(cpu="800m").labels(app="guarded").build()
        v1["spec"]["nodeName"] = "n1"
        client.create(PODS, v1)
        v2 = make_pod("v2").priority(1).req(cpu="800m").labels(app="free").build()
        v2["spec"]["nodeName"] = "n2"
        client.create(PODS, v2)
        pdb = meta.new_object("PodDisruptionBudget", "guard", "default")
        pdb["spec"] = {"selector": {"matchLabels": {"app": "guarded"}}}
        pdb["status"] = {"disruptionsAllowed": 0}
        client.create(PDBS, pdb)
        time.sleep(0.2)
        client.create(PODS, make_pod("high").priority(100).req(cpu="800m").build())
        assert wait_for(lambda: node_of(client, "high") == "n2", timeout=20)
        assert node_of(client, "v1") == "n1"   # PDB-protected victim spared
        assert node_of(client, "v2") is None
