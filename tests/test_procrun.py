"""Process-true scale-out: the procrun supervisor drives a real
apiserver process plus N scheduler processes wired only over HTTP.

These are the cross-PROCESS versions of test_scaleout.py's chaos layer:
the shared interpreter is gone, so every property must hold across
actual OS process boundaries — exactly-once binding proved by a
store-watch ledger over the wire, crash->failover driven by SIGKILL (not
coordinator.retire()), and graceful drain as a SIGTERM/exit-code
contract.  Reference analog: test/integration/scheduler runs the real
binaries against a live apiserver for the same reason.

Every test takes proc_reaper (conftest): registered clusters are
force-reaped on teardown and a watchdog SIGKILLs the children if the
test wedges, so a hung child can never hold tier-1 hostage.
"""

import time

import pytest

from kubernetes_tpu.client.clientset import NODES, PODS
from kubernetes_tpu.component_base.profiling import federate_texts
from kubernetes_tpu.ops.faults import (
    KILL_INSTANCE, ProcessChurner, ScaleOutSchedule)
from kubernetes_tpu.scheduler.procrun import ProcCluster, WireBindLedger
from kubernetes_tpu.testing import make_node, make_pod

pytestmark = pytest.mark.proc


def wait_for(pred, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


def fill_cluster(admin, nodes: int):
    for i in range(nodes):
        admin.create(NODES, make_node(f"n{i}")
                     .capacity(cpu="16", mem="64Gi", pods=110).build())


def submit_pods(admin, count: int, offset: int = 0):
    for i in range(offset, offset + count):
        admin.create(PODS, make_pod(f"p{i}")
                     .req(cpu="100m", mem="128Mi").build())


class TestProcessTopology:
    def test_two_process_exactly_once_and_drain(self, proc_reaper):
        """The tier-1 keeper: 2 scheduler processes over one wire
        apiserver bind every pod exactly once (cross-process BindLedger:
        zero double-binds, zero lost pods), per-instance /metrics
        federate, and SIGTERM drains both children to exit code 0."""
        cluster = ProcCluster(2, nodes=16)
        proc_reaper(cluster)
        cluster.start()
        assert cluster.live_indices() == [0, 1]
        admin = cluster.admin_client()
        fill_cluster(admin, 16)
        ledger = WireBindLedger(admin)
        submit_pods(admin, 80)

        assert wait_for(lambda: ledger.bound_total() >= 80), \
            f"only {ledger.bound_total()}/80 pods bound; " \
            f"live={cluster.live_indices()}"
        ledger.assert_no_double_binds()
        assert ledger.bound_total() == 80  # zero lost pods
        ledger.stop()

        # PR-8 federation over the true cross-process path: one /metrics
        # pull per child, merged into a single view
        texts = cluster.metrics_texts()
        assert len(texts) == 2
        fed = federate_texts(texts)
        assert any(name.startswith("scheduler_") for name in fed), \
            f"no scheduler metrics federated: {sorted(fed)[:5]}"

        # graceful drain contract: SIGTERM -> retire lease -> flush ->
        # exit 0 (a non-zero code means the drain path raised)
        assert cluster.drain(0) == 0
        assert cluster.drain(1) == 0

    def test_crash_failover_under_seeded_churn(self, proc_reaper):
        """SIGKILL one instance mid-stream via the seeded churn schedule
        (the process-true KILL_INSTANCE): the victim's lease lapses, the
        survivor absorbs its ring slices, and every pod still lands
        exactly once."""
        cluster = ProcCluster(2, nodes=8,
                              lease_duration=1.0, renew_interval=0.2)
        proc_reaper(cluster)
        cluster.start()
        admin = cluster.admin_client()
        fill_cluster(admin, 8)
        ledger = WireBindLedger(admin)

        submit_pods(admin, 20)
        assert wait_for(lambda: ledger.bound_total() >= 10)

        churner = ProcessChurner(
            cluster,
            ScaleOutSchedule(seed=7, instance_count=2,
                             script={0: (KILL_INSTANCE, 0)}),
            min_live=1)
        assert churner.step() == (KILL_INSTANCE, 0)
        assert not cluster.alive(0) and cluster.alive(1)
        assert churner.injected[KILL_INSTANCE] == 1

        # pods submitted AFTER the crash prove the survivor absorbed the
        # dead instance's partition, not just finished its own backlog
        submit_pods(admin, 20, offset=20)
        assert wait_for(lambda: ledger.bound_total() >= 40), \
            f"only {ledger.bound_total()}/40 bound after crash"
        ledger.assert_no_double_binds()
        assert ledger.bound_total() == 40
        ledger.stop()
