"""Continuous performance observatory (component_base/profiling.py and
its wiring): HLO collective census, runtime-vs-tool parity, host
profiler lifecycle + pinned overhead bound, /debug/profile endpoints,
SLO burn-rate tracker, cross-process metrics federation under seeded
instance churn, and the 0.010 SLO-boundary latency bucket."""

import threading
import time
import urllib.request

import pytest

from kubernetes_tpu.component_base import metrics as cbm
from kubernetes_tpu.component_base import profiling
from kubernetes_tpu.component_base.profiling import (
    HostProfiler,
    SLOTracker,
    census_from_hlo,
    classify_stage,
    collective_bytes_by_op,
    federate,
    federate_texts,
    parse_prometheus_text,
    shape_bytes,
)
from kubernetes_tpu.ops import faults
from kubernetes_tpu.ops.flatten import Caps
from kubernetes_tpu.perf import run_named_workload
from kubernetes_tpu.scheduler.config import (
    ConfigError,
    ProfilingPolicy,
    _parse_profiling,
)

# Small caps: fast compiles / cheap host tensors (test_scheduler_perf).
CAPS = Caps(n_cap=64, l_cap=64, kl_cap=32, t_cap=8, pt_cap=8, s_cap=2,
            sg_cap=8, asg_cap=8)


# -- HLO collective census core ---------------------------------------------

# Hand-built optimized-HLO module: an all-reduce and an async
# reduce-scatter pair inside the while body (per-wave), an all-gather in
# ENTRY (per-call).  The -done op must NOT be counted (its -start is).
SYNTH_HLO = """\
HloModule synthetic

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

%wave_body (p: (s32[], f32[8,4])) -> (s32[], f32[8,4]) {
  %p = (s32[], f32[8,4]) parameter(0)
  %i = s32[] get-tuple-element((s32[], f32[8,4]) %p), index=0
  %x = f32[8,4] get-tuple-element((s32[], f32[8,4]) %p), index=1
  %ar = f32[8,4] all-reduce(f32[8,4] %x), to_apply=%add
  %rs = (f32[16,4], f32[2,4]) reduce-scatter-start(f32[16,4] %y), dimensions={0}, to_apply=%add
  %rsd = f32[2,4] reduce-scatter-done((f32[16,4], f32[2,4]) %rs)
  ROOT %t = (s32[], f32[8,4]) tuple(s32[] %i, f32[8,4] %ar)
}

%wave_cond (p: (s32[], f32[8,4])) -> pred[] {
  %p = (s32[], f32[8,4]) parameter(0)
  ROOT %lt = pred[] compare(s32[] %i, s32[] %n), direction=LT
}

ENTRY %main (z: f32[8,4]) -> f32[32,4] {
  %z = f32[8,4] parameter(0)
  %w = (s32[], f32[8,4]) while((s32[], f32[8,4]) %init), condition=%wave_cond, body=%wave_body
  ROOT %ag = f32[32,4] all-gather(f32[8,4] %z), dimensions={0}
}
"""


class TestCensusFromHLO:
    def test_shape_bytes(self):
        assert shape_bytes("f32[8,4]") == 128
        assert shape_bytes("bf16[16]") == 32
        assert shape_bytes("pred[]") == 1          # scalar: 1 elem x 1 byte
        assert shape_bytes("(f32[2,2], s32[3])") == 16 + 12

    def test_while_body_and_async_start(self):
        rec = census_from_hlo(SYNTH_HLO)
        cols = rec["collectives"]
        ar = cols["all-reduce f32[8,4]"]
        assert (ar["count"], ar["bytes"], ar["per_wave"]) == (1, 128, True)
        # async start: bytes are the RESULT element (last tuple shape)
        rs = cols["reduce-scatter (f32[16,4], f32[2,4])"]
        assert (rs["count"], rs["bytes"], rs["per_wave"]) == (1, 32, True)
        ag = cols["all-gather f32[32,4]"]
        assert (ag["count"], ag["bytes"], ag["per_wave"]) == (1, 512, False)
        # the reduce-scatter-done op did not produce a fourth entry
        assert len(cols) == 3
        assert rec["per_wave_bytes"] == 128 + 32
        assert rec["per_call_bytes"] == 512

    def test_collective_bytes_by_op(self):
        per_wave, per_call = collective_bytes_by_op(
            census_from_hlo(SYNTH_HLO))
        assert per_wave == {"all-reduce": 128, "reduce-scatter": 32}
        assert per_call == {"all-gather": 512}


# -- runtime census vs offline tool (bit-for-bit) ----------------------------

class TestCensusParity:
    def test_single_chip_census_deterministic(self):
        """TPUBatchBackend.device_census: structure + determinism (two
        lowerings of the same step yield the identical record)."""
        from kubernetes_tpu.ops.backend import TPUBatchBackend

        backend = TPUBatchBackend(CAPS, batch_size=16)
        a = backend.device_census(variants=("plain",))
        b = backend.device_census(variants=("plain",))
        assert a == b
        rec = a["plain"]
        assert set(rec) >= {"collectives", "per_call_bytes",
                            "per_wave_bytes", "cost"}
        # single chip: no ICI collectives, but real XLA cost numbers
        assert rec["per_wave_bytes"] == 0 and rec["per_call_bytes"] == 0
        assert rec["cost"].get("flops", 0) > 0

    def test_sharded_census_matches_tool(self):
        """The acceptance pin: the RUNNING backend's census equals
        tools/collective_census.py bit-for-bit at the same shapes on the
        8-way virtual mesh (same fn builder, same abstract inputs, same
        HLO walk)."""
        import jax

        if not hasattr(jax, "shard_map"):
            try:  # shard_map_compat's fallback arm (mesh.py)
                from jax.experimental.shard_map import shard_map  # noqa: F401
            except ImportError:
                pytest.skip("no shard_map entry point on this toolchain")
        from kubernetes_tpu.parallel.backend import ShardedTPUBatchBackend
        from kubernetes_tpu.parallel.census import (
            round_caps_to_mesh,
            sharded_census,
        )
        from kubernetes_tpu.perf import caps_for_nodes

        nodes, batch = 256, 32
        tool = sharded_census(nodes, batch, "full")
        caps = round_caps_to_mesh(caps_for_nodes(nodes), len(jax.devices()))
        backend = ShardedTPUBatchBackend(caps, batch_size=batch)
        runtime = backend.device_census(variants=("full",))["full"]
        for key in ("collectives", "per_wave_bytes", "per_call_bytes",
                    "cost"):
            assert runtime[key] == tool[key], key
        # and the gauges derived from both agree
        assert collective_bytes_by_op(runtime) == \
            collective_bytes_by_op(tool)

    def test_sharded_census_reduce_scatter_replaces_all_reduce(self):
        """The headline byte win, pinned in-band: the conflict matrices
        travel as per-wave reduce-scatter slabs ([P/S,P] per shard), and
        no [P,P]-scale all-reduce remains anywhere in the wave loop."""
        import jax

        from kubernetes_tpu.parallel.census import sharded_census

        nodes, batch = 256, 32
        rec = sharded_census(nodes, batch, "full")
        cols = rec["collectives"]
        rs = [v for v in cols.values()
              if v["op"] == "reduce-scatter" and v["per_wave"]]
        assert rs, f"no per-wave reduce-scatter in {sorted(cols)}"
        # the slab is the full matrix divided by the shard count
        full = batch * batch * 4                       # s32[P,P]
        slab = full // len(jax.devices())              # s32[P/S,P]
        assert any(v["bytes"] == slab for v in rs), sorted(cols)
        # and no wave-loop all-reduce at [P,P] scale survives
        big_ar = [k for k, v in cols.items()
                  if v["op"] == "all-reduce" and v["per_wave"]
                  and v["bytes"] >= full]
        assert not big_ar, big_ar
        # the cut on the conflict matrices alone is the shard count (8x
        # on the virtual mesh), comfortably over the 4x acceptance floor
        assert full // slab >= 4


# -- host sampling profiler --------------------------------------------------

def _sampler_threads():
    return [t for t in threading.enumerate()
            if t.name == HostProfiler.THREAD_NAME]


class TestHostProfiler:
    def test_start_stop_leaves_no_sampler_thread(self):
        prof = HostProfiler(interval=0.001)
        prof.start()
        prof.start()                      # idempotent
        assert prof.running
        assert len(_sampler_threads()) == 1
        time.sleep(0.05)
        assert prof.stop()
        assert prof.stop()                # idempotent
        assert not prof.running
        assert not _sampler_threads()
        assert prof.samples_total() > 0

    def test_collapsed_output_parses(self):
        prof = HostProfiler(interval=0.001)
        prof.start()
        time.sleep(0.05)
        prof.stop()
        text = prof.collapsed()
        lines = [ln for ln in text.splitlines() if ln]
        assert lines
        for ln in lines:
            stack, count = ln.rsplit(" ", 1)
            assert int(count) > 0
            assert stack                     # root-first frames, ; joined

    def test_bounded_stacks_overflow_to_other(self):
        prof = HostProfiler(interval=0.001, max_stacks=1)
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(100))

        t = threading.Thread(target=spin, name="spin-worker", daemon=True)
        t.start()
        prof.start()
        time.sleep(0.08)
        prof.stop()
        stop.set()
        t.join()
        with prof._lock:
            keys = list(prof._stacks)
        distinct = [k for k in keys if not k.endswith("<other>")]
        assert len(distinct) <= 1            # bound held; rest folded

    def test_drain_stage_seconds_is_delta(self):
        prof = HostProfiler(interval=0.001)
        prof.start()
        time.sleep(0.05)
        prof.stop()
        first = prof.drain_stage_seconds()
        assert first and all(v > 0 for v in first.values())
        assert prof.drain_stage_seconds() == {}   # nothing new since
        total = prof.stage_seconds()
        assert sum(total.values()) == pytest.approx(sum(first.values()))

    def test_classify_stage(self):
        assert classify_stage("bind-3", []) == "binder"
        assert classify_stage("sched-loop", []) == "submitter"
        assert classify_stage("informer-pods", []) == "informer"
        assert classify_stage("MainThread", []) == "main"
        assert classify_stage("mystery", []) == "other"
        # binder frame carve-out wins over the thread-name mapping
        assert classify_stage("sched-loop",
                              ["poll", "_bulk_bind_commit"]) == "binder"
        # decoupled-binder frames (turbo/bulk cycles, permit waits, row
        # materialisation) attribute to binder, not the calling thread
        for frame in ("_binding_cycle_turbo", "_binding_cycle_bulk",
                      "wait_on_permit", "binding_rows"):
            assert classify_stage("sched-loop", [frame]) == "binder", frame
        # event-driven row maintenance attributes to snapshot.patch
        for frame in ("_release_row", "_probe_bucket",
                      "_ns_mask_row_update"):
            assert classify_stage("sched-loop",
                                  [frame]) == "snapshot.patch", frame
        # scatter-gather registration is flatten work on its own...
        assert classify_stage("sched-loop",
                              ["register_sg"]) == "snapshot.flatten"
        # ...but under patch_node the patch-first check order wins
        assert classify_stage("sched-loop",
                              ["register_sg", "patch_node"]) \
            == "snapshot.patch"


# -- SLO tracker -------------------------------------------------------------

class TestSLOTracker:
    def make(self, **kw):
        self.now = [0.0]
        kw.setdefault("target_ms", 10.0)
        kw.setdefault("objective", 0.99)
        kw.setdefault("windows", (60.0, 300.0, 3600.0))
        return SLOTracker(time_fn=lambda: self.now[0], **kw)

    def test_quantiles(self):
        slo = self.make()
        slo.observe([i / 1000.0 for i in range(1, 101)])   # 1..100 ms
        q = slo.quantiles()
        assert q["count"] == 100
        assert q["p50_ms"] == pytest.approx(51.0)
        assert q["p95_ms"] == pytest.approx(96.0)
        assert q["p99_ms"] == pytest.approx(100.0)
        assert slo.target_s == pytest.approx(0.010)

    def test_burn_rate_boundary_not_breached(self):
        """Burn of exactly 1.0 consumes budget at the sustainable rate:
        NOT an arm signal (breached requires strictly > 1.0)."""
        slo = self.make()
        slo.observe([0.001] * 99 + [0.02])     # 1/100 over, budget 0.01
        rates = slo.burn_rates()
        assert rates["60s"] == pytest.approx(1.0)
        assert not slo.breached()
        slo.observe([0.02, 0.02])              # 3/102 over -> ~2.9x
        assert slo.burn_rates()["60s"] > 1.0
        assert slo.breached()

    def test_multi_window_confirmation(self):
        """Old breaches age out of the short window: the fast window
        must CONFIRM the burn or the tracker disarms."""
        slo = self.make()
        slo.observe([0.05] * 10, now=0.0)      # all over target
        assert slo.breached(now=1.0)
        # 70s later: outside the 60s window, still inside 300s
        rates = slo.burn_rates(now=70.0)
        assert rates["60s"] == 0.0 and rates["300s"] > 1.0
        assert not slo.breached(now=70.0)

    def test_objective_validated(self):
        with pytest.raises(ValueError):
            SLOTracker(objective=1.0)

    def test_empty_windows_at_startup(self):
        """A fresh tracker must read as healthy, not breached: every
        window burns 0.0 with no samples (the engagement controller
        polls breached() from wave 0, so startup must not arm)."""
        slo = self.make()
        rates = slo.burn_rates()
        assert rates == {"60s": 0.0, "300s": 0.0, "3600s": 0.0}
        assert not slo.breached()
        assert slo.quantiles()["count"] == 0

    def test_sparse_shortest_window(self):
        """One sample in the shortest window is enough to swing its burn
        between 0 and 100x budget — the multi-window AND is what keeps a
        sparse spike from arming on its own."""
        slo = self.make()
        slo.observe([0.001], now=0.0)          # single good sample
        assert slo.burn_rates(now=1.0)["60s"] == 0.0
        assert not slo.breached(now=1.0)
        slo2 = self.make()
        slo2.observe([0.05], now=0.0)          # single bad sample
        # 1/1 over budget 0.01: both short windows see the same lone
        # sample, so a single breach DOES arm — sparse windows are
        # high-variance by design; arm_samples hysteresis absorbs it
        assert slo2.burn_rates(now=1.0)["60s"] == pytest.approx(100.0)
        assert slo2.breached(now=1.0)

    def test_breach_exactly_at_two_window_boundary(self):
        """breached() is the AND of the two shortest windows, each
        strictly > 1.0: the confirming window burning at EXACTLY the
        sustainable rate must not arm."""
        slo = self.make()
        # 99 good samples age out of the 60s window but stay in 300s
        slo.observe([0.001] * 99, now=0.0)
        # one bad sample inside both windows at now=100
        slo.observe([0.05], now=95.0)
        rates = slo.burn_rates(now=100.0)
        assert rates["60s"] == pytest.approx(100.0)    # 1/1 over
        assert rates["300s"] == pytest.approx(1.0)     # 1/100 over: AT budget
        assert not slo.breached(now=100.0)             # strict >
        slo.observe([0.05], now=96.0)                  # 2/101: over budget
        assert slo.burn_rates(now=100.0)["300s"] > 1.0
        assert slo.breached(now=100.0)


# -- cross-process metrics federation ----------------------------------------

class TestFederation:
    def test_parse_prometheus_text(self):
        text = ("# HELP a_total [ALPHA] help\n"
                "# TYPE a_total counter\n"
                'a_total{x="1"} 3\n'
                'a_total{x="2"} 4.5\n'
                "b_gauge 7\n"
                'h_bucket{le="0.01"} 2\n'
                "garbage line ===\n")
        out = parse_prometheus_text(text)
        assert out["a_total"] == {("1",): 3.0, ("2",): 4.5}
        assert out["b_gauge"] == {(): 7.0}
        assert out["h_bucket"] == {("0.01",): 2.0}

    def test_federate_sums_floats_and_tuples(self):
        a = {"c_total": {("x",): 2.0}, "h": {(): (3, 0.5)}}
        b = {"c_total": {("x",): 3.0, ("y",): 1.0}, "h": {(): (1, 0.25)}}
        out = federate([a, b])
        assert out["c_total"] == {("x",): 5.0, ("y",): 1.0}
        assert out["h"] == {(): (4, 0.75)}

    def test_federation_under_seeded_instance_churn(self):
        """Fleet totals survive kills and revives: an instance killed
        mid-run contributes its last /metrics snapshot; a revived slot
        restarts from a fresh registry.  Ground truth is tracked in
        plain dicts alongside, and the federated view must equal it."""

        def fresh_instance():
            reg = cbm.Registry()
            c = cbm.Counter("fleet_binds_total", "Binds per instance.",
                            labels=("result",))
            g = cbm.Gauge("fleet_capacity", "Slots per instance.")
            reg.must_register(c, g)
            return reg, c, g

        n = 4
        instances = [fresh_instance() for _ in range(n)]
        sched = faults.ScaleOutSchedule(seed=7, instance_count=n,
                                        kill_rate=0.25, revive_rate=0.25)
        truth_binds = 0.0
        dead_snapshots = []
        kills = revives = 0
        for wave in range(60):
            for slot in instances:
                if slot is None:
                    continue
                _, c, g = slot
                inc = float(wave % 5 + 1)
                c.inc(inc, "bound")
                g.set(2.0)
                truth_binds += inc
            act, victim = sched.action(wave)
            if act == faults.KILL_INSTANCE and instances[victim] is not None:
                dead_snapshots.append(instances[victim][0].expose())
                instances[victim] = None
                kills += 1
            elif act == faults.REVIVE_INSTANCE and instances[victim] is None:
                instances[victim] = fresh_instance()
                revives += 1
        assert kills > 0 and revives > 0     # seed actually churned
        live = [slot for slot in instances if slot is not None]
        fleet = federate_texts(
            dead_snapshots + [reg.expose() for reg, _, _ in live])
        assert fleet["fleet_binds_total"][("bound",)] == \
            pytest.approx(truth_binds)
        # gauges sum across LIVE instances only (dead snapshots carry
        # the victim's last value; here each live instance reports 2)
        assert fleet["fleet_capacity"][()] >= 2.0 * len(live)


# -- SLO-boundary latency bucket ---------------------------------------------

class TestLatencyBuckets:
    def test_explicit_10ms_boundary(self):
        from kubernetes_tpu.scheduler.metrics import _LATENCY_BUCKETS

        assert 0.010 in _LATENCY_BUCKETS
        # strictly increasing: no duplicate boundaries after the insert
        assert all(a < b for a, b in
                   zip(_LATENCY_BUCKETS, _LATENCY_BUCKETS[1:]))

    def test_cumulative_counts_monotone(self):
        from kubernetes_tpu.scheduler.metrics import _LATENCY_BUCKETS

        h = cbm.Histogram("t_seconds", "h", buckets=_LATENCY_BUCKETS)
        for v in (0.008, 0.0095, 0.010, 0.0101, 0.016, 0.2):
            h.observe(v)
        series = {}
        for line in h.collect():
            if "_bucket" not in line:
                continue
            le = line.split('le="', 1)[1].split('"', 1)[0]
            series[le] = int(line.rsplit(" ", 1)[1])
        counts = list(series.values())   # exposition order: ascending le
        assert counts == sorted(counts)
        assert series["+Inf"] == 6
        # a 10ms observation counts as within the <=10ms SLO boundary
        assert series["0.01"] - series["0.008"] == 2   # 0.0095 and 0.010


# -- /debug/profile endpoints ------------------------------------------------

def _assert_collapsed(body: str):
    lines = [ln for ln in body.splitlines() if ln]
    assert lines
    for ln in lines:
        stack, count = ln.rsplit(" ", 1)
        assert int(count) > 0 and stack


class TestDebugProfileEndpoints:
    def test_apiserver_serves_collapsed_stacks(self):
        from kubernetes_tpu.apiserver import APIServer
        from kubernetes_tpu.store import kv

        prof = profiling.default_host_profiler
        prof.reset()
        prof.start()
        server = APIServer(kv.MemoryStore()).start()
        try:
            time.sleep(0.05)
            with urllib.request.urlopen(server.url + "/debug/profile",
                                        timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                _assert_collapsed(resp.read().decode())
        finally:
            server.stop()
            prof.stop()
            prof.reset()

    def test_device_worker_serves_collapsed_stacks(self):
        from kubernetes_tpu.ops.remote import DeviceWorker

        prof = profiling.default_host_profiler
        prof.reset()
        prof.start()
        worker = DeviceWorker().start()
        try:
            time.sleep(0.05)
            with urllib.request.urlopen(worker.url + "/debug/profile",
                                        timeout=10) as resp:
                assert resp.status == 200
                _assert_collapsed(resp.read().decode())
        finally:
            worker.stop()
            prof.stop()
            prof.reset()


# -- profiling: config stanza ------------------------------------------------

class TestProfilingConfig:
    def test_defaults_off(self):
        p = ProfilingPolicy()
        assert not p.enabled and not p.census

    def test_parse_stanza(self):
        p = _parse_profiling({"enabled": True, "census": True,
                              "sampleIntervalMs": 2,
                              "sloTargetMs": 5,
                              "burnWindowsSeconds": [30, 120]})
        assert p.enabled and p.census
        assert p.sample_interval_ms == 2
        assert p.slo_target_ms == 5
        assert p.burn_windows_s == (30.0, 120.0)

    def test_unknown_key_rejected(self):
        with pytest.raises(ConfigError):
            _parse_profiling({"enabld": True})


# -- end-to-end: profiled null-device workload + overhead bound --------------

def _small_cfg(pods=600):
    # 40 nodes fits CAPS.n_cap=64: pods stay on the batch path (the
    # per-pod oracle fallback has no SLO tap to exercise)
    return {"workloadTemplate": [
        {"opcode": "createNodes", "count": 40},
        {"opcode": "createPods", "count": pods},
        {"opcode": "barrier", "timeout": 120.0},
    ]}


class TestProfiledWorkload:
    def test_observatory_readout_and_overhead(self):
        """Profiler-on run populates host stages / samples / SLO stats,
        leaves no sampler thread behind, and stays within a pinned 2x
        throughput bound of the profiler-off run on the null-device
        host bench."""
        summary_off, stats_off = run_named_workload(
            _small_cfg(), tpu=True, caps=CAPS, batch_size=128,
            null_device=True)
        assert stats_off["barrier_ok"]
        assert "host_stages" not in stats_off      # off: no readout keys

        policy = ProfilingPolicy(enabled=True, sample_interval_ms=2.0,
                                 slo_target_ms=10.0)
        summary_on, stats_on = run_named_workload(
            _small_cfg(), tpu=True, caps=CAPS, batch_size=128,
            null_device=True, profiling_policy=policy)
        assert stats_on["barrier_ok"]
        assert not _sampler_threads()              # harness stopped it
        assert stats_on["profile_samples"] > 0
        stages = stats_on["host_stages"]
        assert stages and sum(stages.values()) > 0
        slo = stats_on["slo"]
        assert slo["count"] == 600                 # every bound pod fed
        assert set(slo["burn_rates"]) == {"60s", "300s", "3600s"}
        # pinned overhead bound: sampling must not halve throughput
        assert summary_on.average >= summary_off.average / 2.0

    def test_slo_gauges_in_exposition(self):
        """The scheduler's /metrics page carries the SLO quantile and
        burn-rate series after a profiled run."""
        from kubernetes_tpu.perf.scheduler_perf import setup_cluster

        policy = ProfilingPolicy(enabled=True, slo_target_ms=10.0)
        cluster = setup_cluster(tpu=True, caps=CAPS, batch_size=128,
                                null_device=True, profiling_policy=policy)
        try:
            cluster.scheduler._slo.observe([0.002, 0.004, 0.02])
            time.sleep(0.05)               # let the sampler take a few
            text = cluster.scheduler.expose_metrics()
            parsed = parse_prometheus_text(text)
            assert ("p99",) in parsed["scheduler_slo_latency_ms"]
            assert ("60s",) in parsed["scheduler_slo_burn_rate"]
            assert "scheduler_host_stage_seconds" in text
        finally:
            cluster.shutdown()
            profiling.default_host_profiler.stop()
            profiling.default_host_profiler.reset()

    def test_e2e_summary_includes_p95(self):
        from kubernetes_tpu.scheduler.scheduler import SchedulerMetrics

        m = SchedulerMetrics()
        m.observe_e2e([(i / 1000.0, 1) for i in range(1, 41)])
        s = m.e2e_summary()
        assert s["p50_ms"] <= s["p95_ms"] <= s["p99_ms"] <= s["max_ms"]
        assert "p95_ms" in s
